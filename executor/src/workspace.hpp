// Workspace path mapping and changed-file detection.
//
// Same semantics as the Python reference implementation
// (bee_code_interpreter_tpu/runtime/executor_core.py): logical client paths
// ("/workspace/...") map into a real root with traversal protection, and
// changed files are found by a *recursive* before/after snapshot diff on
// (mtime_ns, size) -- deliberately stronger than the reference executor's
// top-level-only ctime scan (reference server.rs:98-118).
#pragma once

#include <sys/stat.h>

#include <filesystem>
#include <map>
#include <optional>
#include <string>
#include <utility>
#include <vector>

namespace workspace {

namespace fs = std::filesystem;

struct FileSig {
  int64_t mtime_ns;
  int64_t size;
  bool operator==(const FileSig&) const = default;
};

using Snapshot = std::map<std::string, FileSig>;

// Maps a logical path ("/workspace/a/b", "workspace/a/b", or "a/b") to a real
// path under root. Returns nullopt if the path escapes the workspace.
inline std::optional<fs::path> resolve(const fs::path& root,
                                       std::string logical,
                                       const std::string& prefix = "/workspace") {
  std::string stripped = prefix.substr(1) + "/";  // "workspace/"
  if (logical.rfind(prefix + "/", 0) == 0) {
    logical = logical.substr(prefix.size() + 1);
  } else if (logical.rfind(stripped, 0) == 0) {
    logical = logical.substr(stripped.size());
  }
  while (!logical.empty() && logical.front() == '/') logical.erase(0, 1);
  fs::path joined = root / logical;
  // lexically normalize and verify containment (no symlink resolution needed
  // for containment: reject any ".." that climbs out)
  fs::path normal = joined.lexically_normal();
  fs::path normal_root = root.lexically_normal();
  auto root_it = normal_root.begin();
  for (auto it = normal.begin(); root_it != normal_root.end(); ++it, ++root_it) {
    if (it == normal.end() || *it != *root_it) return std::nullopt;
  }
  return normal;
}

inline Snapshot snapshot(const fs::path& root) {
  Snapshot snap;
  std::error_code ec;
  fs::recursive_directory_iterator it(root, fs::directory_options::skip_permission_denied, ec);
  if (ec) return snap;
  for (const auto& entry : it) {
    std::error_code sec;
    if (!entry.is_regular_file(sec) || sec) continue;
    struct stat st{};
    if (::stat(entry.path().c_str(), &st) != 0) continue;
    std::string rel = fs::relative(entry.path(), root, sec).generic_string();
    if (sec) continue;
    snap[rel] = FileSig{st.st_mtim.tv_sec * 1000000000LL + st.st_mtim.tv_nsec,
                        static_cast<int64_t>(st.st_size)};
  }
  return snap;
}

inline std::vector<std::string> changed_files(const Snapshot& before,
                                              const Snapshot& after) {
  std::vector<std::string> out;
  for (const auto& [rel, sig] : after) {
    auto it = before.find(rel);
    if (it == before.end() || !(it->second == sig)) out.push_back(rel);
  }
  return out;  // std::map iteration => already sorted
}

}  // namespace workspace
