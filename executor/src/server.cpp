// bee-code-interpreter-tpu in-sandbox executor server (native).
//
// C++ replacement for the reference's Rust executor (executor/server.rs:29-201)
// with the same wire contract:
//
//   PUT  /workspace/{path}   stream body into the workspace
//   GET  /workspace/{path}   stream file back
//   POST /execute            {source_code, env?, timeout?} ->
//                            {stdout, stderr, exit_code, files[]}
//   GET  /healthz            readiness probe (new)
//
// TPU-first differences from the reference:
//  * plain `python` instead of xonsh (saves the ~80 ms/exec the reference left
//    on the table, server.rs:152)
//  * in-process dependency guessing (dep_guess.hpp) instead of an `upm guess`
//    subprocess + sqlite map
//  * recursive (mtime,size) changed-file diff instead of top-level ctime scan
//  * process-group SIGKILL on timeout (grandchildren can't leak and hold the
//    pod's TPU)
//  * optional XLA warmup at startup (APP_WARMUP=1): imports jax and touches
//    the device before the pod reports ready, so the first request never pays
//    libtpu init (SURVEY.md §7 hard part (c))
//
// Env: APP_LISTEN_ADDR (0.0.0.0:8000), APP_WORKSPACE (/workspace),
// APP_REQUIREMENTS, APP_REQUIREMENTS_SKIP, APP_PYPI_MAP, APP_SHIM_DIR,
// APP_DISABLE_DEP_INSTALL, APP_EXECUTION_TIMEOUT_S, APP_PYTHON, APP_WARMUP.

#include <fstream>
#include <iostream>
#include <mutex>
#include <sstream>

#include <signal.h>
#include <sys/prctl.h>
#include <unistd.h>

#include <chrono>
#include <thread>

extern char** environ;

#include "dep_guess.hpp"
#include "http.hpp"
#include "json.hpp"
#include "subprocess.hpp"
#include "workspace.hpp"

namespace fs = std::filesystem;

namespace {

std::string env_or(const char* name, const std::string& dflt) {
  const char* v = getenv(name);
  return v && *v ? v : dflt;
}

std::string read_file(const fs::path& path) {
  std::ifstream in(path, std::ios::binary);
  std::ostringstream ss;
  ss << in.rdbuf();
  return ss.str();
}

// Env prefixes forwarded from the pod into every user process so JAX/libtpu
// sees the slice topology (mirrors executor_core.TPU_PASSTHROUGH_PREFIXES):
// the accelerator stack's vars are open-ended, and missing one silently
// strands the sandbox on host CPU.
constexpr const char* kTpuPassthroughPrefixes[] = {
    "TPU_", "JAX_", "XLA_", "PALLAS_", "AXON_", "LIBTPU_", "MEGASCALE_",
};

// Kubernetes service links (enableServiceLinks) auto-inject FOO_SERVICE_HOST /
// FOO_PORT_80_TCP-style vars for every Service in the namespace; a Service
// named tpu-* would land inside the prefixes above and leak cluster addresses
// into untrusted user code (mirrors executor_core._is_passthrough_env).
// Port-shaped keys (FOO_PORT, FOO_PORT_80_TCP) are dropped only when the
// definitive service-link signature — a sibling FOO_SERVICE_HOST — exists:
// real accelerator topology vars share the suffix shape (TPU_PROCESS_PORT,
// MEGASCALE_PORT) and must pass through (libtpu never sets *_SERVICE_HOST).
inline bool is_passthrough_env(const std::string& key) {
  bool prefixed = false;
  for (const char* prefix : kTpuPassthroughPrefixes)
    if (key.rfind(prefix, 0) == 0) { prefixed = true; break; }
  if (!prefixed) return false;
  if (key.find("_SERVICE_") != std::string::npos) return false;
  std::string base;
  if (key.size() >= 5 && key.compare(key.size() - 5, 5, "_PORT") == 0) {
    base = key.substr(0, key.size() - 5);
  } else {
    const auto idx = key.find("_PORT_");
    if (idx == std::string::npos) return true;
    base = key.substr(0, idx);
  }
  return getenv((base + "_SERVICE_HOST").c_str()) == nullptr;
}

// Bootstrap for the pre-started interpreter: a warm python (configured
// imports already loaded) blocked on stdin waiting for its single execution
// request. Because sandboxes are single-use, one pre-started worker removes
// interpreter startup + import cost from the request path entirely. The
// request line carries {script, cwd, env}; request env overlays the worker's
// startup env (same result as base_env(request_env) on the cold path). The
// traceback surgery drops the bootstrap's own frame so errors render exactly
// as `python script.py` would. A ppid watchdog mirrors the server's own
// (PDEATHSIG is unreliable on some sandboxed kernels): the worker never
// outlives the server.
constexpr const char* kPrestartBootstrap = R"PY(
import json, os, sys, threading

_server_pid = os.getppid()
def _watch():
    import time
    while os.getppid() == _server_pid:
        time.sleep(2)
    os._exit(1)
threading.Thread(target=_watch, daemon=True).start()

# Preload output (import-time warnings, library banners) must not leak into
# the request's captured stdout/stderr: mute fds 1/2 until the request.
_saved_out, _saved_err = os.dup(1), os.dup(2)
_devnull = os.open(os.devnull, os.O_WRONLY)
os.dup2(_devnull, 1)
os.dup2(_devnull, 2)

# A hung preload (e.g. accelerator init against an unreachable TPU) must not
# convert every request into an execution timeout: past the deadline the
# worker exits (never having written the started byte on fd 3) and the
# server runs the request cold.
_preload_done = threading.Event()
try:
    _preload_deadline = float(
        os.environ.pop("APP_PRESTART_PRELOAD_TIMEOUT_S", "") or "45"
    )
except ValueError:
    _preload_deadline = 45.0
def _preload_guard():
    if not _preload_done.wait(_preload_deadline):
        os._exit(113)
threading.Thread(target=_preload_guard, daemon=True).start()

for _m in os.environ.pop("APP_PRESTART_IMPORTS", "numpy").split(","):
    _m = _m.strip()
    if _m:
        try:
            __import__(_m)
        except Exception:
            pass
_preload_done.set()
# Preload-done byte ('P') on the status pipe: lets the server tell a ready
# worker from one still importing — a request that doesn't need the preloaded
# modules runs cold immediately instead of blocking on the import.
try:
    os.write(3, b"P")
except OSError:
    pass

_req = json.loads(sys.stdin.readline())
# Started byte on the status pipe: the server now knows user code WILL run,
# so it must never cold-retry this request (side effects would double). The
# pipe stays open — the exit-code report ("X<code>") follows when user code
# finishes.
try:
    os.write(3, b"S")
except OSError:
    pass
os.dup2(_saved_out, 1)
os.dup2(_saved_err, 2)
os.close(_saved_out); os.close(_saved_err); os.close(_devnull)

os.environ.update(_req.get("env", {}))
# (Hermetic requests — BCI_SCRUB_ACCELERATOR=1 — never reach this worker:
# the server routes them cold, since this interpreter already executed the
# host sitecustomize chain at spawn.)
# The preload imported numpy before the request env existed, so the reroute
# proxies were installed regardless of the request's BCI_XLA_REROUTE. The
# proxies re-check the env per call, but a request that opted out deserves a
# fully de-proxied numpy (identical to a cold APP_PRESTART=0 interpreter).
if os.environ.get("BCI_XLA_REROUTE") == "0" and "numpy" in sys.modules:
    try:
        from bee_code_interpreter_tpu.runtime import xla_reroute
        xla_reroute.uninstall(sys.modules["numpy"])
    except Exception:
        pass
os.chdir(_req["cwd"])
# Cold-path sys.path parity: `python script.py` puts the script's directory
# at [0] (under `python -c` that slot is the cwd — replace it), followed by
# PYTHONPATH entries in their merged (shim-first) order — repositioning
# entries the worker's startup already added, so a request-supplied path
# resolves identically warm and cold ([script_dir, shim, request paths...]).
sys.path[0:1] = [os.path.dirname(_req["script"])]
_idx = 1
for _p in _req.get("env", {}).get("PYTHONPATH", "").split(os.pathsep):
    if not _p:
        continue
    if _p in sys.path[1:]:
        sys.path.remove(_p)
    sys.path.insert(_idx, _p)
    _idx += 1
sys.argv = [_req["script"]]
with open(_req["script"], "rb") as _f:
    _code = _f.read()
_g = {
    "__name__": "__main__",
    "__file__": _req["script"],
    "__builtins__": __builtins__,
    "__doc__": None,
    "__package__": None,
    "__spec__": None,
}
# Exit-code report, registered BEFORE user code so it runs LAST among atexit
# handlers (atexit is LIFO): flush + report the script's exit code on the
# status pipe and close stdio, so the server can respond while interpreter
# finalization (slow with a scientific stack loaded) continues behind it.
#
# The report runs before finalization's own io flush, so a file handle user
# code left open (module-global `f = open(...); f.write(...)`) would still
# hold buffered bytes when the server snapshots the workspace. builtins.open
# is wrapped to track live file objects (weakly); the reporter flushes the
# writable ones first.
import atexit, builtins, weakref
_open_files = weakref.WeakSet()
_orig_open = builtins.open
def _tracking_open(*_a, **_kw):
    _f = _orig_open(*_a, **_kw)
    try:
        _open_files.add(_f)
    except TypeError:
        pass
    return _f
builtins.open = _tracking_open
_exit_state = {"code": 0}
def _report_exit():
    for _f in list(_open_files):
        try:
            if not _f.closed and _f.writable():
                _f.flush()
        except Exception:
            pass
    try:
        sys.stdout.flush(); sys.stderr.flush()
    except Exception:
        pass
    try:
        os.write(3, ("X%d\n" % _exit_state["code"]).encode())
        os.close(3)
    except OSError:
        pass
    for _fd in (1, 2):
        try:
            os.close(_fd)
        except OSError:
            pass
atexit.register(_report_exit)
try:
    exec(compile(_code, _req["script"], "exec"), _g)
except SystemExit as _se:
    _c = _se.code
    _exit_state["code"] = _c if isinstance(_c, int) else (0 if _c is None else 1)
    raise
except BaseException:
    import traceback
    _tp, _e, _tb = sys.exc_info()
    traceback.print_exception(_tp, _e, _tb.tb_next)  # drop bootstrap frame
    _exit_state["code"] = 1
    sys.exit(1)
)PY";

struct ExecutorConfig {
  std::string python = env_or("APP_PYTHON", "python3");
  fs::path workspace_root = env_or("APP_WORKSPACE", "/workspace");
  bool disable_dep_install = env_or("APP_DISABLE_DEP_INSTALL", "") == "1";
  double default_timeout_s = std::stod(env_or("APP_EXECUTION_TIMEOUT_S", "60"));
  std::string shim_dir = env_or("APP_SHIM_DIR", "");
  // Pre-started warm interpreter (APP_PRESTART=0 disables; imports list via
  // APP_PRESTART_IMPORTS, default "numpy").
  bool prestart = env_or("APP_PRESTART", "1") == "1";
};

class Executor {
 public:
  explicit Executor(ExecutorConfig config) : config_(std::move(config)) {
    fs::create_directories(config_.workspace_root);
    guesser_.pypi_map = dep_guess::load_pypi_map(
        read_file(env_or("APP_PYPI_MAP", "/pypi_map.tsv")));
    dep_guess::load_requirements_into(
        read_file(env_or("APP_REQUIREMENTS", "/requirements.txt")),
        guesser_.preinstalled);
    dep_guess::load_requirements_into(
        read_file(env_or("APP_REQUIREMENTS_SKIP", "/requirements-skip.txt")),
        guesser_.preinstalled);
    if (config_.prestart) {
      spawn_prestart();
      const char* pt = getenv("APP_PRESTART_PRELOAD_TIMEOUT_S");
      if (pt) {
        char* end = nullptr;
        double v = strtod(pt, &end);
        if (end != pt && v > 0) preload_deadline_s_ = v;
      }
    }
  }

  // Spawn (or re-spawn) the pre-started warm interpreter. Called from the
  // constructor and, under prestart_mutex_, right after a request claims
  // the current worker: a session lease runs N executes against this ONE
  // server, and execute #2..N should find a preloaded interpreter the way
  // execute #1 did. Single-use sandboxes die moments after their one
  // execute; the unclaimed replacement dies with them (ppid watchdog).
  void spawn_prestart() {
    auto env = base_env({});
    // base_env deliberately excludes APP_* control vars; the preload list
    // is the one the bootstrap needs.
    const std::string preload = env_or("APP_PRESTART_IMPORTS", "");
    if (!preload.empty()) env["APP_PRESTART_IMPORTS"] = preload;
    const std::string preload_timeout = env_or("APP_PRESTART_PRELOAD_TIMEOUT_S", "");
    if (!preload_timeout.empty())
      env["APP_PRESTART_PRELOAD_TIMEOUT_S"] = preload_timeout;
    prestart_ = subprocess::spawn({config_.python, "-c", kPrestartBootstrap},
                                  env, config_.workspace_root.string(),
                                  /*want_stdin=*/true, /*want_status=*/true);
    prestart_spawned_at_ = std::chrono::steady_clock::now();
    prestart_warm_seen_ = false;
  }

  minihttp::Response handle(const minihttp::Request& req) {
    if (req.path == "/healthz") {
      // "warm": the pre-started worker finished its preload ('P' on the
      // status pipe) — the pool queues sandboxes only once warm (best
      // effort), keeping the preload wait off the request path. True when
      // prestart is disabled or the worker was already claimed.
      bool warm = true;
      {
        std::lock_guard<std::mutex> lock(prestart_mutex_);
        if (prestart_.valid() && !prestart_warm_seen_) {
          pollfd p{prestart_.status_fd, POLLIN, 0};
          if (poll(&p, 1, 0) > 0 && (p.revents & (POLLIN | POLLHUP))) {
            char b = 0;
            ssize_t n = read(prestart_.status_fd, &b, 1);
            if (n == 1 && b == 'P') {
              prestart_warm_seen_ = true;
            } else if (n == 0) {
              // EOF before 'P': the worker died preloading (e.g. its hung-
              // preload guard fired). Cold fallback is as warm as this
              // sandbox gets — report warm so the pool stops holding it.
              prestart_warm_seen_ = true;
            }
          }
          warm = prestart_warm_seen_;
        }
      }
      return {200, "application/json",
              std::string("{\"status\":\"ok\",\"warm\":") +
                  (warm ? "true" : "false") + "}",
              {}};
    }
    if (req.path.rfind("/workspace/", 0) == 0) {
      auto real = workspace::resolve(config_.workspace_root, req.path);
      if (!real) return {400, "application/json", "{\"detail\":\"path escapes workspace\"}", {}};
      if (req.method == "PUT") return upload(*real, req);
      if (req.method == "GET") return download(*real);
      return {405, "application/json", "{}", {}};
    }
    if (req.path == "/execute" && req.method == "POST") return execute(req.body);
    return {404, "application/json", "{}", {}};
  }

  // --guess CLI mode only: run the guesser exactly as a request would
  // (including lazy stdlib loading), without the install step.
  std::vector<std::string> guess_for_debug(const std::string& source) {
    std::call_once(stdlib_loaded_, [this] { load_stdlib(); });
    return guesser_.guess(source);
  }

  void warmup() {
    // Pre-heat libtpu/XLA before the pod reports ready. Runs a dedicated
    // cold interpreter — it must NOT consume the pre-started worker, whose
    // point is to stay warm for the actual request.
    subprocess::run(
        {config_.python, "-c",
         "try:\n"
         "    import jax\n"
         "    jax.numpy.zeros(8).block_until_ready()\n"
         "except Exception:\n"
         "    pass\n"},
        base_env({}), config_.workspace_root.string(), 300.0);
  }

  // Body-sink selector (runs in minihttp before the body is read): PUT
  // /workspace/... bodies stream straight to a part-file next to their
  // destination — a workspace restore costs disk, not resident memory
  // (parity with the reference's chunk-by-chunk upload, server.rs:83-86).
  // The same-directory part-file makes the final publish an atomic rename.
  std::optional<std::string> upload_sink(const minihttp::Request& req) {
    if (req.method != "PUT" || req.path.rfind("/workspace/", 0) != 0)
      return std::nullopt;
    auto real = workspace::resolve(config_.workspace_root, req.path);
    if (!real) return std::nullopt;  // handler will 400; body stays bounded
    std::error_code ec;
    fs::create_directories(real->parent_path(), ec);
    if (ec) return std::nullopt;
    static std::atomic<uint64_t> seq{0};
    return real->string() + ".__bci_part." + std::to_string(getpid()) + "." +
           std::to_string(seq.fetch_add(1));
  }

 private:
  minihttp::Response upload(const fs::path& real, const minihttp::Request& req) {
    std::error_code ec;
    if (!req.body_file.empty()) {
      fs::rename(req.body_file, real, ec);
      if (ec) {
        fs::remove(req.body_file, ec);
        return {500, "application/json", "{\"detail\":\"rename failed\"}", {}};
      }
      return {204, "application/json", "", {}};
    }
    fs::create_directories(real.parent_path(), ec);
    std::ofstream out(real, std::ios::binary | std::ios::trunc);
    if (!out) return {500, "application/json", "{\"detail\":\"open failed\"}", {}};
    out.write(req.body.data(), static_cast<std::streamsize>(req.body.size()));
    return {204, "application/json", "", {}};
  }

  minihttp::Response download(const fs::path& real) {
    if (!fs::is_regular_file(real)) return {404, "application/json", "{}", {}};
    minihttp::Response resp;
    resp.content_type = "application/octet-stream";
    resp.file_path = real.string();
    return resp;
  }

  minihttp::Response execute(const std::string& body) {
    minijson::Value req;
    try {
      req = minijson::parse(body);
    } catch (const std::exception& e) {
      return {400, "application/json",
              minijson::dump(minijson::Object{{"detail", e.what()}}), {}};
    }
    std::string source = req["source_code"].as_string();
    double timeout = req["timeout"].is_null() ? config_.default_timeout_s
                                              : req["timeout"].as_number();
    std::map<std::string, std::string> request_env;
    for (const auto& [k, v] : req["env"].as_object()) request_env[k] = v.as_string();

    auto before = workspace::snapshot(config_.workspace_root);
    std::string pip_notes = ensure_dependencies(source);
    auto t0 = std::chrono::steady_clock::now();
    auto result = run_python(source, request_env, timeout);
    double run_ms = std::chrono::duration<double, std::milli>(
                        std::chrono::steady_clock::now() - t0)
                        .count();
    auto after = workspace::snapshot(config_.workspace_root);

    minijson::Array files;
    for (const auto& rel : workspace::changed_files(before, after))
      files.push_back(minijson::Value("/workspace/" + rel));

    std::string stderr_out = result.err;
    if (!pip_notes.empty())
      stderr_out = pip_notes + (stderr_out.empty() ? "" : "\n" + stderr_out);

    minijson::Object resp{
        {"stdout", result.out},
        {"stderr", stderr_out},
        {"exit_code", result.exit_code},
        {"files", std::move(files)},
        // Additive diagnostic: in-sandbox wall time of the user subprocess.
        // Client-side (POST latency − duration_ms) isolates control-plane
        // overhead (event-loop contention, refill interference) from the
        // sandbox's own run time without a wire-contract break.
        {"duration_ms", run_ms},
    };
    return {200, "application/json", minijson::dump(minijson::Value(std::move(resp))), {}};
  }

  // Returns pip stderr notes on failure, "" on success/no-op (install
  // failures surface in-band like the reference, server.rs:140-147).
  std::string ensure_dependencies(const std::string& source) {
    if (config_.disable_dep_install) return "";
    // Lazy: asking the interpreter for sys.stdlib_module_names costs a full
    // python startup (~20 ms CPU); paying it in the constructor made every
    // warm-pool refill visibly steal latency from in-flight requests on
    // small hosts. First guess pays it once; disabled dep-install never does.
    std::call_once(stdlib_loaded_, [this] { load_stdlib(); });
    auto deps = guesser_.guess(source);
    {
      std::lock_guard<std::mutex> lock(installed_mutex_);
      deps.erase(std::remove_if(deps.begin(), deps.end(),
                                [&](const std::string& d) {
                                  return installed_this_session_.count(d) > 0;
                                }),
                 deps.end());
    }
    if (deps.empty()) return "";
    std::vector<std::string> argv = {config_.python, "-m", "pip", "install",
                                     "--no-cache-dir"};
    argv.insert(argv.end(), deps.begin(), deps.end());
    auto result = subprocess::run(argv, base_env({}), "", 300.0);
    if (result.exit_code == 0) {
      std::lock_guard<std::mutex> lock(installed_mutex_);
      installed_this_session_.insert(deps.begin(), deps.end());
      return "";
    }
    return result.err;
  }

  subprocess::RunResult run_python(const std::string& source,
                                   const std::map<std::string, std::string>& request_env,
                                   double timeout_s) {
    char tmpl[] = "/tmp/exec-XXXXXX";
    char* tmpdir = mkdtemp(tmpl);
    if (!tmpdir) return {"", "mkdtemp failed", -1, false};
    fs::path script = fs::path(tmpdir) / "script.py";
    {
      std::ofstream out(script, std::ios::binary);
      out << source;
    }

    subprocess::RunResult result;
    // Hermetic requests (BCI_SCRUB_ACCELERATOR=1) must run COLD — the warm
    // worker's interpreter already executed the host sitecustomize chain at
    // spawn, and whatever platform hooks it installed cannot be uninstalled
    // retroactively. The worker is not claimed at all: it stays warm for a
    // later normal request (sandboxes are single-use in production, but the
    // server must not de-warm itself on the first hermetic probe).
    auto hermetic_it = request_env.find("BCI_SCRUB_ACCELERATOR");
    const bool hermetic =
        hermetic_it != request_env.end() && hermetic_it->second == "1";
    subprocess::Child worker;
    if (!hermetic) {
      // Claim the pre-started worker (single-use). From the SECOND claim
      // on, this server is evidently serving a session lease (single-use
      // sandboxes execute once and die), so re-warm for the next REPL
      // turn. The first claim deliberately does NOT respawn: the
      // replacement's preload (numpy import) would compete with the user
      // code for CPU — measured ~4-7 ms added to the stateless warm p50 —
      // for a worker a single-use sandbox never uses. Net: lease turn #1
      // warm, #2 cold (triggers the re-warm), #3+ warm.
      std::lock_guard<std::mutex> lock(prestart_mutex_);
      worker = prestart_;
      prestart_ = {};
      if (config_.prestart && claimed_once_) spawn_prestart();
      claimed_once_ = true;
    }
    bool ran_warm = false;
    double remaining_s = timeout_s;
    if (worker.valid()) {
      // alive() reaps via waitpid(WNOHANG) when the worker already died —
      // after that the pid may be recycled, so never signal it again.
      const bool was_alive = worker.alive();
      bool kill_worker = false;
      // Always prefer the warm worker, even mid-preload: a cold interpreter
      // is not reliably cheap (a host sitecustomize that registers an
      // accelerator plugin costs ~600 ms per python startup — measured), so
      // blocking on the remaining preload is the bounded-loss choice. The
      // pool keeps this path rare by only queueing sandboxes whose preload
      // has finished (the /healthz "warm" field).
      if (was_alive &&
          send_prestart_request(worker, script.string(), request_env)) {
        // Phase 1: wait for the started byte — written right before user
        // code runs, so its presence/absence tells us EXACTLY whether a
        // cold retry is safe (no exit-code heuristics, no double-running
        // side effects). Waiting is bounded by the preload guard's own
        // remaining deadline (plus grace), never past the request budget.
        const double since_spawn =
            std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                          prestart_spawned_at_)
                .count();
        const double guard_remaining =
            std::max(0.0, preload_deadline_s_ - since_spawn) + 2.0;
        const auto t0 = std::chrono::steady_clock::now();
        if (subprocess::wait_for_status_byte(
                worker.status_fd, std::min(timeout_s, guard_remaining), 'S')) {
          // status_fd stays open: the exit-code report ("X<code>") arrives
          // on it when user code finishes. Charge the phase-1 wait against
          // the request budget: collect_warm() must not restart a full
          // budget or the warm path could run for guard+timeout, past what
          // the control-plane client waits for.
          const double waited =
              std::chrono::duration<double>(std::chrono::steady_clock::now() -
                                            t0)
                  .count();
          result =
              subprocess::collect_warm(worker, std::max(0.5, timeout_s - waited));
          ran_warm = true;
        } else {
          // preload never finished: cold-retry with the remaining budget
          remaining_s = std::max(
              0.5, timeout_s - std::chrono::duration<double>(
                                   std::chrono::steady_clock::now() - t0)
                                   .count());
          kill_worker = true;
        }
      } else {
        kill_worker = was_alive;
      }
      bool started_after_deadline = false;
      if (!ran_warm) {
        if (kill_worker) {
          // kill and reap (blocking is safe — SIGKILL delivery to our own
          // unwaited child is certain).
          worker.kill_group();
          int status = 0;
          waitpid(worker.pid, &status, 0);
          // Close the race between deadline expiry and the kill: if the
          // started byte landed in that gap, user code already began in the
          // warm worker (side effects possible) and a cold retry would
          // double-execute it. One final drain of the (now-EOF'd) status
          // pipe tells us for certain.
          started_after_deadline =
              subprocess::wait_for_status_byte(worker.status_fd, 0.05, 'S');
        }
        worker.close_fds();
      }
      if (started_after_deadline) {
        std::error_code ec;
        fs::remove_all(tmpdir, ec);
        // Not a request timeout — only the (much shorter) preload-guard
        // window elapsed. Say what actually happened instead of borrowing
        // the timeout sentinel.
        return {"",
                "Execution aborted: the warm interpreter was killed at its "
                "preload deadline after user code had already started; not "
                "retried to avoid running the code twice",
                -1, false};
      }
    }
    if (!ran_warm) {
      result = subprocess::run({config_.python, script.string()},
                               base_env(request_env),
                               config_.workspace_root.string(), remaining_s);
    }
    std::error_code ec;
    fs::remove_all(tmpdir, ec);
    return result;
  }

  // One JSON line into the warm worker's stdin: {script, cwd, env}. The
  // request env gets the same shim-PYTHONPATH merge the cold path's
  // base_env applies, so grandchildren spawned by user code inherit the
  // shim identically on both paths.
  bool send_prestart_request(
      subprocess::Child& worker, const std::string& script,
      const std::map<std::string, std::string>& request_env) {
    minijson::Object env_obj;
    for (const auto& [k, v] : request_env) env_obj[k] = minijson::Value(v);
    if (!config_.shim_dir.empty() && request_env.count("PYTHONPATH")) {
      env_obj["PYTHONPATH"] =
          minijson::Value(merge_shim_pythonpath(request_env.at("PYTHONPATH")));
    }
    minijson::Object msg{
        {"script", minijson::Value(script)},
        {"cwd", minijson::Value(config_.workspace_root.string())},
        {"env", minijson::Value(std::move(env_obj))},
    };
    std::string line = minijson::dump(minijson::Value(std::move(msg))) + "\n";
    size_t sent = 0;
    while (sent < line.size()) {
      ssize_t n = write(worker.stdin_fd, line.data() + sent, line.size() - sent);
      if (n <= 0) return false;  // worker gone (SIGPIPE ignored in main)
      sent += static_cast<size_t>(n);
    }
    close(worker.stdin_fd);
    worker.stdin_fd = -1;
    return true;
  }

  std::map<std::string, std::string> base_env(
      const std::map<std::string, std::string>& request_env) {
    std::map<std::string, std::string> env{
        {"PATH", env_or("PATH", "/usr/local/bin:/usr/bin:/bin")},
        {"HOME", env_or("HOME", config_.workspace_root.string())},
        {"LANG", "C.UTF-8"},
        {"PYTHONUNBUFFERED", "1"},
    };
    for (char** e = environ; *e; ++e) {
      const std::string entry(*e);
      const auto eq = entry.find('=');
      if (eq == std::string::npos) continue;
      const std::string key = entry.substr(0, eq);
      if (is_passthrough_env(key)) env[key] = entry.substr(eq + 1);
    }
    if (!config_.shim_dir.empty()) {
      std::string existing = env_or("PYTHONPATH", "");
      env["PYTHONPATH"] =
          existing.empty() ? config_.shim_dir : config_.shim_dir + ":" + existing;
    } else if (getenv("PYTHONPATH")) {
      env["PYTHONPATH"] = getenv("PYTHONPATH");
    }
    // Shared persistent XLA compile cache (operator opt-in, e.g. a pod
    // volume): single-use sandboxes then pay each unique program's compile
    // once per deployment instead of once per request.
    const std::string jax_cache = env_or("APP_JAX_CACHE_DIR", "");
    if (!jax_cache.empty() && !env.count("JAX_COMPILATION_CACHE_DIR"))
      env["JAX_COMPILATION_CACHE_DIR"] = jax_cache;
    for (const auto& [k, v] : request_env) env[k] = v;  // request env wins
    // ...except the shim must survive a request-supplied PYTHONPATH: it is
    // part of the sandbox platform (reroute/display patches), not a default
    // the request replaces. (BCI_XLA_REROUTE=0 is the opt-out.)
    if (!config_.shim_dir.empty()) {
      auto it = env.find("PYTHONPATH");
      env["PYTHONPATH"] =
          merge_shim_pythonpath(it == env.end() ? "" : it->second);
    }
    // Hermetic-CPU opt-out: a request env can't REMOVE inherited vars, so
    // BCI_SCRUB_ACCELERATOR=1 drops the tunnel-plugin vars whose mere
    // presence hooks jax backend init even under JAX_PLATFORMS=cpu, and
    // rebuilds PYTHONPATH from the shim + request-supplied entries only —
    // a host sitecustomize chain can force-register the tunnel platform
    // independent of env vars. The prefix list comes from the control plane
    // (APP_SCRUB_PREFIXES, sourced from utils/envscrub.py — the single
    // source of truth); the literal below is only the no-control-plane
    // fallback.
    auto scrub = env.find("BCI_SCRUB_ACCELERATOR");
    if (scrub != env.end() && scrub->second == "1") {
      std::vector<std::string> prefixes;
      {
        std::string spec = env_or("APP_SCRUB_PREFIXES", "PALLAS_,AXON_");
        std::istringstream parts(spec);
        std::string part;
        while (std::getline(parts, part, ','))
          if (!part.empty()) prefixes.push_back(part);
      }
      for (auto it2 = env.begin(); it2 != env.end();) {
        bool drop = false;
        for (const auto& prefix : prefixes)
          if (it2->first.rfind(prefix, 0) == 0) drop = true;
        if (drop) {
          it2 = env.erase(it2);
        } else {
          ++it2;
        }
      }
      std::string hermetic_path = config_.shim_dir;
      auto req_pp = request_env.find("PYTHONPATH");
      if (req_pp != request_env.end() && !req_pp->second.empty()) {
        hermetic_path += hermetic_path.empty() ? req_pp->second
                                               : ":" + req_pp->second;
      }
      if (hermetic_path.empty()) {
        env.erase("PYTHONPATH");
      } else {
        env["PYTHONPATH"] = hermetic_path;
      }
    }
    return env;
  }

  // Prepend the shim dir unless it is already a path *component* (substring
  // matching would be fooled by e.g. /opt/shim vs /opt/shim2).
  std::string merge_shim_pythonpath(const std::string& value) {
    if (value.empty()) return config_.shim_dir;
    size_t start = 0;
    while (start <= value.size()) {
      size_t end = value.find(':', start);
      if (end == std::string::npos) end = value.size();
      if (value.compare(start, end - start, config_.shim_dir) == 0) return value;
      start = end + 1;
    }
    return config_.shim_dir + ":" + value;
  }

  void load_stdlib() {
    // Prefer a pregenerated list (APP_STDLIB_FILE; written once at image
    // build or pool startup) — asking the interpreter costs a full python
    // startup, which single-use sandboxes would otherwise pay per request.
    std::string cached = read_file(env_or("APP_STDLIB_FILE", "/stdlib_names.txt"));
    std::string names = cached;
    if (names.empty()) {
      auto result = subprocess::run(
          {config_.python, "-c",
           "import sys; print('\\n'.join(sorted(sys.stdlib_module_names)))"},
          base_env({}), "", 30.0);
      names = result.out;
    }
    std::istringstream stream(names);
    std::string name;
    while (std::getline(stream, name))
      if (!name.empty()) guesser_.stdlib.insert(name);
    if (guesser_.stdlib.empty())
      std::cerr << "warning: could not load stdlib module names from "
                << config_.python << "\n";
  }

  ExecutorConfig config_;
  dep_guess::Guesser guesser_;
  std::once_flag stdlib_loaded_;
  std::set<std::string> installed_this_session_;
  std::mutex installed_mutex_;
  subprocess::Child prestart_;
  std::mutex prestart_mutex_;
  bool prestart_warm_seen_ = false;
  // True after the first worker claim: the signal that this server is
  // serving a session lease (single-use sandboxes claim exactly once).
  bool claimed_once_ = false;
  std::chrono::steady_clock::time_point prestart_spawned_at_;
  double preload_deadline_s_ = 45.0;
};

}  // namespace

int main(int argc, char** argv) {
  // Debug/parity mode: `executor-server --guess < source.py` prints the
  // guessed PyPI deps one per line (stdlib set from APP_STDLIB_FILE or the
  // interpreter, map from APP_PYPI_MAP). Lets tests pin the native guesser
  // against the Python oracle without booting the HTTP server.
  if (argc > 1 && std::string(argv[1]) == "--guess") {
    ExecutorConfig config;
    Executor executor(config);
    std::stringstream source;
    source << std::cin.rdbuf();
    for (const auto& dep : executor.guess_for_debug(source.str()))
      std::cout << dep << "\n";
    return 0;
  }

  // A dead pre-started worker must surface as a failed write (→ cold-path
  // fallback), not a fatal SIGPIPE.
  signal(SIGPIPE, SIG_IGN);

  // Die with the spawning service (native-process backend). Setting PDEATHSIG
  // here — instead of a Python preexec_fn in the parent — keeps the control
  // plane's Popen on the vfork fast path, so pool refills never block its
  // event loop on a classic fork of the (large) service process.
  //
  // PDEATHSIG alone is not enough: it fires when the spawning *thread* exits
  // (prctl(2)), it can't catch a parent that died before we attached, and on
  // some sandboxed kernels it never fires at all (measured: no delivery even
  // preexec-style on a Firecracker 6.18 microVM). APP_PARENT_PID names the
  // service process explicitly; the watchdog thread below is the guaranteed
  // cleanup path — exit as soon as we are reparented away from the service.
  // (A plain getppid()==1 test would false-positive when the service itself
  // runs as PID 1 in a container.)
  if (env_or("APP_DIE_WITH_PARENT", "") == "1") {
    prctl(PR_SET_PDEATHSIG, SIGKILL);
    const std::string parent = env_or("APP_PARENT_PID", "");
    const long parent_val = parent.empty() ? 0 : strtol(parent.c_str(), nullptr, 10);
    if (parent_val > 0) {
      const pid_t parent_pid = static_cast<pid_t>(parent_val);
      if (getppid() != parent_pid) return 1;  // orphaned before we attached
      std::thread([parent_pid] {
        while (getppid() == parent_pid)
          std::this_thread::sleep_for(std::chrono::seconds(2));
        _exit(1);
      }).detach();
    }
  }

  ExecutorConfig config;
  Executor executor(config);

  if (env_or("APP_WARMUP", "") == "1") executor.warmup();

  std::string listen = env_or("APP_LISTEN_ADDR", "0.0.0.0:8000");
  auto colon = listen.rfind(':');
  std::string host = listen.substr(0, colon);
  int port = std::stoi(listen.substr(colon + 1));

  minihttp::Server server(
      [&executor](const minihttp::Request& req) { return executor.handle(req); },
      [&executor](const minihttp::Request& req) { return executor.upload_sink(req); });
  int bound = server.bind(host, port);
  std::cout << "executor-server listening on " << host << ":" << bound << std::endl;
  server.serve_forever();
  return 0;
}
