// Minimal JSON value type + parser + serializer for the executor wire contract.
// (The reference's Rust executor gets this from serde_json, server.rs deps
// Cargo.toml:14-23; we keep the executor dependency-free instead.)
//
// Supports the full JSON grammar; numbers are doubles (the contract only
// carries small integers: exit_code, timeout). Strings are byte strings --
// UTF-8 passes through untouched, \uXXXX escapes are decoded to UTF-8.
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace minijson {

class Value;
using Array = std::vector<Value>;
using Object = std::map<std::string, Value>;

class Value {
 public:
  enum class Type { Null, Bool, Number, String, Array, Object };

  Value() : type_(Type::Null) {}
  Value(std::nullptr_t) : type_(Type::Null) {}
  Value(bool b) : type_(Type::Bool), bool_(b) {}
  Value(double n) : type_(Type::Number), num_(n) {}
  Value(int n) : type_(Type::Number), num_(n) {}
  Value(int64_t n) : type_(Type::Number), num_(static_cast<double>(n)) {}
  Value(const char* s) : type_(Type::String), str_(s) {}
  Value(std::string s) : type_(Type::String), str_(std::move(s)) {}
  Value(Array a) : type_(Type::Array), arr_(std::move(a)) {}
  Value(Object o) : type_(Type::Object), obj_(std::move(o)) {}

  Type type() const { return type_; }
  bool is_null() const { return type_ == Type::Null; }
  bool as_bool(bool dflt = false) const { return type_ == Type::Bool ? bool_ : dflt; }
  double as_number(double dflt = 0) const { return type_ == Type::Number ? num_ : dflt; }
  const std::string& as_string() const {
    static const std::string empty;
    return type_ == Type::String ? str_ : empty;
  }
  const Array& as_array() const {
    static const Array empty;
    return type_ == Type::Array ? arr_ : empty;
  }
  const Object& as_object() const {
    static const Object empty;
    return type_ == Type::Object ? obj_ : empty;
  }
  const Value& operator[](const std::string& key) const {
    static const Value null_value;
    if (type_ != Type::Object) return null_value;
    auto it = obj_.find(key);
    return it == obj_.end() ? null_value : it->second;
  }

 private:
  Type type_;
  bool bool_ = false;
  double num_ = 0;
  std::string str_;
  Array arr_;
  Object obj_;
};

namespace detail {

struct Parser {
  const char* p;
  const char* end;

  [[noreturn]] void fail(const std::string& msg) {
    throw std::runtime_error("json parse error: " + msg);
  }
  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }
  char peek() {
    if (p >= end) fail("unexpected end");
    return *p;
  }
  void expect(char c) {
    if (p >= end || *p != c) fail(std::string("expected '") + c + "'");
    ++p;
  }
  bool consume(const char* lit) {
    size_t n = strlen(lit);
    if (static_cast<size_t>(end - p) >= n && memcmp(p, lit, n) == 0) {
      p += n;
      return true;
    }
    return false;
  }

  Value parse_value() {
    skip_ws();
    char c = peek();
    if (c == '{') return parse_object();
    if (c == '[') return parse_array();
    if (c == '"') return Value(parse_string());
    if (consume("true")) return Value(true);
    if (consume("false")) return Value(false);
    if (consume("null")) return Value(nullptr);
    return parse_number();
  }

  Value parse_object() {
    expect('{');
    Object obj;
    skip_ws();
    if (peek() == '}') { ++p; return Value(std::move(obj)); }
    while (true) {
      skip_ws();
      std::string key = parse_string();
      skip_ws();
      expect(':');
      obj[std::move(key)] = parse_value();
      skip_ws();
      if (peek() == ',') { ++p; continue; }
      expect('}');
      return Value(std::move(obj));
    }
  }

  Value parse_array() {
    expect('[');
    Array arr;
    skip_ws();
    if (peek() == ']') { ++p; return Value(std::move(arr)); }
    while (true) {
      arr.push_back(parse_value());
      skip_ws();
      if (peek() == ',') { ++p; continue; }
      expect(']');
      return Value(std::move(arr));
    }
  }

  std::string parse_string() {
    expect('"');
    std::string out;
    while (true) {
      if (p >= end) fail("unterminated string");
      char c = *p++;
      if (c == '"') return out;
      if (c == '\\') {
        if (p >= end) fail("bad escape");
        char e = *p++;
        switch (e) {
          case '"': out += '"'; break;
          case '\\': out += '\\'; break;
          case '/': out += '/'; break;
          case 'b': out += '\b'; break;
          case 'f': out += '\f'; break;
          case 'n': out += '\n'; break;
          case 'r': out += '\r'; break;
          case 't': out += '\t'; break;
          case 'u': {
            unsigned cp = parse_hex4();
            if (cp >= 0xD800 && cp <= 0xDBFF) {  // surrogate pair
              if (!consume("\\u")) fail("lone high surrogate");
              unsigned lo = parse_hex4();
              cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
            }
            append_utf8(out, cp);
            break;
          }
          default: fail("bad escape char");
        }
      } else {
        out += c;
      }
    }
  }

  unsigned parse_hex4() {
    if (end - p < 4) fail("bad \\u escape");
    unsigned v = 0;
    for (int i = 0; i < 4; ++i) {
      char c = *p++;
      v <<= 4;
      if (c >= '0' && c <= '9') v |= c - '0';
      else if (c >= 'a' && c <= 'f') v |= c - 'a' + 10;
      else if (c >= 'A' && c <= 'F') v |= c - 'A' + 10;
      else fail("bad hex digit");
    }
    return v;
  }

  static void append_utf8(std::string& out, unsigned cp) {
    if (cp < 0x80) {
      out += static_cast<char>(cp);
    } else if (cp < 0x800) {
      out += static_cast<char>(0xC0 | (cp >> 6));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else if (cp < 0x10000) {
      out += static_cast<char>(0xE0 | (cp >> 12));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    } else {
      out += static_cast<char>(0xF0 | (cp >> 18));
      out += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
      out += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
      out += static_cast<char>(0x80 | (cp & 0x3F));
    }
  }

  Value parse_number() {
    const char* start = p;
    if (p < end && *p == '-') ++p;
    while (p < end && ((*p >= '0' && *p <= '9') || *p == '.' || *p == 'e' ||
                       *p == 'E' || *p == '+' || *p == '-'))
      ++p;
    if (p == start) fail("invalid value");
    return Value(std::stod(std::string(start, p)));
  }
};

inline void dump_string(const std::string& s, std::string& out) {
  out += '"';
  for (unsigned char c : s) {
    switch (c) {
      case '"': out += "\\\""; break;
      case '\\': out += "\\\\"; break;
      case '\b': out += "\\b"; break;
      case '\f': out += "\\f"; break;
      case '\n': out += "\\n"; break;
      case '\r': out += "\\r"; break;
      case '\t': out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof buf, "\\u%04x", c);
          out += buf;
        } else {
          out += static_cast<char>(c);
        }
    }
  }
  out += '"';
}

}  // namespace detail

inline Value parse(const std::string& text) {
  detail::Parser parser{text.data(), text.data() + text.size()};
  Value v = parser.parse_value();
  parser.skip_ws();
  if (parser.p != parser.end) parser.fail("trailing garbage");
  return v;
}

inline void dump(const Value& v, std::string& out) {
  switch (v.type()) {
    case Value::Type::Null: out += "null"; break;
    case Value::Type::Bool: out += v.as_bool() ? "true" : "false"; break;
    case Value::Type::Number: {
      double d = v.as_number();
      if (d == static_cast<int64_t>(d)) {
        out += std::to_string(static_cast<int64_t>(d));
      } else {
        char buf[32];
        snprintf(buf, sizeof buf, "%.17g", d);
        out += buf;
      }
      break;
    }
    case Value::Type::String: detail::dump_string(v.as_string(), out); break;
    case Value::Type::Array: {
      out += '[';
      bool first = true;
      for (const auto& e : v.as_array()) {
        if (!first) out += ',';
        first = false;
        dump(e, out);
      }
      out += ']';
      break;
    }
    case Value::Type::Object: {
      out += '{';
      bool first = true;
      for (const auto& [k, val] : v.as_object()) {
        if (!first) out += ',';
        first = false;
        detail::dump_string(k, out);
        out += ':';
        dump(val, out);
      }
      out += '}';
      break;
    }
  }
}

inline std::string dump(const Value& v) {
  std::string out;
  dump(v, out);
  return out;
}

}  // namespace minijson
