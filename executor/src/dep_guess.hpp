// Static import -> PyPI dependency guesser (native).
//
// In-process replacement for the reference's `upm guess` subprocess + sqlite
// map (reference server.rs:126-138, executor/Dockerfile:30-37,124-126). Same
// algorithm as the Python oracle (bee_code_interpreter_tpu/runtime/dep_guess.py):
// scan top-level absolute imports, drop stdlib/skip/preinstalled, map through
// the import->PyPI table (pypi_map.tsv, shared with the Python side).
//
// The stdlib module set is asked from the interpreter once at startup
// (sys.stdlib_module_names) rather than embedded, so it always matches the
// sandbox's actual Python.
#pragma once

#include <algorithm>
#include <cctype>
#include <map>
#include <regex>
#include <set>
#include <sstream>
#include <string>
#include <vector>

namespace dep_guess {

// PEP 503 normalization + extras stripping ("pandas[excel]" -> "pandas").
inline std::string normalize(std::string name) {
  auto bracket = name.find('[');
  if (bracket != std::string::npos) name.resize(bracket);
  // trim
  while (!name.empty() && isspace(static_cast<unsigned char>(name.back()))) name.pop_back();
  size_t start = 0;
  while (start < name.size() && isspace(static_cast<unsigned char>(name[start]))) ++start;
  name = name.substr(start);
  for (auto& c : name) {
    c = static_cast<char>(tolower(static_cast<unsigned char>(c)));
    if (c == '_' || c == '.') c = '-';
  }
  return name;
}

// Accelerator stack + OS-provided names that must never be pip-installed
// (mirrors dep_guess.py SKIP; reference requirements-skip.txt:1-26).
inline const std::set<std::string>& builtin_skip() {
  static const std::set<std::string> skip = {
      "jax", "jaxlib", "libtpu", "torch", "torch_xla", "functorch",
      "flax", "optax", "orbax", "chex", "haiku", "pallas",
      // NOT "ffmpeg": that import maps to the real ffmpeg-python dist.
      "pandoc", "magick", "imagemagick",
      "bee_code_interpreter_tpu",
  };
  return skip;
}

// PEP 420 namespace packages whose top-level name is NOT an installable
// distribution (mirrors dep_guess.py NAMESPACE_PREFIXES): retain one more
// path component under these so the map can key on the level that actually
// identifies a distribution ("google.protobuf" -> protobuf).
inline const std::set<std::string>& namespace_prefixes() {
  static const std::set<std::string> prefixes = {
      "google", "google.cloud",
      // azure: pure PEP-420 namespace; per-component dists follow the
      // dots->dashes convention the unmapped fallback applies
      "azure", "azure.storage", "azure.keyvault", "azure.mgmt",
      "azure.search", "azure.ai", "azure.data", "azure.communication",
      "azure.monitor", "azure.iot", "azure.synapse",
  };
  return prefixes;
}

// Truncate a dotted module path to the map-lookup key: the top-level name,
// extended one level at a time while the prefix is a known namespace.
inline std::string retained_name(const std::string& dotted) {
  size_t end = dotted.find('.');
  while (end != std::string::npos &&
         namespace_prefixes().count(dotted.substr(0, end))) {
    end = dotted.find('.', end + 1);
  }
  return dotted.substr(0, end);
}

// Module names from absolute `import X` / `from X import ...` statements,
// truncated to the top level — except under namespace packages, where one
// more component is retained. A line-based scan is sufficient for dependency
// *guessing* (imports hidden behind exec/getattr are out of scope, same as upm).
inline std::set<std::string> guessed_imports(const std::string& source) {
  static const std::regex import_re(R"(^\s*import\s+(.+?)\s*$)");
  static const std::regex from_re(
      R"(^\s*from\s+([A-Za-z_][\w.]*)\s+import\b\s*(.*))");
  static const std::regex import_start_re(R"(^\s*(from|import)\b)");
  std::set<std::string> names;
  std::istringstream stream(source);
  std::string line;
  auto paren_balance = [](const std::string& s) {
    int b = 0;
    for (char c : s) {
      if (c == '(') ++b;
      else if (c == ')') --b;
    }
    return b;
  };
  while (std::getline(stream, line)) {
    // Join parenthesized continuations so
    // `from google.cloud import (\n  storage,\n  bigquery,\n)` scans as one
    // logical line (the Python AST oracle sees it that way for free). Gated
    // on lines that actually START with from/import — an unbalanced '(' in
    // an arbitrary line (string literal, comment) must not swallow genuine
    // import lines after it.
    if (std::regex_search(line, import_start_re)) {
      int balance = paren_balance(line);
      std::string next;
      while (balance > 0 && std::getline(stream, next)) {
        line += " " + next;
        balance += paren_balance(next);
      }
    }
    std::smatch m;
    if (std::regex_search(line, m, from_re)) {
      std::string mod = m[1].str();
      if (namespace_prefixes().count(mod)) {
        // `from google.cloud import storage, bigquery` — the imported names
        // are the level that identifies the distribution.
        std::string rest = m[2].str();
        auto hash = rest.find('#');
        if (hash != std::string::npos) rest.resize(hash);
        std::istringstream parts(rest);
        std::string part;
        while (std::getline(parts, part, ',')) {
          part.erase(std::remove_if(part.begin(), part.end(),
                                    [](char c) { return c == '(' || c == ')'; }),
                     part.end());
          std::istringstream words(part);
          std::string name;
          words >> name;  // first token; ignores "as alias"
          if (name.empty() || name == "*") continue;
          bool valid = true;
          for (char c : name)
            if (!(isalnum(static_cast<unsigned char>(c)) || c == '_')) valid = false;
          if (valid) names.insert(retained_name(mod + "." + name));
        }
      } else {
        names.insert(retained_name(mod));
      }
    } else if (std::regex_match(line, m, import_re)) {
      // "import a.b as c, d" -> a, d ; strip trailing comments
      std::string rest = m[1].str();
      auto hash = rest.find('#');
      if (hash != std::string::npos) rest.resize(hash);
      std::istringstream parts(rest);
      std::string part;
      while (std::getline(parts, part, ',')) {
        std::istringstream words(part);
        std::string mod;
        words >> mod;  // first token; ignores "as alias"
        if (mod.empty() || mod[0] == '.') continue;
        bool valid = true;
        for (char c : mod) {
          if (!(isalnum(static_cast<unsigned char>(c)) || c == '_' || c == '.')) {
            valid = false;
            break;
          }
        }
        if (valid) names.insert(retained_name(mod));
      }
    }
  }
  return names;
}

struct Guesser {
  std::set<std::string> stdlib;                 // module names
  std::map<std::string, std::string> pypi_map;  // import name -> dist name
  std::set<std::string> preinstalled;           // normalized dist names

  std::vector<std::string> guess(const std::string& source) const {
    std::vector<std::string> deps;
    for (const auto& mod : guessed_imports(source)) {
      std::string top = mod.substr(0, mod.find('.'));
      if (stdlib.count(top) || builtin_skip().count(top)) continue;
      if (namespace_prefixes().count(mod)) continue;  // bare `import google`
      auto it = pypi_map.find(mod);
      std::string pkg;
      if (it != pypi_map.end()) {
        pkg = it->second;
      } else {
        // Unmapped namespace names fall back to dots→dashes — the actual
        // convention for e.g. google.cloud.storage → google-cloud-storage.
        pkg = mod;
        std::replace(pkg.begin(), pkg.end(), '.', '-');
      }
      if (preinstalled.count(normalize(pkg)) || preinstalled.count(normalize(mod)))
        continue;
      deps.push_back(pkg);
    }
    std::sort(deps.begin(), deps.end());
    deps.erase(std::unique(deps.begin(), deps.end()), deps.end());
    return deps;
  }
};

// pypi_map.tsv: "<import-name>\t<pypi-name>" per line, '#' comments.
inline std::map<std::string, std::string> load_pypi_map(const std::string& text) {
  std::map<std::string, std::string> map;
  std::istringstream stream(text);
  std::string line;
  while (std::getline(stream, line)) {
    if (line.empty() || line[0] == '#') continue;
    auto tab = line.find('\t');
    if (tab == std::string::npos) continue;
    map[line.substr(0, tab)] = line.substr(tab + 1);
  }
  return map;
}

// requirements.txt-style parsing into the normalized preinstalled set
// (reference server.rs:44-67).
inline void load_requirements_into(const std::string& text,
                                   std::set<std::string>& out) {
  std::istringstream stream(text);
  std::string line;
  while (std::getline(stream, line)) {
    auto hash = line.find('#');
    if (hash != std::string::npos) line.resize(hash);
    for (const char* sep : {"==", ">=", "<=", "~=", "!=", ">", "<", ";", "@"}) {
      auto pos = line.find(sep);
      if (pos != std::string::npos) line.resize(pos);
    }
    std::string name = normalize(line);
    if (!name.empty()) out.insert(name);
  }
}

}  // namespace dep_guess
