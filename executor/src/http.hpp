// Minimal multi-threaded HTTP/1.1 server for the executor wire contract.
//
// Replaces the reference's actix-web dependency (executor/server.rs:186-192)
// with a dependency-free implementation: blocking accept loop, one thread per
// connection (per-pod concurrency is a handful of requests), Content-Length
// and chunked request bodies (the control plane streams uploads chunked),
// streaming file responses. Not a general web server -- exactly what the
// executor needs.
#pragma once

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cstdio>
#include <cstring>
#include <functional>
#include <map>
#include <optional>
#include <string>
#include <thread>
#include <vector>

namespace minihttp {

struct Request {
  std::string method;
  std::string path;  // percent-decoded, query stripped
  std::map<std::string, std::string> headers;  // lower-cased keys
  std::string body;       // buffered body (empty when streamed to disk)
  std::string body_file;  // when non-empty, body was streamed to this path
};

struct Response {
  int status = 200;
  std::string content_type = "application/json";
  std::string body;
  std::optional<std::string> file_path;  // if set, stream this file as body
};

using Handler = std::function<Response(const Request&)>;

// Called once the request line + headers are parsed. Returning a file path
// streams the body to that file chunk-by-chunk as it arrives (the request's
// `body_file` is set, `body` stays empty) — a large workspace restore costs
// disk, not resident memory (the reference executor streams uploads the same
// way, server.rs:83-86). Returning nullopt buffers the body in RAM as before.
using SinkSelector =
    std::function<std::optional<std::string>(const Request&)>;

inline std::string status_text(int code) {
  switch (code) {
    case 200: return "OK";
    case 204: return "No Content";
    case 400: return "Bad Request";
    case 404: return "Not Found";
    case 405: return "Method Not Allowed";
    case 413: return "Payload Too Large";
    default: return "Internal Server Error";
  }
}

class Server {
 public:
  explicit Server(Handler handler, SinkSelector sink = nullptr)
      : handler_(std::move(handler)), sink_(std::move(sink)) {}

  // Binds and listens; returns the bound port (for ":0" style tests).
  int bind(const std::string& host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof one);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (inet_pton(AF_INET, host.c_str(), &addr.sin_addr) != 1)
      addr.sin_addr.s_addr = INADDR_ANY;
    if (::bind(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof addr) != 0)
      throw std::runtime_error("bind failed: " + std::string(strerror(errno)));
    if (::listen(fd_, 64) != 0)
      throw std::runtime_error("listen failed");
    socklen_t len = sizeof addr;
    getsockname(fd_, reinterpret_cast<sockaddr*>(&addr), &len);
    return ntohs(addr.sin_port);
  }

  void serve_forever() {
    while (!stopping_.load()) {
      int client = ::accept(fd_, nullptr, nullptr);
      if (client < 0) {
        if (stopping_.load()) break;
        continue;
      }
      std::thread([this, client] { handle_connection(client); }).detach();
    }
  }

  void stop() {
    stopping_.store(true);
    ::shutdown(fd_, SHUT_RDWR);
    ::close(fd_);
  }

 private:
  void handle_connection(int fd) {
    int one = 1;
    setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof one);
    std::string buffer;
    // keep-alive loop: serve requests until the peer closes
    while (true) {
      Request req;
      if (!read_request(fd, buffer, req)) break;
      Response resp;
      try {
        resp = handler_(req);
      } catch (const std::exception& e) {
        resp.status = 500;
        resp.body = std::string("{\"detail\":\"") + e.what() + "\"}";
      }
      if (!write_response(fd, resp)) break;
      auto it = req.headers.find("connection");
      if (it != req.headers.end() && it->second == "close") break;
    }
    ::close(fd);
  }

  // Reads one full request (headers + body) from fd into req. Returns false
  // on EOF/error. `buffer` carries over bytes read past the previous request.
  bool read_request(int fd, std::string& buffer, Request& req) {
    // -- headers --
    size_t header_end;
    while ((header_end = buffer.find("\r\n\r\n")) == std::string::npos) {
      if (!fill(fd, buffer)) return false;
      if (buffer.size() > (1u << 20)) return false;  // header flood
    }
    std::string head = buffer.substr(0, header_end);
    buffer.erase(0, header_end + 4);

    size_t line_end = head.find("\r\n");
    std::string request_line = head.substr(0, line_end);
    size_t sp1 = request_line.find(' ');
    size_t sp2 = request_line.find(' ', sp1 + 1);
    if (sp1 == std::string::npos || sp2 == std::string::npos) return false;
    req.method = request_line.substr(0, sp1);
    std::string target = request_line.substr(sp1 + 1, sp2 - sp1 - 1);
    size_t q = target.find('?');
    if (q != std::string::npos) target.resize(q);
    req.path = percent_decode(target);

    size_t pos = line_end + 2;
    while (pos < head.size()) {
      size_t eol = head.find("\r\n", pos);
      if (eol == std::string::npos) eol = head.size();
      std::string line = head.substr(pos, eol - pos);
      pos = eol + 2;
      size_t colon = line.find(':');
      if (colon == std::string::npos) continue;
      std::string key = line.substr(0, colon);
      for (auto& c : key) c = static_cast<char>(tolower(c));
      size_t vstart = line.find_first_not_of(" \t", colon + 1);
      req.headers[key] = vstart == std::string::npos ? "" : line.substr(vstart);
    }

    // -- body --
    // One parser per framing, writing through a sink: append-to-string for
    // buffered bodies, fwrite-to-disk when the selector claims the request
    // (resident memory stays one recv buffer regardless of body size, and
    // kMaxBody acts as a disk quota instead of a RAM cap).
    std::optional<std::string> sink_path;
    if (sink_) sink_path = sink_(req);
    auto te = req.headers.find("transfer-encoding");
    bool chunked = te != req.headers.end() &&
                   te->second.find("chunked") != std::string::npos;
    auto cl = req.headers.find("content-length");
    size_t content_length = 0;
    if (cl != req.headers.end()) {
      try {
        content_length = std::stoull(cl->second);
      } catch (const std::exception&) {
        return false;  // garbage Content-Length: drop the connection
      }
    }

    bool ok;
    if (sink_path) {
      FILE* out = fopen(sink_path->c_str(), "wb");
      if (!out) return false;
      auto write_file = [out](const char* data, size_t n) {
        return fwrite(data, 1, n, out) == n;
      };
      ok = chunked ? read_chunked_body(fd, buffer, write_file)
                   : read_length_body(fd, buffer, content_length, write_file);
      if (fclose(out) != 0) ok = false;
      if (!ok) {
        ::remove(sink_path->c_str());  // never leave a torn part-file behind
        return false;
      }
      req.body_file = *sink_path;
      return true;
    }
    auto write_mem = [&req](const char* data, size_t n) {
      req.body.append(data, n);
      return true;
    };
    ok = chunked ? read_chunked_body(fd, buffer, write_mem)
                 : read_length_body(fd, buffer, content_length, write_mem);
    return ok;
  }

  using BodySink = std::function<bool(const char*, size_t)>;

  bool read_length_body(int fd, std::string& buffer, size_t remaining,
                        const BodySink& write) {
    if (remaining > kMaxBody) return false;
    while (remaining > 0) {
      if (buffer.empty() && !fill(fd, buffer)) return false;
      size_t take = std::min(remaining, buffer.size());
      if (!write(buffer.data(), take)) return false;
      buffer.erase(0, take);
      remaining -= take;
    }
    return true;
  }

  bool read_chunked_body(int fd, std::string& buffer, const BodySink& write) {
    size_t total = 0;
    while (true) {
      size_t eol;
      while ((eol = buffer.find("\r\n")) == std::string::npos) {
        if (!fill(fd, buffer)) return false;
      }
      size_t chunk_size;
      try {
        chunk_size = std::stoull(buffer.substr(0, eol), nullptr, 16);
      } catch (const std::exception&) {
        return false;  // garbage chunk-size line
      }
      buffer.erase(0, eol + 2);
      if (chunk_size == 0) {
        // trailer section ends with CRLF
        while (buffer.find("\r\n") == std::string::npos) {
          if (!fill(fd, buffer)) return false;
        }
        buffer.erase(0, buffer.find("\r\n") + 2);
        return true;
      }
      total += chunk_size;
      if (total > kMaxBody) return false;
      size_t remaining = chunk_size;
      while (remaining > 0) {
        if (buffer.empty() && !fill(fd, buffer)) return false;
        size_t take = std::min(remaining, buffer.size());
        if (!write(buffer.data(), take)) return false;
        buffer.erase(0, take);
        remaining -= take;
      }
      // trailing CRLF after the chunk data
      while (buffer.size() < 2) {
        if (!fill(fd, buffer)) return false;
      }
      buffer.erase(0, 2);
    }
  }

  bool read_chunked_body(int fd, std::string& buffer, std::string& body) {
    while (true) {
      size_t eol;
      while ((eol = buffer.find("\r\n")) == std::string::npos) {
        if (!fill(fd, buffer)) return false;
      }
      size_t chunk_size = std::stoull(buffer.substr(0, eol), nullptr, 16);
      buffer.erase(0, eol + 2);
      if (chunk_size == 0) {
        // trailer section ends with CRLF
        while (buffer.find("\r\n") == std::string::npos) {
          if (!fill(fd, buffer)) return false;
        }
        buffer.erase(0, buffer.find("\r\n") + 2);
        return true;
      }
      if (body.size() + chunk_size > kMaxBody) return false;
      while (buffer.size() < chunk_size + 2) {
        if (!fill(fd, buffer)) return false;
      }
      body.append(buffer, 0, chunk_size);
      buffer.erase(0, chunk_size + 2);  // chunk + CRLF
    }
  }

  bool write_response(int fd, const Response& resp) {
    std::string body = resp.body;
    long long content_length = static_cast<long long>(body.size());
    FILE* file = nullptr;
    if (resp.file_path) {
      file = fopen(resp.file_path->c_str(), "rb");
      if (!file) {
        return write_response(fd, Response{404, "application/json", "{}", {}});
      }
      fseek(file, 0, SEEK_END);
      content_length = ftell(file);
      fseek(file, 0, SEEK_SET);
    }
    std::string head = "HTTP/1.1 " + std::to_string(resp.status) + " " +
                       status_text(resp.status) + "\r\n" +
                       "Content-Type: " + resp.content_type + "\r\n" +
                       "Content-Length: " + std::to_string(content_length) +
                       "\r\n\r\n";
    bool ok = send_all(fd, head.data(), head.size());
    if (ok && file) {
      char buf[1 << 16];
      size_t n;
      while (ok && (n = fread(buf, 1, sizeof buf, file)) > 0)
        ok = send_all(fd, buf, n);
    } else if (ok && !body.empty()) {
      ok = send_all(fd, body.data(), body.size());
    }
    if (file) fclose(file);
    return ok;
  }

  static bool send_all(int fd, const char* data, size_t len) {
    while (len > 0) {
      ssize_t n = ::send(fd, data, len, MSG_NOSIGNAL);
      if (n <= 0) return false;
      data += n;
      len -= static_cast<size_t>(n);
    }
    return true;
  }

  static bool fill(int fd, std::string& buffer) {
    char buf[1 << 16];
    ssize_t n = ::recv(fd, buf, sizeof buf, 0);
    if (n <= 0) return false;
    buffer.append(buf, static_cast<size_t>(n));
    return true;
  }

  static std::string percent_decode(const std::string& s) {
    std::string out;
    for (size_t i = 0; i < s.size(); ++i) {
      if (s[i] == '%' && i + 2 < s.size()) {
        auto hex = [](char c) -> int {
          if (c >= '0' && c <= '9') return c - '0';
          if (c >= 'a' && c <= 'f') return c - 'a' + 10;
          if (c >= 'A' && c <= 'F') return c - 'A' + 10;
          return -1;
        };
        int hi = hex(s[i + 1]), lo = hex(s[i + 2]);
        if (hi >= 0 && lo >= 0) {
          out += static_cast<char>(hi * 16 + lo);
          i += 2;
          continue;
        }
      }
      out += s[i];
    }
    return out;
  }

  static constexpr size_t kMaxBody = 1ull << 30;  // 1 GiB, matches control plane

  Handler handler_;
  SinkSelector sink_;
  int fd_ = -1;
  std::atomic<bool> stopping_{false};
};

}  // namespace minihttp
