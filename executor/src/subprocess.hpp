// Subprocess execution with wall-clock timeout and process-group kill.
//
// Same behavior as the reference executor's run path (server.rs:149-169):
// run the interpreter on the script with the request env merged in, capture
// stdout/stderr, and on timeout return exit_code -1 with stderr "Execution
// timed out". The child gets its own process group (setpgid) so the timeout
// kill also reaps grandchildren the user code spawned.
#pragma once

#include <fcntl.h>
#include <poll.h>
#include <signal.h>
#include <spawn.h>
#include <sys/wait.h>
#include <unistd.h>

#include <algorithm>
#include <chrono>
#include <cstring>
#include <map>
#include <string>
#include <thread>
#include <vector>

namespace subprocess {

struct RunResult {
  std::string out;
  std::string err;
  int exit_code = 0;
  bool timed_out = false;
};

inline constexpr const char* kTimeoutMessage = "Execution timed out";

// A spawned child with captured output (and optionally writable stdin).
// Returned by spawn(); pass to collect() to stream output until exit.
struct Child {
  pid_t pid = -1;
  int stdin_fd = -1;   // -1 unless want_stdin
  int status_fd = -1;  // -1 unless want_status (child writes on its fd 3)
  int out_fd = -1;
  int err_fd = -1;

  bool valid() const { return pid > 0; }

  bool alive() const {
    if (pid <= 0) return false;
    int status = 0;
    return waitpid(pid, &status, WNOHANG) == 0;
  }

  void close_fds() {
    if (stdin_fd >= 0) { close(stdin_fd); stdin_fd = -1; }
    if (status_fd >= 0) { close(status_fd); status_fd = -1; }
    if (out_fd >= 0) { close(out_fd); out_fd = -1; }
    if (err_fd >= 0) { close(err_fd); err_fd = -1; }
  }

  void kill_group() {
    if (pid > 0) kill(-pid, SIGKILL);
  }
};

// Block up to timeout_s for one byte on a status fd. True iff a byte arrived;
// false on EOF (writer died without reporting) or deadline.
// Waits for a specific status byte on the pipe, skipping earlier protocol
// bytes (the warm worker writes 'P' at preload-done, then 'S' right before
// user code runs; a caller waiting for 'S' must tolerate an unconsumed 'P').
// expected == 0 accepts any byte.
inline bool wait_for_status_byte(int fd, double timeout_s, char expected = 0) {
  if (fd < 0) return false;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_s);
  while (true) {
    auto remaining = std::chrono::duration_cast<std::chrono::milliseconds>(
                         deadline - std::chrono::steady_clock::now())
                         .count();
    pollfd p{fd, POLLIN, 0};
    int rc = poll(&p, 1,
                  static_cast<int>(std::clamp<long long>(remaining, 0, 1000)));
    if (rc < 0) return false;
    if (p.revents & (POLLIN | POLLHUP)) {
      char b;
      ssize_t n = read(fd, &b, 1);
      if (n == 1) {
        if (expected == 0 || b == expected) return true;
        continue;  // earlier protocol byte; keep draining
      }
      if (n == 0) return false;  // EOF: writer exited silently
      if (errno != EAGAIN && errno != EINTR) return false;
    }
    if (remaining <= 0) return false;
  }
}

// Fork+exec into its own process group with stdout/stderr pipes (and stdin /
// status pipes when requested). env is the COMPLETE child environment.
inline Child spawn(const std::vector<std::string>& argv,
                   const std::map<std::string, std::string>& env,
                   const std::string& cwd,
                   bool want_stdin = false,
                   bool want_status = false) {
  int out_pipe[2] = {-1, -1}, err_pipe[2] = {-1, -1}, in_pipe[2] = {-1, -1},
      status_pipe[2] = {-1, -1};
  auto close_all = [&] {
    for (int fd : {out_pipe[0], out_pipe[1], err_pipe[0], err_pipe[1],
                   in_pipe[0], in_pipe[1], status_pipe[0], status_pipe[1]})
      if (fd >= 0) close(fd);
  };
  if (pipe(out_pipe) != 0 || pipe(err_pipe) != 0 ||
      (want_stdin && pipe(in_pipe) != 0) ||
      (want_status && pipe(status_pipe) != 0)) {
    close_all();
    return {};
  }

  pid_t pid = fork();
  if (pid < 0) {
    close_all();
    return {};
  }
  if (pid == 0) {
    // child
    setpgid(0, 0);
    if (!cwd.empty()) {
      if (chdir(cwd.c_str()) != 0) _exit(127);
    }
    if (want_stdin) {
      dup2(in_pipe[0], STDIN_FILENO);
      close(in_pipe[0]); close(in_pipe[1]);
    }
    dup2(out_pipe[1], STDOUT_FILENO);
    dup2(err_pipe[1], STDERR_FILENO);
    close(out_pipe[0]); close(out_pipe[1]);
    close(err_pipe[0]); close(err_pipe[1]);
    if (want_status) {
      // AFTER the other pipes are dup2'd+closed: fd 3 may have been one of
      // their descriptor numbers, and closing them would clobber it.
      dup2(status_pipe[1], 3);
      if (status_pipe[0] != 3) close(status_pipe[0]);
      if (status_pipe[1] != 3) close(status_pipe[1]);
    }
    std::vector<char*> cargv;
    for (const auto& a : argv) cargv.push_back(const_cast<char*>(a.c_str()));
    cargv.push_back(nullptr);
    std::vector<std::string> env_strings;
    env_strings.reserve(env.size());
    for (const auto& [k, v] : env) env_strings.push_back(k + "=" + v);
    std::vector<char*> cenv;
    for (const auto& e : env_strings) cenv.push_back(const_cast<char*>(e.c_str()));
    cenv.push_back(nullptr);
    execve(argv[0].c_str(), cargv.data(), cenv.data());
    // fallback to PATH lookup
    execvpe(argv[0].c_str(), cargv.data(), cenv.data());
    fprintf(stderr, "exec failed: %s\n", strerror(errno));
    _exit(127);
  }

  // parent
  setpgid(pid, pid);  // race-safe double setpgid
  close(out_pipe[1]);
  close(err_pipe[1]);
  Child child;
  child.pid = pid;
  child.out_fd = out_pipe[0];
  child.err_fd = err_pipe[0];
  if (want_stdin) {
    close(in_pipe[0]);
    child.stdin_fd = in_pipe[1];
  }
  if (want_status) {
    close(status_pipe[1]);
    child.status_fd = status_pipe[0];
    fcntl(child.status_fd, F_SETFL, O_NONBLOCK);
  }
  fcntl(child.out_fd, F_SETFL, O_NONBLOCK);
  fcntl(child.err_fd, F_SETFL, O_NONBLOCK);
  return child;
}

// Stream the child's output until exit or deadline (timeout → process-group
// SIGKILL, exit_code -1, stderr replaced with the timeout message).
inline RunResult collect(Child child, double timeout_s) {
  if (!child.valid()) return {"", "spawn failed", -1, false};
  if (child.stdin_fd >= 0) { close(child.stdin_fd); child.stdin_fd = -1; }
  if (child.status_fd >= 0) { close(child.status_fd); child.status_fd = -1; }
  int out_pipe0 = child.out_fd, err_pipe0 = child.err_fd;
  pid_t pid = child.pid;

  RunResult result;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_s);
  bool out_open = true, err_open = true;
  char buf[1 << 16];
  while (out_open || err_open) {
    auto remaining = std::chrono::duration_cast<std::chrono::milliseconds>(
                         deadline - std::chrono::steady_clock::now())
                         .count();
    if (remaining <= 0) {
      result.timed_out = true;
      kill(-pid, SIGKILL);
      break;
    }
    pollfd fds[2];
    nfds_t nfds = 0;
    if (out_open) fds[nfds++] = {out_pipe0, POLLIN, 0};
    if (err_open) fds[nfds++] = {err_pipe0, POLLIN, 0};
    int rc = poll(fds, nfds, static_cast<int>(std::min<long long>(remaining, 1000)));
    if (rc < 0) break;
    for (nfds_t i = 0; i < nfds; ++i) {
      if (!(fds[i].revents & (POLLIN | POLLHUP))) continue;
      ssize_t n = read(fds[i].fd, buf, sizeof buf);
      bool is_out = fds[i].fd == out_pipe0;
      if (n > 0) {
        (is_out ? result.out : result.err).append(buf, static_cast<size_t>(n));
      } else if (n == 0 || (n < 0 && errno != EAGAIN)) {
        if (is_out) out_open = false; else err_open = false;
      }
    }
  }
  close(out_pipe0);
  close(err_pipe0);

  int status = 0;
  waitpid(pid, &status, 0);
  if (result.timed_out) {
    result.out.clear();
    result.err = kTimeoutMessage;
    result.exit_code = -1;
  } else if (WIFEXITED(status)) {
    result.exit_code = WEXITSTATUS(status);
  } else if (WIFSIGNALED(status)) {
    result.exit_code = -WTERMSIG(status);
  }
  return result;
}

// Warm-worker collect: the bootstrap reports the script's exit code on the
// status pipe ("X<code>\n") and closes its stdio as soon as user code and
// user atexit handlers finish, so the response doesn't wait out interpreter
// finalization (~100 ms with a scientific stack loaded — measured as the
// whole warm-path latency floor). The zombie is reaped on a detached thread.
// Falls back to a blocking reap when the worker dies without reporting
// (crash/signal/user closed fd 3).
inline RunResult collect_warm(Child child, double timeout_s) {
  if (!child.valid()) return {"", "spawn failed", -1, false};
  if (child.stdin_fd >= 0) { close(child.stdin_fd); child.stdin_fd = -1; }
  int out_pipe0 = child.out_fd, err_pipe0 = child.err_fd;
  pid_t pid = child.pid;

  RunResult result;
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_s);
  bool out_open = true, err_open = true;
  char buf[1 << 16];
  while (out_open || err_open) {
    auto remaining = std::chrono::duration_cast<std::chrono::milliseconds>(
                         deadline - std::chrono::steady_clock::now())
                         .count();
    if (remaining <= 0) {
      result.timed_out = true;
      kill(-pid, SIGKILL);
      break;
    }
    pollfd fds[2];
    nfds_t nfds = 0;
    if (out_open) fds[nfds++] = {out_pipe0, POLLIN, 0};
    if (err_open) fds[nfds++] = {err_pipe0, POLLIN, 0};
    int rc = poll(fds, nfds, static_cast<int>(std::min<long long>(remaining, 1000)));
    if (rc < 0) break;
    for (nfds_t i = 0; i < nfds; ++i) {
      if (!(fds[i].revents & (POLLIN | POLLHUP))) continue;
      ssize_t n = read(fds[i].fd, buf, sizeof buf);
      bool is_out = fds[i].fd == out_pipe0;
      if (n > 0) {
        (is_out ? result.out : result.err).append(buf, static_cast<size_t>(n));
      } else if (n == 0 || (n < 0 && errno != EAGAIN)) {
        if (is_out) out_open = false; else err_open = false;
      }
    }
  }
  close(out_pipe0);
  close(err_pipe0);

  if (result.timed_out) {
    if (child.status_fd >= 0) { close(child.status_fd); child.status_fd = -1; }
    int status = 0;
    waitpid(pid, &status, 0);
    result.out.clear();
    result.err = kTimeoutMessage;
    result.exit_code = -1;
    return result;
  }

  // Exit-code line ("X<code>\n") — normally already buffered when the pipes
  // EOF'd. Bounded by the REQUEST deadline, not a flat grace: user code that
  // closes its own stdio (both pipes EOF immediately) and keeps running must
  // still be limited by the execution timeout, and the fallback reap below
  // must never block on a live worker.
  std::string line;
  bool got_code = false;
  if (child.status_fd >= 0) {
    while (std::chrono::steady_clock::now() < deadline) {
      pollfd p{child.status_fd, POLLIN, 0};
      if (poll(&p, 1, 100) <= 0) continue;
      if (!(p.revents & (POLLIN | POLLHUP))) continue;
      char b;
      ssize_t n = read(child.status_fd, &b, 1);
      if (n <= 0) break;  // EOF: worker exited without reporting
      if (b == '\n') {
        got_code = !line.empty() && line[0] == 'X';
        break;
      }
      line.push_back(b);
    }
    close(child.status_fd);
    child.status_fd = -1;
  }
  if (got_code) {
    result.exit_code = atoi(line.c_str() + 1);
    std::thread([pid] {
      int status = 0;
      waitpid(pid, &status, 0);
    }).detach();
  } else {
    // No report: crashed worker (already dead — kill is a no-op) or stdio
    // closed by user code and the deadline elapsed (still running — kill
    // enforces the budget). Either way the reap below cannot block.
    const bool deadline_hit = std::chrono::steady_clock::now() >= deadline;
    kill(-pid, SIGKILL);
    int status = 0;
    waitpid(pid, &status, 0);
    if (deadline_hit) {
      result.out.clear();
      result.err = kTimeoutMessage;
      result.exit_code = -1;
      result.timed_out = true;
    } else if (WIFEXITED(status)) {
      result.exit_code = WEXITSTATUS(status);
    } else if (WIFSIGNALED(status)) {
      result.exit_code = -WTERMSIG(status);
    }
  }
  return result;
}

// argv: program + args. env: complete child environment.
inline RunResult run(const std::vector<std::string>& argv,
                     const std::map<std::string, std::string>& env,
                     const std::string& cwd,
                     double timeout_s) {
  return collect(spawn(argv, env, cwd), timeout_s);
}

}  // namespace subprocess
