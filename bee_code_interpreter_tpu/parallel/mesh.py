"""Mesh construction and sharding helpers.

Multi-host awareness: inside a pod group spawned by the scheduler
(services/kubernetes_code_executor.py), ``initialize_distributed()`` reads the
env the control plane baked into each worker (JAX_COORDINATOR_ADDRESS,
JAX_NUM_PROCESSES, JAX_PROCESS_ID) and brings up ``jax.distributed`` so
``jax.devices()`` spans every host of the slice; the mesh axes then map onto
ICI (within slice) / DCN (across slices) by device order, which is exactly the
layout XLA's collectives want.
"""

from __future__ import annotations

import dataclasses
import math
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("dp", "fsdp", "tp", "sp", "ep")


def initialize_distributed() -> bool:
    """Bring up jax.distributed from the pod-group env. Idempotent, no-op on
    single-process sandboxes."""
    num_processes = int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    if num_processes <= 1:
        return False
    # Idempotency must NOT be probed via jax.process_count(): that call
    # initializes the XLA backend, after which jax.distributed.initialize
    # refuses to run at all (caught by tests/test_multihost_distributed.py).
    # is_initialized() checks the coordination client without touching XLA —
    # but only newer jax exposes it publicly; otherwise probe the internal
    # coordination state the same way is_initialized() does.
    is_initialized = getattr(jax.distributed, "is_initialized", None)
    if is_initialized is not None:
        initialized = is_initialized()
    else:
        try:
            from jax._src.distributed import global_state

            initialized = global_state.client is not None
        except Exception:
            initialized = False
    if initialized:
        return True
    jax.distributed.initialize(
        coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
        num_processes=num_processes,
        process_id=int(os.environ.get("JAX_PROCESS_ID", "0")),
    )
    return True


def local_device_count() -> int:
    return len(jax.devices())


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A named assignment of the device grid: axis name -> size."""

    axes: dict[str, int]

    @property
    def n_devices(self) -> int:
        return math.prod(self.axes.values())

    def names(self) -> tuple[str, ...]:
        return tuple(self.axes.keys())


def make_mesh(axes: dict[str, int], devices=None) -> Mesh:
    """Build a Mesh with the given axis sizes over the (global) device list.

    Axis order follows the dict order; put the most communication-hungry axis
    (tp, then sp) last so it lands on adjacent devices — on TPU, adjacency in
    the device list means ICI neighbours, which is where all-gather/ppermute
    bandwidth lives.
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    plan = MeshPlan(dict(axes))
    if plan.n_devices > devices.size:
        raise ValueError(
            f"mesh plan {axes} needs {plan.n_devices} devices, have {devices.size}"
        )
    grid = devices[: plan.n_devices].reshape(tuple(axes.values()))
    return Mesh(grid, plan.names())


def auto_mesh(n_devices: int | None = None, *, sp: int = 1) -> Mesh:
    """A sensible default mesh: tp over adjacent chips, dp over the rest.

    ``sp`` > 1 carves a sequence-parallel axis for long-context work.
    """
    total = n_devices or local_device_count()
    if total % sp != 0:
        raise ValueError(f"{total} devices not divisible by sp={sp}")
    rest = total // sp
    # tp gets the largest power of two <= min(rest, 8) that divides rest
    tp = 1
    for candidate in (8, 4, 2):
        if rest % candidate == 0:
            tp = candidate
            break
    dp = rest // tp
    return make_mesh({"dp": dp, "sp": sp, "tp": tp})


def axis_size_compat(axis_name: str) -> int:
    """Static size of a named mesh axis from inside ``shard_map`` across
    jax versions: new jax has ``lax.axis_size``; on 0.4.x ``psum(1, axis)``
    constant-folds to the same static int."""
    from jax import lax

    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)


def pcast_compat(x, axes, *, to="varying"):
    """``lax.pcast`` across jax versions: marks a value varying over mesh
    axes for the vma type system. 0.4.x has no vma typing (and
    ``shard_map_compat`` runs it with the replication check off), so the
    cast is the identity there."""
    from jax import lax

    if hasattr(lax, "pcast"):
        return lax.pcast(x, axes, to=to)
    return x


def shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across jax versions: new jax exposes it at the top
    level with ``check_vma``; 0.4.x spells it ``jax.experimental.shard_map
    .shard_map``. Every shard_map call in models/ and parallel/ routes
    through here. On 0.4.x the replication checker (``check_rep``) predates
    vma typing and rejects valid ``lax.cond`` bodies (the ring/pipeline
    hop-skipping pattern) with "mismatched replication types", so the
    legacy path always disables it — ``check_vma`` only reaches a backend
    that can actually honor it."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Inputs: batch over dp, sequence over sp (if present)."""
    seq_axis = "sp" if "sp" in mesh.axis_names else None
    return NamedSharding(mesh, P("dp", seq_axis))


def batch_axes(mesh: Mesh | None) -> tuple[str, ...] | None:
    """The data-parallel-ish axes an activation batch dim shards over —
    the ONE policy for which mesh axes count as batch (models/transformer
    and models/vision both key off this)."""
    if mesh is None:
        return None
    axes = tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)
    return axes or None


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def mesh_shape_key(mesh: Mesh | None) -> str:
    """Stable string key for a mesh's axis sizes (``"dp=2,tp=4"``) — the
    per-shape bucket the step-telemetry aggregates group under. ``"1"``
    for no mesh (single-device serving)."""
    if mesh is None:
        return "1"
    key = ",".join(
        f"{name}={int(size)}"
        for name, size in zip(mesh.axis_names, mesh.devices.shape)
    )
    return key or "1"


def mesh_descriptor(mesh: Mesh | None) -> dict:
    """JSON-able description of a mesh for telemetry (observability's
    ``GET /v1/accelerator``): axis names/sizes, device counts, this
    process's position in the grid (the coordinates of its first local
    device per axis — dp/tp placement for multi-host step records), and
    the device platform. With no mesh, the single-device degradation:
    axes ``{}``, shape ``"1"``."""
    process_index = int(jax.process_index())
    if mesh is None:
        devices = jax.devices()
        return {
            "axes": {},
            "shape": "1",
            "n_devices": 1,
            "n_local_devices": 1,
            "process_index": process_index,
            "coords": {},
            "platform": devices[0].platform if devices else "unknown",
        }
    local = [d for d in mesh.devices.flat if d.process_index == process_index]
    coords: dict[str, int] = {}
    if local:
        idx = np.argwhere(mesh.devices == local[0])
        if idx.size:
            coords = {
                name: int(i) for name, i in zip(mesh.axis_names, idx[0])
            }
    return {
        "axes": {
            name: int(size)
            for name, size in zip(mesh.axis_names, mesh.devices.shape)
        },
        "shape": mesh_shape_key(mesh),
        "n_devices": int(mesh.devices.size),
        "n_local_devices": len(local),
        "process_index": process_index,
        "coords": coords,
        "platform": local[0].platform if local else "unknown",
    }
