"""GPipe-style pipeline parallelism over a ``pp`` mesh axis (shard_map).

TPU-first design: the stacked ``[n_layers, ...]`` parameter pytree (the same
layout ``models/transformer.py`` scans over) is sharded on its leading axis
over ``pp``, so each rank holds a contiguous block of layers. Microbatches
flow stage-to-stage with ``lax.ppermute`` over ICI in a static
``M + S - 1``-tick schedule (GPipe fill/drain bubbles) — one compiled
program, no data-dependent control flow.

Everything is differentiable (ppermute/psum transpose cleanly), so the same
primitive serves training: grads flow back through the pipeline in the
transposed schedule XLA derives automatically.

Stages may carry a scalar auxiliary loss (``with_aux`` — MoE load
balancing): per-tick contributions are masked to the ticks that process a
real microbatch (fill/drain bubbles run the layer body on garbage and must
not pollute the sum), summed across the pp ring, averaged over microbatches
and any data-parallel batch axes.

The reference has no parallelism at all (SURVEY.md §2); this module completes
the dp/fsdp/sp/tp/ep/pp axis set the framework's scheduler can provision.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from bee_code_interpreter_tpu.parallel.mesh import pcast_compat


def spmd_pipeline(
    stage_fn: Callable,
    layer_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    axis: str = "pp",
    n_microbatches: int,
    batch_axes: tuple[str, ...] = (),
    with_aux: bool = False,
):
    """Apply ``n_layers`` stacked layers to ``x`` pipelined over ``axis``.

    ``stage_fn(h, layer) -> h`` applies ONE layer (the per-step body the
    sequential implementation would ``lax.scan``); with ``with_aux`` it
    returns ``(h, aux)`` where ``aux`` is a scalar f32 per-layer loss term.
    ``layer_params`` is a pytree whose leaves have a leading ``[n_layers]``
    axis with ``n_layers % mesh.shape[axis] == 0``. ``x`` is ``[B, ...]``
    with ``B % n_microbatches == 0``; ``batch_axes`` optionally shards B over
    data-parallel mesh axes (composing dp x pp).

    Returns ``[B, ...]`` — identical to the sequential scan, modulo dtype
    rounding — or ``(out, aux)`` with ``with_aux``, where ``aux`` is the
    layer-summed loss term averaged over microbatches and ``batch_axes``
    (matching a sequential per-microbatch forward).
    """
    S = mesh.shape[axis]
    n_layers = jax.tree.leaves(layer_params)[0].shape[0]
    if n_layers % S != 0:
        raise ValueError(f"{n_layers} layers not divisible by {axis}={S}")
    B = x.shape[0]
    if B % n_microbatches != 0:
        raise ValueError(f"batch {B} not divisible by {n_microbatches} microbatches")
    M = n_microbatches
    mb = B // M
    xm = x.reshape(M, mb, *x.shape[1:])

    def scalar_zero(ref):
        # Scalar f32 zero for the aux accumulators. Under vma typing it must
        # be data-derived (plain constants are unvarying and scan/fori reject
        # the carry); on 0.4.x it must be a PLAIN constant — a data-derived
        # scalar is computed in grad's known sub-jaxpr and crosses into the
        # staged one as a float32[] residual whose {0: axes} name the legacy
        # transpose cannot check (no dim 0 to map).
        if hasattr(jax, "shard_map"):
            return (ref.reshape(-1)[0] * 0.0).astype(jnp.float32)
        return jnp.float32(0.0)

    def per_rank(local_params, xm):
        # local_params: [n_layers/S, ...] (this rank's layer block)
        # xm: [M, mb_local, ...] (microbatches; batch possibly dp-sharded)
        idx = lax.axis_index(axis)

        def apply_stage(h):
            def body(carry, layer):
                h, aux = carry
                if with_aux:
                    h, a = stage_fn(h, layer)
                    aux = aux + a.astype(jnp.float32)
                else:
                    h = stage_fn(h, layer)
                return (h, aux), None

            (h, aux), _ = lax.scan(body, (h, scalar_zero(h)), local_params)
            return h, aux

        def tick(t, carry):
            state, outputs, aux_acc = carry
            # stage 0 ingests microbatch t; later stages consume the
            # activation ppermute'd from their predecessor last tick
            feed = lax.dynamic_index_in_dim(
                xm, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            h = jnp.where(idx == 0, feed, state)
            y, aux_t = apply_stage(h)
            # this rank processes microbatch t - idx at tick t; outside
            # [0, M) it's a fill/drain bubble chewing on garbage — its aux
            # contribution must be masked out
            m_idx = t - idx
            valid = jnp.logical_and(m_idx >= 0, m_idx < M)
            aux_acc = aux_acc + jnp.where(valid, aux_t, 0.0)
            # the last stage completes microbatch t-(S-1) at tick t
            out_idx = jnp.clip(t - (S - 1), 0, M - 1)
            updated = lax.dynamic_update_index_in_dim(outputs, y, out_idx, 0)
            take = jnp.logical_and(idx == S - 1, t >= S - 1)
            outputs = jnp.where(take, updated, outputs)
            state = lax.ppermute(
                y, axis, [(i, (i + 1) % S) for i in range(S)]
            )
            return state, outputs, aux_acc

        # the loop body produces pp-varying values (axis_index branches), so
        # the initial carry must be marked varying too or scan rejects it
        state0 = pcast_compat(jnp.zeros_like(xm[0]), (axis,), to="varying")
        outputs0 = pcast_compat(jnp.zeros_like(xm), (axis,), to="varying")
        aux0 = pcast_compat(scalar_zero(xm), (axis,), to="varying")
        _, outputs, aux_acc = lax.fori_loop(
            0, M + S - 1, tick, (state0, outputs0, aux0)
        )
        # sum each rank's layer contributions across the ring, then average
        # over microbatches and data-parallel shards → replicated scalar
        aux = lax.psum(aux_acc, axis) / M
        if batch_axes:
            aux = lax.pmean(aux, batch_axes)
        # replicate the last stage's collected outputs across the pp ring
        out = lax.psum(
            jnp.where(idx == S - 1, outputs, jnp.zeros_like(outputs)), axis
        )
        return out, aux

    from bee_code_interpreter_tpu.parallel.mesh import shard_map_compat

    batch = batch_axes or None
    if hasattr(jax, "shard_map"):
        fn = shard_map_compat(
            per_rank,
            mesh=mesh,
            in_specs=(P(axis), P(None, batch)),
            out_specs=(P(None, batch), P()),
        )
        out, aux = fn(layer_params, xm)
    else:
        # 0.4.x shard_map cannot transpose (grad through) UNMAPPED
        # out_specs with the replication checker off: give each output a
        # leading pp-mapped dim instead — every rank returns the identical
        # psum'd value, the global array stacks S copies, and row 0 is the
        # answer. Same numerics, grad-safe on the legacy tracer.
        def per_rank_stacked(layer_params, xm):
            out, aux = per_rank(layer_params, xm)
            return out[None], aux[None]

        fn = shard_map_compat(
            per_rank_stacked,
            mesh=mesh,
            in_specs=(P(axis), P(None, batch)),
            out_specs=(P(axis, None, batch), P(axis)),
        )
        out, aux = fn(layer_params, xm)
        out, aux = out[0], aux[0]
    out = out.reshape(B, *x.shape[1:])
    if with_aux:
        return out, aux
    return out
