"""Device-mesh parallelism for sandboxed TPU workloads.

The reference has no tensor/data/sequence parallelism of any kind (SURVEY.md
§2 "Parallelism strategies": grep-verifiable absence of NCCL/MPI/collectives) —
its scale story is "many pods". The TPU build makes parallelism a first-class
sandbox capability: LLM-submitted code (and our bundled models) runs SPMD over
a `jax.sharding.Mesh` spanning the pod group's chips, with XLA collectives
riding ICI within a slice and DCN across slices.

Axis conventions (used by models/, ops/ and the flagship train step):

- ``dp``   data parallel (batch dimension)
- ``fsdp`` parameter sharding within data parallel (ZeRO-style)
- ``tp``   tensor parallel (Megatron column/row splits)
- ``sp``   sequence/context parallel (ring attention over ICI)
- ``ep``   expert parallel (MoE)
- ``pp``   pipeline parallel (GPipe microbatch schedule over ppermute)
"""

from bee_code_interpreter_tpu.parallel.mesh import (  # noqa: F401
    MeshPlan,
    auto_mesh,
    initialize_distributed,
    local_device_count,
    make_mesh,
)
from bee_code_interpreter_tpu.parallel.pipeline import (  # noqa: F401
    spmd_pipeline,
)
from bee_code_interpreter_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
)
from bee_code_interpreter_tpu.parallel.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_attention_sharded,
)
