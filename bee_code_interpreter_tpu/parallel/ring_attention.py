"""Ring attention: exact attention over sequences sharded across devices.

Long-context is first-class in this framework (SURVEY.md §5 "Long-context"):
a sequence of length L is sharded L/sp per device over the ``sp`` mesh axis,
and K/V blocks rotate around the ring via ``lax.ppermute`` (ICI
neighbour-to-neighbour — the cheapest collective on TPU) while each device
accumulates its queries' attention with a numerically-stable online softmax
(flash-attention style running max/normalizer). Peak memory per device is
O(L/sp · d); communication is sp-1 ppermute steps of the local K/V block,
fully overlappable with compute by XLA since each step's matmuls depend only
on the block already received.

Causality is handled per block pair: a device's query block q_idx attends to
rotating K/V blocks k_idx with full attention (k_idx < q_idx), triangular
masking (k_idx == q_idx), or is skipped entirely via lax.cond (k_idx > q_idx).

``ring_attention`` is the collective core, to be called *inside* shard_map
(models/transformer.py does this when the mesh has sp > 1);
``ring_attention_sharded`` wraps it for standalone use on a mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bee_code_interpreter_tpu.parallel.mesh import axis_size_compat


def _block_attend(q, k, v, m, l, o, sm_scale, mask):
    """One online-softmax accumulation step against a K/V block.

    q, o: [B, G, R, Lq, D]; k, v: [B, G, Lk, D]; m, l: [B, G, R, Lq, 1]
    (all float32 accumulators) — G = KV heads, R = query heads per KV head
    (R == 1 when not grouped-query; the einsums broadcast K/V over R, so the
    compact KV block is what rotates the ring). mask: [Lq, Lk] additive
    (-inf) or None.
    """
    scores = jnp.einsum(
        "bgrqd,bgkd->bgrqk", q, k, preferred_element_type=jnp.float32
    ) * sm_scale
    if mask is not None:
        scores = scores + mask
    block_max = jnp.max(scores, axis=-1, keepdims=True)  # [B,G,R,Lq,1]
    new_m = jnp.maximum(m, block_max)
    # rescale previous accumulator to the new max
    correction = jnp.exp(m - new_m)
    p = jnp.exp(scores - new_m)  # [B,G,R,Lq,Lk]
    new_l = l * correction + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bgrqk,bgkd->bgrqd", p, v.astype(jnp.float32))
    new_o = o * correction + pv
    return new_m, new_l, new_o


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    sm_scale: float | None = None,
    use_flash: bool | None = None,
    window: int | None = None,
) -> jax.Array:
    """Exact attention with K/V rotating around the ``axis_name`` ring.

    Shapes (per device): q: [B, H, L_local, D]; k, v: [B, KVH, L_local, D]
    with ``H % KVH == 0`` — grouped-query KV stays compact, so the ring
    rotates (and each hop's ppermute moves) KVH heads of K/V, not H. Returns
    [B, H, L_local, D] in q's dtype. Must run inside shard_map with
    ``axis_name`` bound.

    ``use_flash`` (default: on TPU) runs each hop through the Pallas flash
    kernel (ops/flash_attention.flash_attention_with_lse) and merges hops on
    their log-sum-exp — the MXU-tiled kernel replaces the jax-level einsum
    accumulation, and the same-block hop gets the kernel's causal
    block-skipping. Differentiable either way (the lse outputs carry real
    gradients; the kernel's VJP folds them into its delta shift).

    ``window`` (requires ``causal``) is sliding-window attention in GLOBAL
    positions: query at global position p sees keys in (p - window, p].
    Block structure per hop, with delta = (my_idx - k_idx) · L_local the
    query-block/key-block global offset: hops entirely below the window
    (delta ≥ window + L_local - 1) are skipped like future blocks — a
    window spanning w/L_local blocks turns the ring's O(sp) attended hops
    into O(w/L_local) while still paying sp-1 ppermutes; the own block uses
    the local causal+window mask; straddling hops mask rows to
    row - col < window - delta.
    """
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (sliding window)")
        if window < 1:
            # the einsum path would otherwise mask every row of the own
            # block and emit silent NaNs where the flash kernel raises
            raise ValueError(f"window must be >= 1, got {window}")
    if use_flash is None:
        from bee_code_interpreter_tpu.ops.flash_attention import uses_flash

        use_flash = uses_flash()
    if use_flash:
        return _ring_attention_flash(
            q, k, v, axis_name=axis_name, causal=causal, sm_scale=sm_scale,
            window=window,
        )
    orig_dtype = q.dtype
    B, H, Lq, D = q.shape
    KVH = k.shape[1]
    if H % KVH != 0:
        raise ValueError(f"n_heads {H} not a multiple of kv_heads {KVH}")
    Lk = k.shape[2]
    sm_scale = sm_scale if sm_scale is not None else D ** -0.5

    n = axis_size_compat(axis_name)
    my_idx = lax.axis_index(axis_name)

    qf = q.astype(jnp.float32).reshape(B, KVH, H // KVH, Lq, D)
    # derive accumulators from qf so they carry the same varying-axes type as
    # the data (shard_map vma typing: plain constants are "unvarying" and make
    # lax.cond branches disagree, whatever the surrounding mesh axes are)
    m0 = jnp.zeros_like(qf[..., :1]) - jnp.inf
    l0 = jnp.zeros_like(qf[..., :1])
    o0 = jnp.zeros_like(qf)

    causal_mask = None
    row = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
    col = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
    if causal:
        visible = row >= col
        if window is not None:  # own block: local offsets == global offsets
            visible &= row - col < window
        causal_mask = jnp.where(visible, 0.0, -jnp.inf).astype(jnp.float32)

    # send to next ring member; after `step` hops we hold block (my_idx - step)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def body(step, carry):
        m, l, o, k_blk, v_blk = carry
        k_idx = (my_idx - step) % n

        def attend(args):
            m, l, o = args
            if causal:
                # same block: triangular (+window) mask; earlier block: no
                # mask, or the window-straddle mask in global offsets
                def same_block(_):
                    return _block_attend(qf, k_blk, v_blk, m, l, o, sm_scale, causal_mask)

                def earlier_block(_):
                    mask = None
                    if window is not None:
                        delta = (my_idx - k_idx) * Lq  # global row - col shift
                        mask = jnp.where(
                            row - col < window - delta, 0.0, -jnp.inf
                        ).astype(jnp.float32)
                    return _block_attend(qf, k_blk, v_blk, m, l, o, sm_scale, mask)

                return lax.cond(k_idx == my_idx, same_block, earlier_block, None)
            return _block_attend(qf, k_blk, v_blk, m, l, o, sm_scale, None)

        def skip(args):
            return args

        if causal:
            skip_pred = k_idx > my_idx  # future block
            if window is not None:
                # entirely below the window: min global offset over the
                # block, (my_idx - k_idx)·L - (L-1), already >= window
                skip_pred |= (my_idx - k_idx) * Lq - (Lq - 1) >= window
            m, l, o = lax.cond(skip_pred, skip, attend, (m, l, o))
        else:
            m, l, o = attend((m, l, o))

        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return m, l, o, k_next, v_next

    m, l, o, _, _ = lax.fori_loop(0, n, body, (m0, l0, o0, k, v))
    # guard fully-masked rows (shouldn't occur: every query sees its own block)
    out = o / jnp.maximum(l, 1e-30)
    return out.reshape(B, H, Lq, D).astype(orig_dtype)


def _ring_attention_flash(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool,
    sm_scale: float | None,
    window: int | None = None,
) -> jax.Array:
    """Ring attention with the Pallas flash kernel per hop.

    Each hop computes a *normalized* attention block plus its log-sum-exp;
    hops merge in the standard lse algebra — running
    (m = max lse, s = Σ e^{lse−m}, o = Σ out·e^{lse−m}), final o/s. The
    causal structure is per block pair exactly as the einsum ring: earlier
    blocks attend fully (kernel causal=False), the own block triangularly
    (causal=True), later blocks are skipped. lax.cond keeps both kernel
    variants compiled once; the skip branch costs nothing but the carry.

    ``window`` rides the same structure: the own block uses the kernel's
    causal+window masking (static width — same offsets as local attention);
    hops fully inside the window run the plain non-causal kernel; hops the
    window boundary straddles (at most ceil(window/L_local) of them) run a
    jax-level masked softmax block — its mask width (window − delta) is
    device-dependent, which a static kernel parameter cannot express — and
    merge on lse exactly like kernel hops; hops entirely below the window
    are skipped like future blocks.
    """
    from bee_code_interpreter_tpu.ops.flash_attention import (
        flash_attention_with_lse,
    )

    orig_dtype = q.dtype
    B, H, Lq, D = q.shape
    KVH = k.shape[1]
    Lk = k.shape[2]
    n = axis_size_compat(axis_name)
    my_idx = lax.axis_index(axis_name)
    scale = sm_scale if sm_scale is not None else D ** -0.5

    NEG = jnp.float32(-1e30)  # not -inf: (-inf) - (-inf) would NaN the scale
    m0 = jnp.full((B, H, Lq, 1), NEG) + jnp.zeros_like(
        q[..., :1], dtype=jnp.float32
    )  # derive vma from q (shard_map typing), value NEG
    s0 = jnp.zeros_like(m0)
    o0 = jnp.zeros_like(q, dtype=jnp.float32)

    perm = [(i, (i + 1) % n) for i in range(n)]

    def boundary_block(k_idx, k_blk, v_blk):
        """One jax-level online-softmax block with the window-straddle mask
        (row − col < window − delta in global offsets), returned as
        (normalized out, lse) so it merges like a kernel hop. Fully-masked
        rows surface as lse ≈ −1e30 and merge to weight 0."""
        delta = (my_idx - k_idx) * Lq
        qf = q.astype(jnp.float32).reshape(B, KVH, H // KVH, Lq, D)
        scores = jnp.einsum(
            "bgrqd,bgkd->bgrqk", qf, k_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * scale
        row = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
        col = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
        scores = jnp.where(row - col < window - delta, scores, NEG)
        m_b = jnp.max(scores, axis=-1, keepdims=True)
        p = jnp.exp(scores - m_b)
        l_b = jnp.sum(p, axis=-1, keepdims=True)  # >= 1: some e^0 survives
        out = jnp.einsum(
            "bgrqk,bgkd->bgrqd", p, v_blk.astype(jnp.float32)
        ) / l_b
        lse = (m_b + jnp.log(l_b))[..., 0]  # [B, KVH, rep, Lq]
        return (
            out.reshape(B, H, Lq, D).astype(orig_dtype),
            lse.reshape(B, H, Lq),
        )

    def body(step, carry):
        m, s, o, k_blk, v_blk = carry
        k_idx = (my_idx - step) % n

        def attend(args):
            m, s, o = args

            def own_block(_):
                return flash_attention_with_lse(
                    q, k_blk, v_blk, True, sm_scale, window=window
                )

            def earlier_block(_):
                if window is None:
                    return flash_attention_with_lse(q, k_blk, v_blk, False, sm_scale)

                def full_block(_):
                    return flash_attention_with_lse(q, k_blk, v_blk, False, sm_scale)

                # fully visible iff even the largest offset, delta + (L-1),
                # is inside the window
                delta = (my_idx - k_idx) * Lq
                return lax.cond(
                    delta + Lq - 1 < window,
                    full_block,
                    lambda _: boundary_block(k_idx, k_blk, v_blk),
                    None,
                )

            if causal:
                out_blk, lse_blk = lax.cond(
                    k_idx == my_idx, own_block, earlier_block, None
                )
            else:
                out_blk, lse_blk = earlier_block(None)
            lse_blk = lse_blk[..., None]  # [B, H, Lq, 1]
            m_new = jnp.maximum(m, lse_blk)
            scale_old = jnp.exp(m - m_new)
            scale_blk = jnp.exp(lse_blk - m_new)
            o = o * scale_old + out_blk.astype(jnp.float32) * scale_blk
            s = s * scale_old + scale_blk
            return m_new, s, o

        def skip(args):
            return args

        if causal:
            skip_pred = k_idx > my_idx
            if window is not None:
                skip_pred |= (my_idx - k_idx) * Lq - (Lq - 1) >= window
            m, s, o = lax.cond(skip_pred, skip, attend, (m, s, o))
        else:
            m, s, o = attend((m, s, o))

        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return m, s, o, k_next, v_next

    m, s, o, _, _ = lax.fori_loop(0, n, body, (m0, s0, o0, k, v))
    out = o / jnp.maximum(s, 1e-30)
    return out.astype(orig_dtype)


def ring_attention_sharded(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    sm_scale: float | None = None,
    use_flash: bool | None = None,
    window: int | None = None,
) -> jax.Array:
    """Standalone entry: shards [B, H, L, D] inputs over ``axis_name`` on L
    and runs the ring. For use outside an existing shard_map context.
    ``sm_scale``/``use_flash``/``window`` forward to ``ring_attention`` (so
    the einsum fallback or the flash-hop path can be forced from here too)."""
    spec = P(None, None, axis_name, None)
    # the flash-hop path runs pallas_call under shard_map, which vma
    # checking cannot lower yet — disable the check exactly when that path
    # is taken (see models/transformer._attention)
    from bee_code_interpreter_tpu.ops.flash_attention import uses_flash

    flash = use_flash if use_flash is not None else uses_flash()
    from bee_code_interpreter_tpu.parallel.mesh import shard_map_compat

    fn = shard_map_compat(
        functools.partial(
            ring_attention, axis_name=axis_name, causal=causal,
            sm_scale=sm_scale, use_flash=use_flash, window=window,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=not flash,
    )
    return fn(q, k, v)


def reference_attention(q, k, v, *, causal=True, window=None):
    """O(L²)-memory reference for tests. Accepts grouped-query K/V
    ([B, KVH, L, D] with KVH dividing q's head count) by broadcasting;
    ``window`` masks keys more than window-1 positions behind the query
    (sliding-window attention; requires causal)."""
    if k.shape[1] != q.shape[1]:
        rep = q.shape[1] // k.shape[1]
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32),
        preferred_element_type=jnp.float32,
    ) * (q.shape[-1] ** -0.5)
    if window is not None and not causal:
        # mirror the flash kernel's validation: local_attention must behave
        # identically across platforms
        raise ValueError("window requires causal=True (sliding window)")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if causal:
        Lq, Lk = scores.shape[-2:]
        row = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 0)
        col = lax.broadcasted_iota(jnp.int32, (Lq, Lk), 1)
        mask = row >= col
        if window is not None:
            mask = mask & (row - col < window)
        scores = jnp.where(mask, scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v.astype(jnp.float32)).astype(q.dtype)
