"""Ulysses-style sequence parallelism: all-to-all head/sequence exchange.

The second canonical long-context strategy next to ring attention
(parallel/ring_attention.py). Where the ring keeps queries resident and
rotates K/V around the ``sp`` axis in sp-1 ppermute hops, Ulysses
(DeepSpeed-Ulysses / all-to-all context parallelism) re-shards once: an
``all_to_all`` turns the sequence-sharded [B, H, L/sp, D] activations into
head-sharded [B, H/sp, L, D] — each device then holds the FULL sequence for
a slice of heads, runs an ordinary (here: Pallas flash, GQA-native) local
attention, and a second all-to-all restores sequence sharding.

Trade-off, TPU terms: the ring moves (sp-1)/sp of K+V over neighbour ICI
links and needs the online-softmax accumulation; Ulysses moves q+k+v+out
once each through all-to-alls (cheap on a torus, but all-pairs) and runs the
unmodified single-device kernel — better when heads are plentiful and the
per-device sequence is short, and it composes with the flash kernel's causal
block-skipping, which the ring's per-hop blocks cannot exploit across
devices. sp must divide the head count (asserted); grouped-query K/V stays
compact when sp also divides kv_heads, otherwise it is broadcast up first.

``models/transformer.py`` selects between the two via
``TransformerConfig.sp_attention`` ("ring" | "ulysses").

The reference has no parallelism of any kind (SURVEY.md §2 "Parallelism
strategies"); this module is part of the framework's first-class
long-context story (SURVEY.md §5).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from bee_code_interpreter_tpu.parallel.mesh import axis_size_compat


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    local_attention=None,
    window: int | None = None,
    use_flash: bool | None = None,
) -> jax.Array:
    """All-to-all sequence-parallel attention. Must run inside shard_map.

    Per-device shapes: q [B, H, L/sp, D]; k, v [B, KVH, L/sp, D] with KVH ≤ H
    (grouped-query). Returns [B, H, L/sp, D]. ``local_attention(q, k, v)``
    runs on the gathered [B, heads/sp, L, D] blocks and defaults to the
    GQA-native Pallas flash kernel on TPU (reference attention elsewhere).

    ``window`` (sliding-window attention, requires ``causal``) falls out
    structurally: after the all-to-all each device holds the FULL sequence
    for its head slice, so global positions equal local positions and the
    ordinary local window mask is exact — no per-hop geometry like the ring.
    """
    if local_attention is None:
        # the shared ops-level dispatch: Pallas flash on TPU in either
        # causal mode (the gathered full sequence is exactly where O(L²)
        # reference memory would blow up), reference einsum off-TPU.
        # ``use_flash`` FORCES a path (mirroring ring_attention's knob —
        # True must actually run the kernel, not just flip check_vma):
        if use_flash is None:
            from bee_code_interpreter_tpu.ops.flash_attention import (
                local_attention as _dispatch,
            )

            local_attention = functools.partial(
                _dispatch, causal=causal, window=window
            )
        elif use_flash:
            from bee_code_interpreter_tpu.ops.flash_attention import (
                flash_attention,
            )

            local_attention = lambda q, k, v: flash_attention(  # noqa: E731
                q, k, v, causal, window=window
            )
        else:
            from bee_code_interpreter_tpu.parallel.ring_attention import (
                reference_attention,
            )

            local_attention = functools.partial(
                reference_attention, causal=causal, window=window
            )
    elif window is not None or use_flash is not None:
        raise ValueError(
            "window/use_flash with a custom local_attention: fold them into "
            "the callable instead (the default dispatch handles them)"
        )
    sp = axis_size_compat(axis_name)
    B, H, Lloc, D = q.shape
    KVH = k.shape[1]
    if H % sp != 0:
        raise ValueError(f"sp={sp} must divide n_heads {H} for ulysses")
    if KVH % sp != 0:
        # KV heads don't scatter over sp: broadcast up — only to
        # lcm(KVH, sp), the minimal multiple that shards evenly (both divide
        # H, so the lcm does too and group-major q→kv pairing is preserved —
        # same argument as the tp-lcm broadcast in models/transformer.py).
        # The ring path keeps KV fully compact; prefer ring when KVH < sp.
        rep = math.lcm(KVH, sp) // KVH
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)

    # head-scatter / sequence-gather: [B, h, L/sp, D] -> [B, h/sp, L, D].
    # Sequence blocks concatenate in sp-rank order — the same contiguous
    # layout the sequence sharding put them in.
    a2a = functools.partial(
        lax.all_to_all, axis_name=axis_name, split_axis=1, concat_axis=2,
        tiled=True,
    )
    out = local_attention(a2a(q), a2a(k), a2a(v))  # [B, H/sp, L, D]
    # inverse exchange: sequence-scatter / head-gather
    return lax.all_to_all(
        out, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )


def ulysses_attention_sharded(
    mesh: Mesh,
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    use_flash: bool | None = None,
    window: int | None = None,
) -> jax.Array:
    """Standalone entry: shards [B, H, L, D] inputs over ``axis_name`` on L
    and runs the exchange. For use outside an existing shard_map context.

    ``use_flash`` mirrors ring_attention_sharded: when the local attention
    will dispatch to the Pallas flash kernel (the TPU default), the vma
    checker must be disabled — pallas_call cannot lower under it (ADVICE r3:
    without this the standalone entry failed on real TPU while CPU tests
    passed, because uses_flash() is false off-TPU).
    """
    from bee_code_interpreter_tpu.ops.flash_attention import uses_flash

    flash = use_flash if use_flash is not None else uses_flash()
    spec = P(None, None, axis_name, None)
    from bee_code_interpreter_tpu.parallel.mesh import shard_map_compat

    fn = shard_map_compat(
        functools.partial(
            ulysses_attention, axis_name=axis_name, causal=causal,
            window=window, use_flash=use_flash,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=not flash,
    )
    return fn(q, k, v)
