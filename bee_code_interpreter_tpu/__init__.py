"""bee-code-interpreter-tpu: a TPU-native sandboxed code-execution service for LLM agents.

A from-scratch rebuild of the capability surface of i-am-bee/bee-code-interpreter
(reference: /root/reference) designed TPU-first:

- Control plane (this package): asyncio service exposing ``POST /v1/execute``,
  ``/v1/parse-custom-tool``, ``/v1/execute-custom-tool`` over HTTP (aiohttp) and the
  equivalent 3 RPCs over gRPC, maintaining a warm pool of single-use sandbox pods.
  (Reference layer map: SURVEY.md §1; reference API at
  src/code_interpreter/services/http_server.py:89-160.)
- In-sandbox executor: a native C++ HTTP server (``executor/``) replacing the
  reference's Rust server (executor/server.rs:29-201) — workspace file I/O,
  auto-dependency-install, subprocess execution with timeout, changed-file scan —
  extended to own the pod's TPU chips and export ICI/DCN topology env.
- TPU sandbox runtime (``runtime/``, ``models/``, ``ops/``, ``parallel/``): the
  JAX/XLA-native library available to LLM-submitted code inside the sandbox —
  transparent numpy→XLA rerouting, device meshes, sharded training steps, ring
  attention for long sequences, and Pallas kernels for the hot ops.
"""

__version__ = "0.1.0"
