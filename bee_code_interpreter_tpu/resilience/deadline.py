"""Request deadline propagation.

A ``Deadline`` is created once at the API edge (HTTP or gRPC handler) and
passed down the whole vertical stack — pod-group spawn, workspace upload,
``POST /execute``, download — so every downstream operation budgets against
*the same clock* instead of each holding an independent fixed timeout. The
classic failure this prevents: a 60 s pod spawn followed by a 60 s execute
"succeeding" 100 s after the client gave up at 30 s.

The clock is injectable (``clock=time.monotonic`` by default) so breaker and
deadline unit tests are deterministic. ``run()`` — the hard wall-clock bound —
always uses the event loop's real clock, because it must actually cancel work.
"""

from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, TypeVar

T = TypeVar("T")


class DeadlineExceeded(Exception):
    """The edge deadline for this request ran out.

    Deliberately NOT a ``RuntimeError``: retry policies retry RuntimeErrors
    (spawn) and transient sandbox errors, and a blown deadline must never be
    retried — there is no budget left to retry into.
    """

    def __init__(self, what: str = "request") -> None:
        super().__init__(f"deadline exceeded during {what}")
        self.what = what


class Deadline:
    """Monotonic absolute deadline with a shrinking ``remaining()`` budget."""

    def __init__(
        self, seconds: float, clock: Callable[[], float] = time.monotonic
    ) -> None:
        self.budget_s = seconds
        self._clock = clock
        self._expires_at = clock() + seconds

    @classmethod
    def after(
        cls, seconds: float, clock: Callable[[], float] = time.monotonic
    ) -> "Deadline":
        return cls(seconds, clock=clock)

    def remaining(self) -> float:
        """Seconds left; never negative."""
        return max(0.0, self._expires_at - self._clock())

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "request") -> None:
        """Raise ``DeadlineExceeded`` if the budget is gone (pre-flight gate:
        don't start an operation there is no time to finish)."""
        if self.expired:
            raise DeadlineExceeded(what)

    def clamp(self, timeout_s: float | None) -> float:
        """An operation-local timeout, never past the deadline."""
        remaining = self.remaining()
        if timeout_s is None:
            return remaining
        return min(timeout_s, remaining)

    async def run(self, awaitable: Awaitable[T], what: str = "request") -> T:
        """Await with a hard bound at the deadline; the awaited work is
        cancelled (cleanup handlers run) and ``DeadlineExceeded`` raised when
        the budget runs out."""
        if self.expired:
            close = getattr(awaitable, "close", None)
            if close is not None:
                close()  # never-started coroutine: don't leave it dangling
            raise DeadlineExceeded(what)
        try:
            return await asyncio.wait_for(awaitable, timeout=self.remaining())
        except (asyncio.TimeoutError, TimeoutError) as e:
            raise DeadlineExceeded(what) from e

    def __repr__(self) -> str:  # debugging/log ergonomics
        return f"Deadline(remaining={self.remaining():.3f}s of {self.budget_s:.3f}s)"
