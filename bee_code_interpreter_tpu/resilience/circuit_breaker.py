"""Failure-rate circuit breaker: closed → open → half-open.

Wraps the two places the Kubernetes backend can melt down under load — pod
group spawn (apiserver / scheduler trouble) and the executor HTTP data plane
(pod network / sandbox trouble). While OPEN, calls fail immediately with
``BreakerOpenError`` carrying a retry-after hint, instead of queueing behind
a backend that is down; the service layer uses that signal to degrade to the
local executor (``APP_FALLBACK_TO_LOCAL``).

State machine:

- CLOSED: outcomes are recorded in a sliding window of the last ``window``
  calls. Once at least ``min_calls`` outcomes exist and the failure rate
  reaches ``failure_rate_threshold``, the breaker trips OPEN.
- OPEN: every call is rejected until ``cooldown_s`` elapses.
- HALF_OPEN: up to ``half_open_max_calls`` concurrent probes are let through.
  A probe success closes the breaker (window reset); a failure re-opens it
  and restarts the cooldown.

The clock is injectable for deterministic tests (``tests/chaos.ManualClock``).
"""

from __future__ import annotations

import asyncio
import enum
import time
from collections import deque
from contextlib import asynccontextmanager
from typing import Callable

from bee_code_interpreter_tpu.resilience.deadline import DeadlineExceeded


class BreakerState(enum.IntEnum):
    CLOSED = 0
    OPEN = 1
    HALF_OPEN = 2


class BreakerOpenError(Exception):
    """Rejected fast because the breaker is open.

    Not a ``RuntimeError`` on purpose: retry policies must never retry it
    (the whole point is to stop hammering a down backend), and the service
    layer catches it specifically to route to the fallback executor.
    """

    def __init__(self, name: str, retry_after_s: float) -> None:
        super().__init__(
            f"circuit breaker {name!r} is open; retry in {retry_after_s:.1f}s"
        )
        self.name = name
        self.retry_after_s = max(0.0, retry_after_s)


class CircuitBreaker:
    def __init__(
        self,
        name: str,
        *,
        window: int = 10,
        failure_rate_threshold: float = 0.5,
        min_calls: int = 4,
        cooldown_s: float = 30.0,
        half_open_max_calls: int = 1,
        clock: Callable[[], float] = time.monotonic,
        is_failure: Callable[[BaseException], bool] | None = None,
        on_transition: Callable[[str, BreakerState], None] | None = None,
    ) -> None:
        self.name = name
        self._window: deque[bool] = deque(maxlen=max(1, window))
        self._failure_rate_threshold = failure_rate_threshold
        self._min_calls = max(1, min_calls)
        self._cooldown_s = cooldown_s
        self._half_open_max_calls = max(1, half_open_max_calls)
        self._clock = clock
        self._is_failure = is_failure or (lambda e: True)
        # Public so a host (e.g. KubernetesCodeExecutor) can attach its metrics
        # recorder to an externally constructed breaker.
        self.on_transition = on_transition
        self._state = BreakerState.CLOSED
        self._opened_at = 0.0
        self._half_open_inflight = 0

    # ------------------------------------------------------------------ state

    @property
    def state(self) -> BreakerState:
        """Effective state (reports HALF_OPEN once the cooldown has elapsed,
        without waiting for the next call to observe it)."""
        if (
            self._state is BreakerState.OPEN
            and self._clock() >= self._opened_at + self._cooldown_s
        ):
            return BreakerState.HALF_OPEN
        return self._state

    def _transition(self, new: BreakerState) -> None:
        if new is self._state:
            return
        self._state = new
        if self.on_transition is not None:
            self.on_transition(self.name, new)

    def _trip(self) -> None:
        self._opened_at = self._clock()
        self._window.clear()
        self._half_open_inflight = 0
        self._transition(BreakerState.OPEN)

    # ------------------------------------------------------------------ calls

    def before_call(self) -> None:
        """Gate a call; raises ``BreakerOpenError`` when it must not proceed.
        In half-open state this reserves one of the probe slots."""
        if self._state is BreakerState.OPEN:
            now = self._clock()
            reopen_at = self._opened_at + self._cooldown_s
            if now < reopen_at:
                raise BreakerOpenError(self.name, reopen_at - now)
            self._half_open_inflight = 0
            self._transition(BreakerState.HALF_OPEN)
        if self._state is BreakerState.HALF_OPEN:
            if self._half_open_inflight >= self._half_open_max_calls:
                raise BreakerOpenError(self.name, self._cooldown_s)
            self._half_open_inflight += 1

    def record_success(self) -> None:
        if self._state is BreakerState.HALF_OPEN:
            self._half_open_inflight = max(0, self._half_open_inflight - 1)
            self._window.clear()
            self._transition(BreakerState.CLOSED)
            return
        self._window.append(True)

    def record_failure(self) -> None:
        if self._state is BreakerState.HALF_OPEN:
            self._half_open_inflight = max(0, self._half_open_inflight - 1)
            self._trip()
            return
        if self._state is BreakerState.OPEN:
            return
        self._window.append(False)
        if len(self._window) >= self._min_calls:
            failures = sum(1 for ok in self._window if not ok)
            if failures / len(self._window) >= self._failure_rate_threshold:
                self._trip()

    def record_abandoned(self) -> None:
        """A call ended without a verdict on backend health (e.g. the client
        disconnected): release any half-open probe slot, record nothing."""
        if self._state is BreakerState.HALF_OPEN:
            self._half_open_inflight = max(0, self._half_open_inflight - 1)

    @asynccontextmanager
    async def guard(self):
        """``async with breaker.guard(): ...`` — gates the call and records
        its outcome. Exceptions the ``is_failure`` predicate rejects (e.g. a
        4xx ``SandboxFatalError``: the backend *answered*) count as successes
        for breaker purposes. ``CancelledError`` and ``DeadlineExceeded`` are
        client-driven — the caller's budget ran out, which says nothing about
        backend health — and count as neither: a few impatient clients must
        not trip the breaker for everyone. Genuine backend hangs still count,
        because they blow the *config-level* bounds (pod_ready_timeout_s /
        executor_http_timeout_s) and surface as transient/runtime errors."""
        self.before_call()
        try:
            yield
        except BaseException as e:
            if isinstance(e, (asyncio.CancelledError, DeadlineExceeded)):
                self.record_abandoned()
            elif self._is_failure(e):
                self.record_failure()
            else:
                self.record_success()
            raise
        else:
            self.record_success()
