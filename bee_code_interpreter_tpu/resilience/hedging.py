"""Tail-tolerant execution: transparent replay and opt-in hedging.

Both are safe for the same structural reason: sandboxes are **single-use**
and the workspace is a **content-addressed snapshot** — every attempt
restores the identical input state on a fresh sandbox, so re-running is a
pure re-play of the request, never a resume of half-mutated state. The
caveat is the one the retry layer already documents (docs/resilience.md):
user code with non-idempotent *external* side effects can run more than
once; such workloads should keep replay at 0 and hedging off.

- **Replay**: an execution whose sandbox died mid-flight (the backend
  surfaced ``SandboxTransientError`` after its own retry budget) is
  re-launched on a fresh sandbox instead of surfacing a 500 — immediately,
  with no backoff (the sandbox is *gone*, not overloaded) — bounded by
  ``APP_EXECUTION_REPLAY_MAX`` and the request deadline. Counted in
  ``bci_execution_replays_total``.

- **Hedging** (``APP_HEDGE_DELAY_S``, opt-in): when the primary attempt
  has not finished after the hedge delay, the same request is launched on
  a second warm sandbox; the first result wins and the loser is cancelled
  (its sandbox torn down by the pool's single-use contract). Converts
  p99 stragglers (slow pod, cold cache, flaky node) into ~p50 at the cost
  of duplicate work. Counted in ``bci_hedge_total{outcome}`` — outcomes
  ``primary_won`` / ``hedge_won`` / ``both_failed``, incremented only when
  a hedge actually launched.
"""

from __future__ import annotations

import asyncio
import logging

from bee_code_interpreter_tpu.resilience.deadline import Deadline
from bee_code_interpreter_tpu.resilience.errors import SandboxTransientError
from bee_code_interpreter_tpu.services.code_executor import Result
from bee_code_interpreter_tpu.utils.validation import AbsolutePath, Hash

logger = logging.getLogger(__name__)


def _annotate_root(key: str, value: str) -> None:
    """Stamp a replay/hedge outcome on the ambient request's root span so
    the flight recorder's wide event carries it; a no-op off the request
    path (lazy import: resilience loads before observability finishes)."""
    from bee_code_interpreter_tpu.observability.tracing import current_trace

    trace = current_trace()
    if trace is not None:
        trace.root.attributes[key] = value


class HedgingExecutor:
    """Replay + hedge front over a pool executor backend.

    Sits *inside* the resilience front (``ResilientCodeExecutor`` wraps
    this, this wraps the pool backend): breaker-open rejections pass
    through untouched for the fallback router, and the edge deadline's
    hard wall-clock bound covers replays and hedges alike.
    """

    def __init__(
        self,
        primary,
        *,
        replay_max: int = 1,
        hedge_delay_s: float | None = None,
        metrics=None,
    ) -> None:
        self.primary = primary
        self._replay_max = max(0, replay_max)
        self._hedge_delay_s = (
            hedge_delay_s if hedge_delay_s is not None and hedge_delay_s > 0 else None
        )
        self._replays_total = (
            metrics.counter(
                "bci_execution_replays_total",
                "Executions replayed on a fresh sandbox after the previous one died mid-flight",
            )
            if metrics is not None
            else None
        )
        self._hedge_total = (
            metrics.counter(
                "bci_hedge_total",
                "Hedged executions by outcome (counted when a hedge launched)",
            )
            if metrics is not None
            else None
        )

    @property
    def journal(self):
        """The backend's fleet journal (journal-discovery passthrough)."""
        return getattr(self.primary, "journal", None)

    async def execute(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        deadline: Deadline | None = None,
    ) -> Result:
        replays = 0
        while True:
            try:
                return await self._execute_maybe_hedged(
                    source_code, files, env, timeout_s, deadline
                )
            except SandboxTransientError as e:
                if replays >= self._replay_max:
                    raise
                if deadline is not None and deadline.expired:
                    raise
                replays += 1
                if self._replays_total is not None:
                    self._replays_total.inc()
                _annotate_root("replays", str(replays))
                logger.warning(
                    "Execution attempt died mid-flight (%s); replaying on a "
                    "fresh sandbox (replay %d/%d)",
                    e,
                    replays,
                    self._replay_max,
                )

    async def _execute_maybe_hedged(
        self, source_code, files, env, timeout_s, deadline
    ) -> Result:
        if self._hedge_delay_s is None:
            return await self.primary.execute(
                source_code=source_code,
                files=files,
                env=env,
                timeout_s=timeout_s,
                deadline=deadline,
            )

        def attempt() -> asyncio.Task:
            return asyncio.ensure_future(
                self.primary.execute(
                    source_code=source_code,
                    files=files,
                    env=env,
                    timeout_s=timeout_s,
                    deadline=deadline,
                )
            )

        names: dict[asyncio.Task, str] = {attempt(): "primary"}
        try:
            primary_task = next(iter(names))
            delay = self._hedge_delay_s
            if deadline is not None and deadline.remaining() <= delay:
                # No budget for a useful hedge: a second attempt bounded by
                # the same expiring deadline can never win — don't burn a
                # second warm sandbox on a doomed request.
                return await primary_task
            done, _ = await asyncio.wait({primary_task}, timeout=delay)
            if done:
                return primary_task.result()
            names[attempt()] = "hedge"
            pending = set(names)
            first_error: BaseException | None = None
            while pending:
                done, pending = await asyncio.wait(
                    pending, return_when=asyncio.FIRST_COMPLETED
                )
                for task in done:
                    if task.exception() is None:
                        for loser in pending:
                            await self._cancel(loser)
                        outcome = f"{names[task]}_won"
                        if self._hedge_total is not None:
                            self._hedge_total.inc(outcome=outcome)
                        _annotate_root("hedge", outcome)
                        logger.info("Hedged execution resolved: %s", outcome)
                        return task.result()
                    if first_error is None:
                        first_error = task.exception()
            if self._hedge_total is not None:
                self._hedge_total.inc(outcome="both_failed")
            _annotate_root("hedge", "both_failed")
            assert first_error is not None
            raise first_error
        except asyncio.CancelledError:
            # Our caller was cancelled (deadline/shutdown): neither attempt
            # may keep holding a sandbox.
            for task in names:
                if not task.done():
                    await self._cancel(task)
            raise

    @staticmethod
    async def _cancel(task: asyncio.Task) -> None:
        task.cancel()
        try:
            await task
        except BaseException:
            pass
