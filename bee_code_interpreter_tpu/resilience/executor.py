"""Deadline-enforcing, breaker-aware front over the primary code executor.

This is the graceful-degradation seam: when the Kubernetes backend's spawn
(or data-plane) breaker is open and a local fallback executor is configured
(``APP_FALLBACK_TO_LOCAL=true``), requests are served by the local
native-process path instead of failing — degraded isolation, preserved
availability. The edge deadline is also enforced here as a *hard* wall-clock
bound (``Deadline.run``): downstream code already budgets each call with
``remaining()``, and this wrapper guarantees the sum cannot drift past the
edge promise even through retries and teardown.
"""

from __future__ import annotations

import logging

from bee_code_interpreter_tpu.resilience.circuit_breaker import BreakerOpenError
from bee_code_interpreter_tpu.resilience.deadline import Deadline
from bee_code_interpreter_tpu.services.code_executor import Result
from bee_code_interpreter_tpu.utils.validation import AbsolutePath, Hash

logger = logging.getLogger(__name__)


class ResilientCodeExecutor:
    def __init__(
        self,
        primary,
        fallback=None,
        metrics=None,
        fallback_breakers: tuple[str, ...] = ("k8s-spawn",),
    ) -> None:
        self.primary = primary
        self.fallback = fallback
        # Only breakers that reject BEFORE user code is dispatched are safe
        # to fall back from: the spawn breaker fires during sandbox
        # acquisition. The data-plane breaker can open mid-request — after
        # /execute already ran on the pod — and re-running side-effectful
        # user code locally would execute it twice.
        self._fallback_breakers = frozenset(fallback_breakers)
        self._fallback_total = None
        if metrics is not None:
            self._fallback_total = metrics.counter(
                "bci_executor_fallback_total",
                "Executions routed to the local fallback while a breaker was open",
            )

    async def execute(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        deadline: Deadline | None = None,
    ) -> Result:
        # Blown deadlines are counted once, at the API edge (the
        # bci_deadline_exceeded_total{transport=...} counter) — not here too.
        inner = self._execute(source_code, files, env, timeout_s, deadline)
        if deadline is None:
            return await inner
        return await deadline.run(inner, what="execute")

    async def _execute(self, source_code, files, env, timeout_s, deadline) -> Result:
        try:
            return await self.primary.execute(
                source_code=source_code,
                files=files,
                env=env,
                timeout_s=timeout_s,
                deadline=deadline,
            )
        except BreakerOpenError as e:
            if self.fallback is None or e.name not in self._fallback_breakers:
                raise
            logger.warning(
                "Breaker %r open (%s); degrading to the local fallback executor",
                e.name, e,
            )
            if self._fallback_total is not None:
                self._fallback_total.inc()
            return await self.fallback.execute(
                source_code=source_code,
                files=files,
                env=env,
                timeout_s=timeout_s,
                deadline=deadline,
            )
