"""Resilience subsystem: deadline propagation, retries, circuit breaking,
admission control, and graceful degradation (see docs/resilience.md).

The API edge creates a ``Deadline`` per request and passes it down; executors
retry transient failures under ``RetryPolicy``; ``CircuitBreaker`` trips on
sustained failure of pod spawn or the executor data plane; the
``AdmissionController`` sheds load once in-flight + queue bounds are hit; and
``ResilientCodeExecutor`` routes around an open breaker to the local fallback.
"""

from bee_code_interpreter_tpu.resilience.admission import (
    AdmissionController,
    AdmissionRejected,
)
from bee_code_interpreter_tpu.resilience.autoscaler import (
    PoolAutoscaler,
    autoscale_snapshot,
)
from bee_code_interpreter_tpu.resilience.circuit_breaker import (
    BreakerOpenError,
    BreakerState,
    CircuitBreaker,
)
from bee_code_interpreter_tpu.resilience.deadline import Deadline, DeadlineExceeded
from bee_code_interpreter_tpu.resilience.errors import (
    SandboxError,
    SandboxFatalError,
    SandboxTransientError,
    classify_http_status,
)
from bee_code_interpreter_tpu.resilience.executor import ResilientCodeExecutor
from bee_code_interpreter_tpu.resilience.hedging import HedgingExecutor
from bee_code_interpreter_tpu.resilience.retry import RetryPolicy, retryable
from bee_code_interpreter_tpu.resilience.supervisor import (
    DrainController,
    InflightExecution,
    InflightRegistry,
    PoolSupervisor,
    journal_sandbox_teardown,
)

__all__ = [
    "AdmissionController",
    "AdmissionRejected",
    "BreakerOpenError",
    "BreakerState",
    "CircuitBreaker",
    "Deadline",
    "DeadlineExceeded",
    "DrainController",
    "HedgingExecutor",
    "InflightExecution",
    "InflightRegistry",
    "PoolAutoscaler",
    "PoolSupervisor",
    "autoscale_snapshot",
    "ResilientCodeExecutor",
    "RetryPolicy",
    "SandboxError",
    "SandboxFatalError",
    "SandboxTransientError",
    "classify_http_status",
    "journal_sandbox_teardown",
    "retryable",
]
