"""Typed sandbox data-plane errors.

The reference (and our seed) raised bare ``RuntimeError`` for every executor
HTTP failure, which made its retry layer re-attempt non-retryable failures —
a sandbox answering 400 is *working* and will answer 400 again. These classes
split the space the way every production RPC stack does:

- ``SandboxTransientError`` — the backend may recover: 5xx responses, request
  timeouts, connection resets/refusals. Worth retrying — with the caveat that
  retrying a failure observed AFTER ``/execute`` was dispatched gives
  at-least-once execution semantics for the user's code (the reference
  behaved the same way; see docs/resilience.md).
- ``SandboxFatalError`` — the backend answered authoritatively with a client
  error (4xx) or an otherwise non-retryable response. Retrying burns budget
  and latency for an identical answer.

Both subclass ``RuntimeError`` so pre-existing ``except RuntimeError`` call
sites keep working; retry policies narrow on the transient subclass only.
"""

from __future__ import annotations


class SandboxError(RuntimeError):
    """Base class for executor data-plane failures."""


class SandboxTransientError(SandboxError):
    """Retryable failure: 5xx, timeout, connect error, connection reset."""


class SandboxFatalError(SandboxError):
    """Non-retryable failure: the sandbox answered, and the answer is no."""


def classify_http_status(status: int, what: str) -> "SandboxError":
    """Build the right error for a non-success executor HTTP status."""
    if status >= 500:
        return SandboxTransientError(f"{what}: HTTP {status}")
    return SandboxFatalError(f"{what}: HTTP {status}")
