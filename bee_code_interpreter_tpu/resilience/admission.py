"""Admission control: bounded in-flight work + bounded, deadline-aware queue.

The seed accepted every request and let them pile up inside the executor
(unbounded queueing → every client times out). This gate enforces the
standard load-shedding contract instead:

- up to ``max_in_flight`` requests execute concurrently;
- up to ``max_queue`` more wait, each bounded by its own request deadline
  (or ``default_wait_s`` when the edge didn't attach one);
- everything beyond that — and any waiter whose deadline would expire in the
  queue — is shed *immediately* with ``AdmissionRejected`` carrying a
  retry-after hint. The HTTP edge maps this to 429 + ``Retry-After``; the
  gRPC edge to ``RESOURCE_EXHAUSTED``. Nothing ever hangs.

Slot handoff is direct: a releasing request transfers its slot to the oldest
live waiter without decrementing the in-flight count, so a burst can never
overshoot ``max_in_flight``.

Cost-aware mode (``APP_ADMISSION_COST_AWARE``, default off): the edge
analyzer's ``cost_class`` hint (docs/analysis.md "Cost classes") becomes a
priority signal — executions classified ``io_heavy``/``install_heavy``
additionally pass :meth:`AdmissionController.heavy_lane`, a bounded
secondary gate (half of ``max_in_flight``), after analysis and before the
sandbox is touched. A saturated heavy lane sheds immediately
(``reason="heavy_lane"``) instead of letting a burst of slow expensive work
occupy every slot cheap interactive turns need.
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from contextlib import asynccontextmanager

from bee_code_interpreter_tpu.observability import span as trace_span

# Mirror of analysis.policy.HEAVY_COST_CLASSES, spelled here so resilience/
# never imports the analysis layer (the hint arrives as a plain string).
_HEAVY_COST_CLASSES = frozenset({"io_heavy", "install_heavy"})


class AdmissionRejected(Exception):
    def __init__(self, reason: str, retry_after_s: float) -> None:
        super().__init__(f"request shed: {reason} (retry in {retry_after_s:.1f}s)")
        self.reason = reason
        self.retry_after_s = max(0.0, retry_after_s)


class AdmissionController:
    def __init__(
        self,
        max_in_flight: int = 64,
        max_queue: int = 128,
        default_wait_s: float = 30.0,
        retry_after_s: float = 1.0,
        metrics=None,
        demand=None,  # observability.DemandTracker (capacity telemetry)
        cost_aware: bool = False,
        heavy_max_in_flight: int | None = None,
    ) -> None:
        self._max_in_flight = max(1, max_in_flight)
        self._max_queue = max(0, max_queue)
        self._default_wait_s = default_wait_s
        self._retry_after_s = retry_after_s
        self._in_flight = 0
        self._cost_aware = cost_aware
        self._heavy_max = (
            heavy_max_in_flight
            if heavy_max_in_flight is not None
            else max(1, self._max_in_flight // 2)
        )
        self._heavy_in_flight = 0
        # The gate is the ONE chokepoint every sandbox-bound request on
        # either transport passes, which makes it the natural demand
        # sensor: arrivals, sheds, queue waits, and the in-flight
        # high-water feed the capacity tracker here (docs/autoscaling.md).
        self._demand = demand
        self._waiters: deque[asyncio.Future] = deque()
        self._shed_total = None
        self._admitted_total = None
        if metrics is not None:
            self._shed_total = metrics.counter(
                "bci_admission_shed_total", "Requests shed by admission control"
            )
            self._admitted_total = metrics.counter(
                "bci_admission_admitted_total", "Requests admitted past the gate"
            )
            metrics.gauge(
                "bci_admission_in_flight",
                "Requests currently executing past admission",
                lambda: self._in_flight,
            )
            metrics.gauge(
                "bci_admission_queue_depth",
                "Requests waiting in the admission queue",
                lambda: len(self._waiters),
            )
            metrics.gauge(
                "bci_admission_heavy_in_flight",
                "Cost-classified heavy executions currently in the heavy lane",
                lambda: self._heavy_in_flight,
            )

    @property
    def in_flight(self) -> int:
        return self._in_flight

    @property
    def queue_depth(self) -> int:
        return len(self._waiters)

    @property
    def heavy_in_flight(self) -> int:
        return self._heavy_in_flight

    @asynccontextmanager
    async def heavy_lane(self, cost_class: str | None):
        """The cost-aware secondary gate (docs/analysis.md "Cost classes").

        A no-op unless cost-aware mode is on AND the edge analyzer
        classified this execution heavy (io_heavy/install_heavy). It runs
        AFTER :meth:`admit` (analysis needs the request body, which is only
        read once admitted), so a heavy-lane shed releases an admission
        slot immediately — the bounded cost of classifying is one queue
        check, never a sandbox checkout."""
        if not self._cost_aware or cost_class not in _HEAVY_COST_CLASSES:
            yield
            return
        if self._heavy_in_flight >= self._heavy_max:
            self._shed("heavy_lane")
        self._heavy_in_flight += 1
        try:
            yield
        finally:
            self._heavy_in_flight -= 1

    def _shed(self, reason: str) -> None:
        if self._shed_total is not None:
            self._shed_total.inc(reason=reason)
        if self._demand is not None:
            self._demand.record_shed()
        raise AdmissionRejected(reason, self._retry_after_s)

    @asynccontextmanager
    async def admit(self, deadline=None):
        # The trace stage span covers ONLY the acquire (the queue wait a
        # slow request may have paid); the admitted body's time belongs to
        # its own stages. One instrumentation site serves every edge.
        if self._demand is not None:
            self._demand.record_arrival()
        wait_start = time.monotonic()
        with trace_span("admission"):
            await self._acquire(deadline)
        if self._demand is not None:
            self._demand.record_admitted(
                queue_wait_s=time.monotonic() - wait_start,
                in_flight=self._in_flight,
            )
        try:
            yield
        finally:
            self._release()

    async def _acquire(self, deadline) -> None:
        if self._in_flight < self._max_in_flight and not self._waiters:
            self._in_flight += 1
            self._admitted()
            return
        if len(self._waiters) >= self._max_queue:
            self._shed("queue_full")
        timeout = self._default_wait_s
        if deadline is not None:
            timeout = min(timeout, deadline.remaining())
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, TimeoutError):
            self._abandon_wait(fut)
            self._shed("queue_timeout")
        except asyncio.CancelledError:
            # Client disconnected while queued: the dead future must not keep
            # consuming a queue slot (it would shed healthy traffic as
            # queue_full long after the client left).
            self._abandon_wait(fut)
            raise
        else:
            # Slot transferred by _release(); in-flight already accounts us.
            self._admitted()

    def _abandon_wait(self, fut: asyncio.Future) -> None:
        """Withdraw a waiter that will not proceed, returning any slot the
        grant-vs-abandon race already transferred to it."""
        try:
            self._waiters.remove(fut)
        except ValueError:
            pass
        if fut.done() and not fut.cancelled():
            self._release()

    def _admitted(self) -> None:
        if self._admitted_total is not None:
            self._admitted_total.inc()

    def _release(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)  # direct handoff: in-flight unchanged
                return
        self._in_flight -= 1
