"""Admission control: bounded in-flight work + bounded, deadline-aware queue,
weighted-fair across tenants.

The seed accepted every request and let them pile up inside the executor
(unbounded queueing → every client times out). This gate enforces the
standard load-shedding contract instead:

- up to ``max_in_flight`` requests execute concurrently;
- up to ``max_queue`` more wait, each bounded by its own request deadline
  (or ``default_wait_s`` when the edge didn't attach one);
- everything beyond that — and any waiter whose deadline would expire in the
  queue — is shed *immediately* with ``AdmissionRejected`` carrying a
  retry-after hint. The HTTP edge maps this to 429 + ``Retry-After``; the
  gRPC edge to ``RESOURCE_EXHAUSTED``. Nothing ever hangs.

Multi-tenant fairness (docs/tenancy.md): when the edges resolve a
:class:`~..tenancy.TenantContext`, the single FIFO becomes per-tenant FIFOs
scheduled by deficit round-robin weighted by each tenant's configured
``weight`` — under saturation, grants track weights instead of arrival
order, so one hot tenant can no longer monopolize the queue. On top of the
fair scheduler each tenant gets its own quotas:

- a **token-bucket rate quota** (``rps``/``burst``): excess arrivals shed
  as ``reason="tenant_quota"`` with a Retry-After naming when the next
  token lands — a per-tenant verdict, not a global one;
- a **concurrency cap** (``max_in_flight``): requests over it queue in the
  tenant's own FIFO (never another tenant's share) until a slot frees;
- a **queue share**: each tenant may occupy at most its weight-proportional
  slice of ``max_queue`` (shed ``tenant_quota`` past it), so a flood can
  fill its own slice but never the whole queue;
- a **retry budget**: tenants with a rate quota get a matching retry
  token bucket (~10% of quota); the resilience retry loop consults it via
  the ambient tenant context and fails fast when it is spent.

The global bounds still cap aggregate load; with no tenant table declared
every request shares one unlimited ``default`` lane and behavior is
identical to the pre-tenancy gate.

Slot accounting is exact: a grant increments both the global and the lane
in-flight counts before the waiter resumes, so a burst can never overshoot
``max_in_flight``; a waiter abandoned after winning the grant race returns
the slot through the same ``_release`` path, and its demand-tracker sample
is a single shed — never shed *and* admitted.

Cost-aware mode (``APP_ADMISSION_COST_AWARE``, default off): the edge
analyzer's ``cost_class`` hint (docs/analysis.md "Cost classes") becomes a
priority signal — executions classified ``io_heavy``/``install_heavy``
additionally pass :meth:`AdmissionController.heavy_lane`, a bounded
secondary gate (half of ``max_in_flight``), after analysis and before the
sandbox is touched. A saturated heavy lane sheds immediately
(``reason="heavy_lane"``). Independently of that gate, a heavy-classified
execution debits its tenant's WFQ deficit by one extra unit — heavy work
costs double the fair-share credit, generalizing the serving engine's
priority classes to the executor pool (tenant weight × cost class).
"""

from __future__ import annotations

import asyncio
import time
from collections import deque
from contextlib import asynccontextmanager

from bee_code_interpreter_tpu.observability import span as trace_span
from bee_code_interpreter_tpu.tenancy.context import current_tenant_context
from bee_code_interpreter_tpu.tenancy.registry import Tenant

# Mirror of analysis.policy.HEAVY_COST_CLASSES, spelled here so resilience/
# never imports the analysis layer (the hint arrives as a plain string).
# `accelerator` rides the heavy lane too: device-bound work holds a sandbox
# for whole training/inference runs, the opposite of an interactive turn
# (tests/test_analysis.py pins the two sets equal).
_HEAVY_COST_CLASSES = frozenset({"io_heavy", "install_heavy", "accelerator"})

# DRR bookkeeping: every admitted request costs one unit of its lane's
# deficit; a visit tops each eligible lane up by its weight, so grant
# ratios converge to weight ratios under sustained backlog. Heavy-classed
# work debits one extra unit (docs/tenancy.md "Cost classes").
_REQUEST_COST = 1.0
_HEAVY_EXTRA_COST = 1.0
# A lane may bank at most this many top-up rounds of credit (and never
# less than one request's cost), bounding post-idle bursts.
_DEFICIT_CAP_ROUNDS = 4.0

# Retry budget (docs/tenancy.md "Retry budgets"): tenants with a rate
# quota may retry at ~10% of it, bucket depth 10.
_RETRY_BUDGET_RATIO = 0.1
_RETRY_BUDGET_MIN_RATE = 0.1
_RETRY_BUDGET_BURST = 10.0


class AdmissionRejected(Exception):
    def __init__(self, reason: str, retry_after_s: float) -> None:
        super().__init__(f"request shed: {reason} (retry in {retry_after_s:.1f}s)")
        self.reason = reason
        self.retry_after_s = max(0.0, retry_after_s)


class _TenantLane:
    """One tenant's admission state: FIFO, in-flight count, DRR deficit,
    and the rate/retry token buckets."""

    __slots__ = (
        "tenant",
        "label",
        "waiters",
        "in_flight",
        "deficit",
        "tokens",
        "tokens_mono",
        "retry_tokens",
        "retry_mono",
        "admitted",
        "sheds",
        "retries_denied",
        "queue_wait_sum_s",
    )

    def __init__(self, tenant: Tenant, now: float) -> None:
        self.tenant = tenant
        self.label = tenant.id
        self.waiters: deque[asyncio.Future] = deque()
        self.in_flight = 0
        self.deficit = 0.0
        self.tokens = tenant.burst_depth
        self.tokens_mono = now
        self.retry_tokens = _RETRY_BUDGET_BURST
        self.retry_mono = now
        self.admitted = 0
        self.sheds: dict[str, int] = {}
        self.retries_denied = 0
        self.queue_wait_sum_s = 0.0


class AdmissionController:
    def __init__(
        self,
        max_in_flight: int = 64,
        max_queue: int = 128,
        default_wait_s: float = 30.0,
        retry_after_s: float = 1.0,
        metrics=None,
        demand=None,  # observability.DemandTracker (capacity telemetry)
        cost_aware: bool = False,
        heavy_max_in_flight: int | None = None,
        tenancy=None,  # tenancy.TenantRegistry (per-tenant quotas + WFQ)
        quota_leases=None,  # tenancy.QuotaLeaseCache (fleet-wide quotas)
        clock=time.monotonic,  # injectable for the token buckets
    ) -> None:
        self._max_in_flight = max(1, max_in_flight)
        self._max_queue = max(0, max_queue)
        self._default_wait_s = default_wait_s
        self._retry_after_s = retry_after_s
        self._in_flight = 0
        self._queued = 0
        self._cost_aware = cost_aware
        self._heavy_max = (
            heavy_max_in_flight
            if heavy_max_in_flight is not None
            else max(1, self._max_in_flight // 2)
        )
        self._heavy_in_flight = 0
        self._tenancy = tenancy
        # Fleet-wide rate quotas (docs/tenancy.md "Fleet-wide tenancy"):
        # when a lease cache is wired in, each lane's token bucket refills
        # at this replica's GRANTED slice of the tenant's fleet-wide rps
        # rather than the full declared quota; with no cache (single
        # replica, pre-fleet deployments) behavior is unchanged.
        self._quota_leases = quota_leases
        self._clock = clock
        self._lanes: dict[str, _TenantLane] = {}
        self._rr_cursor: str | None = None
        # The gate is the ONE chokepoint every sandbox-bound request on
        # either transport passes, which makes it the natural demand
        # sensor: arrivals, sheds, queue waits, and the in-flight
        # high-water feed the capacity tracker here (docs/autoscaling.md).
        self._demand = demand
        self._metrics = metrics
        self._shed_total = None
        self._admitted_total = None
        self._tenant_shed_total = None
        self._tenant_admitted_total = None
        self._tenant_queue_wait_seconds = None
        if metrics is not None:
            self._shed_total = metrics.counter(
                "bci_admission_shed_total", "Requests shed by admission control"
            )
            self._admitted_total = metrics.counter(
                "bci_admission_admitted_total", "Requests admitted past the gate"
            )
            self._tenant_shed_total = metrics.counter(
                "bci_tenant_shed_total",
                "Requests shed per tenant, by reason (tenant_quota/queue_full/"
                "queue_timeout/heavy_lane)",
            )
            self._tenant_admitted_total = metrics.counter(
                "bci_tenant_admitted_total",
                "Requests admitted past the gate, per tenant",
            )
            self._tenant_queue_wait_seconds = metrics.histogram(
                "bci_tenant_queue_wait_seconds",
                "Admission queue wait per tenant (admitted requests)",
            )
            metrics.gauge(
                "bci_admission_in_flight",
                "Requests currently executing past admission",
                lambda: self._in_flight,
            )
            metrics.gauge(
                "bci_admission_queue_depth",
                "Requests waiting in the admission queue",
                lambda: self._queued,
            )
            metrics.gauge(
                "bci_admission_heavy_in_flight",
                "Cost-classified heavy executions currently in the heavy lane",
                lambda: self._heavy_in_flight,
            )
        # The default lane exists from construction: its per-tenant gauges
        # must be scrapable before the first request arrives.
        self._lane(self._default_tenant())

    def _default_tenant(self) -> Tenant:
        if self._tenancy is not None:
            return self._tenancy.default
        return Tenant(id="default")

    @property
    def in_flight(self) -> int:
        return self._in_flight

    @property
    def queue_depth(self) -> int:
        return self._queued

    @property
    def heavy_in_flight(self) -> int:
        return self._heavy_in_flight

    # ---------------------------------------------------------------- lanes

    def _lane(self, tenant: Tenant) -> _TenantLane:
        lane = self._lanes.get(tenant.id)
        if lane is None:
            lane = self._lanes[tenant.id] = _TenantLane(tenant, self._clock())
            if self._metrics is not None:
                self._metrics.gauge(
                    "bci_tenant_in_flight",
                    "Requests currently executing past admission, per tenant",
                    (lambda l: lambda: l.in_flight)(lane),
                    tenant=lane.label,
                )
                self._metrics.gauge(
                    "bci_tenant_queue_depth",
                    "Requests waiting in the admission queue, per tenant",
                    (lambda l: lambda: len(l.waiters))(lane),
                    tenant=lane.label,
                )
        return lane

    def _lane_for(self, tenant) -> _TenantLane:
        """The lane a request belongs to. ``tenant`` may be a
        ``TenantContext``, a ``Tenant``, or None (→ the default lane);
        unknown ids already resolved to the default tenant at the edge, so
        they share its lane and quotas."""
        resolved = getattr(tenant, "tenant", tenant)
        if resolved is None:
            resolved = self._default_tenant()
        return self._lane(resolved)

    def _ambient_lane(self) -> _TenantLane | None:
        ctx = current_tenant_context()
        return None if ctx is None else self._lane_for(ctx)

    def _lane_queue_cap(self, lane: _TenantLane) -> int:
        """A tenant's slice of the global queue, proportional to weight —
        one flooding tenant can fill its slice, never the whole queue. A
        single-lane (tenancy-less) gate keeps the full queue."""
        if self._tenancy is None:
            return self._max_queue
        tenants = self._tenancy.tenants()
        if len(tenants) <= 1:
            return self._max_queue
        total_weight = sum(t.weight for t in tenants)
        share = self._max_queue * lane.tenant.weight / total_weight
        return max(1, int(share))

    # ----------------------------------------------------------- heavy lane

    @asynccontextmanager
    async def heavy_lane(self, cost_class: str | None):
        """The cost-aware secondary gate (docs/analysis.md "Cost classes").

        The bounded-lane half is a no-op unless cost-aware mode is on AND
        the edge analyzer classified this execution heavy (io_heavy/
        install_heavy). It runs AFTER :meth:`admit` (analysis needs the
        request body, which is only read once admitted), so a heavy-lane
        shed releases an admission slot immediately — the bounded cost of
        classifying is one queue check, never a sandbox checkout.

        Independently of the gate, a heavy classification debits the
        ambient tenant's WFQ deficit (tenant weight × cost class): under
        saturation a tenant spending heavy requests earns fewer grants."""
        heavy = cost_class in _HEAVY_COST_CLASSES
        if heavy:
            lane = self._ambient_lane()
            if lane is not None:
                floor = -lane.tenant.weight * _DEFICIT_CAP_ROUNDS
                lane.deficit = max(floor, lane.deficit - _HEAVY_EXTRA_COST)
        if not self._cost_aware or not heavy:
            yield
            return
        if self._heavy_in_flight >= self._heavy_max:
            self._shed("heavy_lane", self._ambient_lane())
        self._heavy_in_flight += 1
        try:
            yield
        finally:
            self._heavy_in_flight -= 1

    # ----------------------------------------------------------------- shed

    def _shed(
        self,
        reason: str,
        lane: _TenantLane | None = None,
        retry_after_s: float | None = None,
    ) -> None:
        if self._shed_total is not None:
            self._shed_total.inc(reason=reason)
        if lane is not None:
            lane.sheds[reason] = lane.sheds.get(reason, 0) + 1
            if self._tenant_shed_total is not None:
                self._tenant_shed_total.inc(tenant=lane.label, reason=reason)
        if self._demand is not None:
            self._demand.record_shed(
                tenant=lane.label if lane is not None else None
            )
        raise AdmissionRejected(
            reason,
            retry_after_s if retry_after_s is not None else self._retry_after_s,
        )

    # ---------------------------------------------------------------- admit

    @asynccontextmanager
    async def admit(self, deadline=None, tenant=None):
        # The trace stage span covers ONLY the acquire (the queue wait a
        # slow request may have paid); the admitted body's time belongs to
        # its own stages. One instrumentation site serves every edge.
        lane = self._lane_for(tenant)
        if self._demand is not None:
            self._demand.record_arrival(tenant=lane.label)
        wait_start = time.monotonic()
        with trace_span("admission"):
            await self._acquire(deadline, lane)
        queue_wait_s = time.monotonic() - wait_start
        lane.queue_wait_sum_s += queue_wait_s
        if self._tenant_queue_wait_seconds is not None:
            self._tenant_queue_wait_seconds.observe(
                queue_wait_s, tenant=lane.label
            )
        if self._demand is not None:
            self._demand.record_admitted(
                queue_wait_s=queue_wait_s,
                in_flight=self._in_flight,
            )
        try:
            yield
        finally:
            self._release(lane)

    def _effective_quota(self, lane: _TenantLane) -> tuple[float, float]:
        """The ``(rps, burst)`` this replica enforces for the lane's
        tenant: the full declared quota without a lease cache, otherwise
        the granted slice (or the cache's fail-safe 1/N split)."""
        tenant = lane.tenant
        if self._quota_leases is None:
            return tenant.rps, tenant.burst_depth
        return self._quota_leases.effective(tenant)

    def _refill_tokens(self, lane: _TenantLane) -> float | None:
        if lane.tenant.rps is None:
            return None
        rate, burst = self._effective_quota(lane)
        now = self._clock()
        lane.tokens = min(
            burst,
            lane.tokens + (now - lane.tokens_mono) * rate,
        )
        lane.tokens_mono = now
        return rate

    async def _acquire(self, deadline, lane: _TenantLane) -> None:
        tenant = lane.tenant
        # 1. Rate quota: a per-tenant verdict, charged at arrival. The
        # Retry-After names when the next token lands at the CURRENT
        # effective rate (the leased slice, behind a fleet router), not a
        # global hint.
        if tenant.rps is not None:
            rate = self._refill_tokens(lane)
            if lane.tokens < 1.0:
                self._shed(
                    "tenant_quota",
                    lane,
                    retry_after_s=(1.0 - lane.tokens) / max(rate, 1e-9),
                )
            lane.tokens -= 1.0
        # 2. Uncontended fast path: free global slot, empty queue, tenant
        # under its concurrency cap.
        cap = tenant.max_in_flight
        if (
            self._in_flight < self._max_in_flight
            and self._queued == 0
            and (cap is None or lane.in_flight < cap)
        ):
            self._grant(lane)
            self._admitted(lane)
            return
        # 3. Queue bounds: the global bound first (aggregate protection),
        # then the tenant's weight-proportional slice (per-tenant verdict).
        if self._queued >= self._max_queue:
            self._shed("queue_full", lane)
        if len(lane.waiters) >= self._lane_queue_cap(lane):
            self._shed("tenant_quota", lane)
        timeout = self._default_wait_s
        if deadline is not None:
            timeout = min(timeout, deadline.remaining())
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        if not lane.waiters:
            lane.deficit = 0.0  # fresh backlog starts without banked credit
        lane.waiters.append(fut)
        self._queued += 1
        # A free slot may exist even with waiters queued (every queued
        # tenant at its cap): dispatch immediately rather than waiting for
        # the next release.
        self._dispatch()
        try:
            await asyncio.wait_for(fut, timeout)
        except (asyncio.TimeoutError, TimeoutError):
            self._abandon_wait(fut, lane)
            self._shed("queue_timeout", lane)
        except asyncio.CancelledError:
            # Client disconnected while queued: the dead future must not keep
            # consuming a queue slot (it would shed healthy traffic as
            # queue_full long after the client left).
            self._abandon_wait(fut, lane)
            raise
        else:
            # Slot granted by _dispatch(); both counts already include us.
            self._admitted(lane)

    def _abandon_wait(self, fut: asyncio.Future, lane: _TenantLane) -> None:
        """Withdraw a waiter that will not proceed. If the grant-vs-abandon
        race already transferred a slot to it, the slot goes back through
        ``_release`` — ONE code path, so the demand tracker sees exactly
        one shed and zero admissions for an abandoned waiter."""
        try:
            lane.waiters.remove(fut)
        except ValueError:
            pass  # already popped by _dispatch
        else:
            self._queued -= 1
        if fut.done() and not fut.cancelled():
            self._release(lane)

    def _admitted(self, lane: _TenantLane) -> None:
        lane.admitted += 1
        if self._admitted_total is not None:
            self._admitted_total.inc()
        if self._tenant_admitted_total is not None:
            self._tenant_admitted_total.inc(tenant=lane.label)

    def _grant(self, lane: _TenantLane, fut: asyncio.Future | None = None) -> None:
        self._in_flight += 1
        lane.in_flight += 1
        if fut is not None:
            fut.set_result(None)

    def _release(self, lane: _TenantLane) -> None:
        self._in_flight -= 1
        lane.in_flight -= 1
        self._dispatch()

    # ------------------------------------------------------------- dispatch

    def _dispatch(self) -> None:
        """Grant free slots to queued waiters, weighted-fair across lanes.
        Runs synchronously on the loop (no awaits), so counts are always
        consistent when control returns to a coroutine."""
        while self._in_flight < self._max_in_flight:
            lane = self._next_lane()
            if lane is None:
                return
            fut: asyncio.Future | None = None
            while lane.waiters:
                cand = lane.waiters.popleft()
                self._queued -= 1
                if not cand.done():
                    fut = cand
                    break
            if fut is None:
                continue  # only dead waiters; re-evaluate lanes
            # Debt is floored like credit is capped: a lane served solo
            # (the single-eligible fast path skips top-ups) must not
            # accrue unbounded debt, or the moment a second tenant starts
            # queuing the weights invert until the debt is paid off.
            floor = -lane.tenant.weight * _DEFICIT_CAP_ROUNDS
            lane.deficit = max(floor, lane.deficit - _REQUEST_COST)
            if not lane.waiters:
                lane.deficit = 0.0  # DRR: an emptied queue banks no credit
            self._grant(lane, fut)

    def _next_lane(self) -> _TenantLane | None:
        """Deficit round-robin: serve the first lane (cursor-rotated) with
        enough credit; when none has, top every eligible lane up by its
        weight and try again — grant ratios converge to weight ratios."""
        eligible = [
            lane
            for label in sorted(self._lanes)
            for lane in (self._lanes[label],)
            if lane.waiters
            and (
                lane.tenant.max_in_flight is None
                or lane.in_flight < lane.tenant.max_in_flight
            )
        ]
        if not eligible:
            return None
        if len(eligible) == 1:
            return eligible[0]
        labels = [lane.label for lane in eligible]
        if self._rr_cursor in labels:
            i = labels.index(self._rr_cursor)
            eligible = eligible[i:] + eligible[:i]
        # Bounded: each top-up adds >= min(weight) > 0 credit to every lane,
        # so some lane reaches _REQUEST_COST within cost/min(weight) rounds.
        min_weight = min(lane.tenant.weight for lane in eligible)
        rounds = max(2, int(_REQUEST_COST / min_weight) + 2)
        for _ in range(rounds):
            for lane in eligible:
                if lane.deficit >= _REQUEST_COST:
                    self._rr_cursor = lane.label
                    return lane
            for lane in eligible:
                cap = max(
                    _REQUEST_COST, lane.tenant.weight * _DEFICIT_CAP_ROUNDS
                )
                lane.deficit = min(cap, lane.deficit + lane.tenant.weight)
        return eligible[0]  # unreachable with weights > 0; safe fallback

    # --------------------------------------------------------- retry budget

    def tenant_retry_budget(self, tenant):
        """A zero-arg callable spending one retry from ``tenant``'s budget
        (the edge binds it into the ``TenantContext``; the resilience retry
        loop consults it). Tenants without a rate quota get no budget —
        ``None`` — preserving pre-tenancy retry behavior for them."""
        lane = self._lane_for(tenant)
        if lane.tenant.rps is None:
            return None
        rate = max(_RETRY_BUDGET_MIN_RATE, lane.tenant.rps * _RETRY_BUDGET_RATIO)

        def spend() -> bool:
            now = self._clock()
            lane.retry_tokens = min(
                _RETRY_BUDGET_BURST,
                lane.retry_tokens + (now - lane.retry_mono) * rate,
            )
            lane.retry_mono = now
            if lane.retry_tokens >= 1.0:
                lane.retry_tokens -= 1.0
                return True
            lane.retries_denied += 1
            return False

        return spend

    # --------------------------------------------------------- quota leases

    def quota_tenants(self) -> list[str]:
        """Tenant ids worth leasing fleet-wide quota slices for: every
        rate-quota'd tenant that has a lane here (i.e. this replica has
        actually seen its traffic). The lease client sends this list each
        refresh — replicas a tenant never reaches never claim a slice, so
        the tenant's active lessees converge to its placement subset."""
        return sorted(
            lane.tenant.id
            for lane in self._lanes.values()
            if lane.tenant.rps is not None
        )

    # ------------------------------------------------------------- operator

    def tenant_snapshot(self) -> dict[str, dict]:
        """Per-tenant admission state for ``GET /v1/tenants``."""
        out: dict[str, dict] = {}
        for label in sorted(self._lanes):
            lane = self._lanes[label]
            out[label] = {
                "weight": lane.tenant.weight,
                "in_flight": lane.in_flight,
                "queued": len(lane.waiters),
                "admitted": lane.admitted,
                "sheds": dict(lane.sheds),
                "retries_denied": lane.retries_denied,
                "queue_wait_avg_ms": (
                    lane.queue_wait_sum_s / lane.admitted * 1000.0
                    if lane.admitted
                    else 0.0
                ),
                "rate_tokens": (
                    round(lane.tokens, 3)
                    if lane.tenant.rps is not None
                    else None
                ),
            }
            if self._quota_leases is not None and lane.tenant.rps is not None:
                rate, burst = self._effective_quota(lane)
                out[label]["quota"] = {
                    "effective_rps": round(rate, 3),
                    "effective_burst": round(burst, 3),
                    "leased": (
                        self._quota_leases.lease(lane.tenant.id) is not None
                    ),
                }
        return out
