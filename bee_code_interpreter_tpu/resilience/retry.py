"""Config-driven async retry with exponential backoff.

In-repo replacement for the tenacity decorators the seed used (the library
isn't available in every runtime image, and its per-class decorators froze
the backoff schedule at import time — untestable and untunable). Policies
live on the *instance* (built from ``Config``), so deployments tune attempts
and backoff via env and tests can observe real schedules in milliseconds.

Deadline-aware: when the wrapped call received a ``deadline=`` kwarg, the
retry loop refuses to sleep past it — the last error is re-raised instead of
burning budget waiting out a backoff that cannot complete.

``functools.wraps`` preserves ``__wrapped__``, so tests can keep calling
``executor.spawn_pod_group.__wrapped__(executor)`` to bypass retries.
"""

from __future__ import annotations

import asyncio
import functools
import logging
from dataclasses import dataclass

from bee_code_interpreter_tpu.resilience.deadline import Deadline
from bee_code_interpreter_tpu.tenancy.context import consume_retry_budget

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff: ``wait_min_s * 2**(attempt-1)`` capped at
    ``wait_max_s``, for ``attempts`` total tries on ``retry_on`` errors."""

    attempts: int = 3
    wait_min_s: float = 4.0
    wait_max_s: float = 10.0
    retry_on: tuple[type[BaseException], ...] = (Exception,)

    def backoff_s(self, attempt: int) -> float:
        return min(self.wait_min_s * (2 ** (attempt - 1)), self.wait_max_s)


def retryable(policy_attr: str, op: str):
    """Decorate an async method; the policy is read from ``self.<policy_attr>``
    at call time. If the instance defines ``_on_retry_backoff(op, attempt,
    sleep_s, exc)`` it is invoked before each backoff sleep (metrics/tests)."""

    def decorate(fn):
        @functools.wraps(fn)
        async def wrapper(self, *args, **kwargs):
            policy: RetryPolicy = getattr(self, policy_attr)
            deadline: Deadline | None = kwargs.get("deadline")
            attempt = 0
            while True:
                attempt += 1
                try:
                    return await fn(self, *args, **kwargs)
                except policy.retry_on as e:
                    if attempt >= policy.attempts:
                        raise
                    if not consume_retry_budget():
                        # Per-tenant retry budget exhausted (docs/tenancy.md
                        # "Retry budgets"): a quota'd tenant whose failures
                        # outpace ~10% of its rate quota fails fast instead
                        # of multiplying load through retries.
                        logger.warning(
                            "%s attempt %d failed (%s); tenant retry budget "
                            "exhausted, not retrying",
                            op, attempt, e,
                        )
                        raise
                    sleep_s = policy.backoff_s(attempt)
                    if deadline is not None and deadline.remaining() <= sleep_s:
                        # No budget to wait out the backoff AND re-attempt:
                        # surface the real failure now, not a later timeout.
                        raise
                    record = getattr(self, "_on_retry_backoff", None)
                    if record is not None:
                        record(op, attempt, sleep_s, e)
                    logger.warning(
                        "%s attempt %d/%d failed (%s); retrying in %.2fs",
                        op, attempt, policy.attempts, e, sleep_s,
                    )
                    await asyncio.sleep(sleep_s)

        return wrapper

    return decorate
