"""Proactive pool resilience: supervisor, stuck-execution watchdog, drain.

PR 1 made the service *react* well to failure (deadlines, breakers,
admission); this module makes it *heal itself* (docs/resilience.md):

- ``PoolSupervisor`` — a background reconciler owned per pool executor.
  Each sweep it (1) health-probes the queued warm sandboxes and reaps the
  dead ones into the fleet journal (``reaped{reason=unhealthy_idle}``),
  (2) kills any execution that has overrun the hard wall-clock cap (the
  stuck-execution watchdog: the sandbox is torn down, the waiting request
  fails as *transient* so the replay/retry layers can recover it), and
  (3) replenishes the pool to target through the backend's existing
  breaker-gated refill. Sweep durations land in
  ``bci_supervisor_probe_seconds``.

- ``InflightRegistry`` — the watchdog's view of executions in flight.
  Pool backends wrap each sandbox-bound execute in :meth:`track`; the
  supervisor kills overdue entries via the backend-provided ``kill``
  callback plus a task cancel, and the registry converts that cancel into
  a ``SandboxTransientError`` (``reap_reason="hung_execute"``) so the
  failure is retryable, never a bare CancelledError surfacing as a 500.

- ``DrainController`` — shared graceful-shutdown state. ``begin()`` flips
  the service into draining mode: both API edges reject *new* sandbox-bound
  work (HTTP 503 + ``Retry-After``, gRPC UNAVAILABLE and health
  ``NOT_SERVING`` via registered callbacks) while requests already admitted
  — tracked through :meth:`track`, exported as ``bci_drain_inflight`` —
  run to completion; ``wait_idle`` bounds the wait by ``APP_DRAIN_GRACE_S``.
"""

from __future__ import annotations

import asyncio
import logging
import time
from contextlib import contextmanager
from dataclasses import dataclass
from typing import Callable

from bee_code_interpreter_tpu.resilience.errors import SandboxTransientError

logger = logging.getLogger(__name__)


def journal_sandbox_teardown(journal, sandbox: str, exc: BaseException | None) -> None:
    """The ONE journal spelling for the end of a sandbox's single use,
    shared by both pool backends (their context managers classify the same
    way, and the replay/chaos acceptance asserts on these exact reasons):

    - transient data-plane failure → ``reaped`` with the exception's
      ``reap_reason`` (``hung_execute`` from the watchdog) or the default
      ``died_mid_execute``;
    - cancellation (deadline fired, hedge lost the race) → ``released``
      with reason ``cancelled``;
    - anything else, including success → ``released`` / ``single_use``.
    """
    if isinstance(exc, SandboxTransientError):
        journal.record(
            sandbox,
            "reaped",
            reason=getattr(exc, "reap_reason", "died_mid_execute"),
            detail=str(exc)[:200],
        )
    elif isinstance(exc, asyncio.CancelledError):
        journal.record(sandbox, "released", reason="cancelled")
    else:
        journal.record(sandbox, "released", reason="single_use")


# ------------------------------------------------------------------ watchdog


@dataclass
class InflightExecution:
    """One sandbox-bound execution currently in flight."""

    sandbox: str
    started_mono: float
    task: asyncio.Task | None
    kill: Callable[[], None] | None
    killed: bool = False
    kill_reason: str = ""

    def age_s(self, now: float) -> float:
        return now - self.started_mono


class InflightRegistry:
    """Executions in flight on one pool backend, killable by the watchdog.

    ``track`` is a *sync* context manager (no awaits) wrapped around the
    backend's execute call while it holds a sandbox. ``kill_overdue``
    (driven by the supervisor sweep) tears the sandbox down via the
    backend's callback and cancels the tracked task; the injected
    CancelledError is converted to a ``SandboxTransientError`` carrying
    ``reap_reason="hung_execute"`` — the request fails *transient* (so
    retry/replay can still save it) and the fleet journal records why the
    sandbox died. A cancel the watchdog did NOT inject (client gone,
    deadline fired) passes through untouched.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic) -> None:
        self._clock = clock
        self._live: dict[int, InflightExecution] = {}
        self._seq = 0

    def __len__(self) -> int:
        return len(self._live)

    @contextmanager
    def track(self, sandbox: str, kill: Callable[[], None] | None = None):
        try:
            task = asyncio.current_task()
        except RuntimeError:
            task = None
        entry = InflightExecution(
            sandbox=sandbox, started_mono=self._clock(), task=task, kill=kill
        )
        self._seq += 1
        key = self._seq
        self._live[key] = entry
        try:
            yield entry
        except asyncio.CancelledError:
            if entry.killed:
                # Swallowing OUR cancel must also rewind the task's
                # cancellation count (3.11+), or an enclosing
                # wait_for/timeout (the edge deadline's hard bound) later
                # sees a cancellation it never requested and re-raises
                # CancelledError instead of its TimeoutError mapping.
                if entry.task is not None and hasattr(entry.task, "uncancel"):
                    entry.task.uncancel()
                err = SandboxTransientError(
                    f"execution on {sandbox} killed by the supervisor watchdog "
                    f"({entry.kill_reason}) after {entry.age_s(self._clock()):.1f}s"
                )
                err.reap_reason = "hung_execute"
                raise err from None
            raise
        finally:
            self._live.pop(key, None)

    def overdue(self, cap_s: float) -> list[InflightExecution]:
        now = self._clock()
        return [
            e
            for e in self._live.values()
            if not e.killed and e.age_s(now) > cap_s
        ]

    def kill(self, entry: InflightExecution, reason: str = "hung_execute") -> None:
        """Kill one in-flight execution: sandbox teardown first (so the
        hung call's transport actually dies), then the task cancel that the
        tracking context converts into a transient failure."""
        entry.killed = True
        entry.kill_reason = reason
        if entry.kill is not None:
            try:
                entry.kill()
            except Exception:
                logger.exception(
                    "Watchdog sandbox-kill callback failed for %s", entry.sandbox
                )
        if entry.task is not None:
            entry.task.cancel()

    def oldest_age_s(self) -> float | None:
        if not self._live:
            return None
        now = self._clock()
        return max(e.age_s(now) for e in self._live.values())


# ---------------------------------------------------------------- supervisor


class PoolSupervisor:
    """Background reconciler for one pool executor (k8s pod groups or native
    processes).

    Session leases (docs/sessions.md) are invisible here BY CONSTRUCTION:
    a leased sandbox was popped out of the queue (so ``reap_unhealthy_idle``
    never probes it) and enters the inflight registry only while one of its
    executes runs (so the watchdog sees a wedged leased execute, never a
    healthy-but-idle REPL). An owned sandbox is not "stuck"; the
    SessionManager's own TTL/idle sweep is its reaper.

    The executor contract is duck-typed:

    - ``reap_unhealthy_idle()`` (async) — probe queued warm sandboxes, reap
      dead ones, return the count;
    - ``fill_executor_pod_queue`` / ``fill_sandbox_queue`` (async) — the
      existing breaker-gated refill to target;
    - ``inflight`` — an :class:`InflightRegistry` (optional; enables the
      stuck-execution watchdog).

    Owned per executor, started by the composition root once a loop runs.
    """

    def __init__(
        self,
        executor,
        *,
        interval_s: float = 10.0,
        execute_hard_cap_s: float | None = None,
        metrics=None,
        drain: "DrainController | None" = None,
        autoscaler=None,  # resilience.PoolAutoscaler (docs/autoscaling.md)
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._executor = executor
        self._interval_s = max(0.05, interval_s)
        self._hard_cap_s = execute_hard_cap_s
        self._drain = drain
        self._autoscaler = autoscaler
        self._clock = clock
        self._reap = getattr(executor, "reap_unhealthy_idle", None)
        self._refill = getattr(
            executor, "fill_executor_pod_queue", None
        ) or getattr(executor, "fill_sandbox_queue", None)
        self._inflight: InflightRegistry | None = getattr(
            executor, "inflight", None
        )
        self._task: asyncio.Task | None = None
        self._stopped = False
        self.sweeps_total = 0
        self.reaped_total = 0
        self.watchdog_kills_total = 0
        self.trimmed_total = 0
        self.last_sweep_mono: float | None = None
        self._probe_seconds = (
            metrics.histogram(
                "bci_supervisor_probe_seconds",
                "Pool supervisor sweep duration (idle health probes + watchdog + refill)",
            )
            if metrics is not None
            else None
        )

    # ------------------------------------------------------------ lifecycle

    @property
    def running(self) -> bool:
        return self._task is not None and not self._task.done()

    def start(self) -> asyncio.Task:
        """Start the reconcile loop (requires a running loop); idempotent."""
        if self.running:
            return self._task
        self._stopped = False
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self._task

    async def stop(self) -> None:
        self._stopped = True
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while not self._stopped:
            try:
                await asyncio.sleep(self._interval_s)
                await self.sweep_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # One bad sweep must not end self-healing for the process.
                logger.exception("Pool supervisor sweep failed")

    # --------------------------------------------------------------- sweeps

    async def sweep_once(self) -> dict:
        """One reconcile pass: probe idle → watchdog → refill. Exposed for
        tests and the chaos harness; the background loop calls it on the
        configured cadence."""
        t0 = self._clock()
        reaped = 0
        if self._reap is not None:
            reaped = await self._reap()
        killed = 0
        if self._inflight is not None and self._hard_cap_s is not None:
            for entry in self._inflight.overdue(self._hard_cap_s):
                logger.warning(
                    "Watchdog: execution on %s exceeded the %.0fs hard cap "
                    "(%.1fs in flight); killing the sandbox",
                    entry.sandbox,
                    self._hard_cap_s,
                    entry.age_s(self._clock()),
                )
                self._inflight.kill(entry)
                killed += 1
        duration = self._clock() - t0
        if self._probe_seconds is not None:
            self._probe_seconds.observe(duration)
        draining = self._drain is not None and self._drain.draining
        if self._autoscaler is not None and not draining:
            # Observe→forecast→recommend BEFORE the refill below, so an
            # act-mode target change is what this sweep replenishes to
            # (docs/autoscaling.md). A draining pool is never resized.
            try:
                self._autoscaler.evaluate()
            except Exception:
                logger.exception("Autoscaler evaluation failed")
            # The shrink half of actuation: a lowered target must also
            # reap the now-excess warm sandboxes (refill alone would hold
            # an idle pool at its peak size forever). No-op unless an
            # act-mode decision dropped pool_target below the queue depth.
            trim = getattr(self._executor, "trim_excess_warm", None)
            if trim is not None:
                try:
                    self.trimmed_total += trim()
                except Exception:
                    logger.exception("Warm-pool trim failed")
        if self._refill is not None and not draining:
            # Replenish through the backend's own breaker-gated refill
            # (a no-op while the spawn breaker is open) — kicked
            # fire-and-forget: a degraded apiserver must not stall the
            # sweep loop (and the next watchdog pass) behind minutes of
            # spawn retries, nor pollute the probe-duration histogram.
            refill = self._refill()
            spawn_background = getattr(self._executor, "_spawn_background", None)
            if spawn_background is not None:
                spawn_background(refill)
            else:
                await refill
        self.sweeps_total += 1
        self.reaped_total += reaped
        self.watchdog_kills_total += killed
        self.last_sweep_mono = self._clock()
        return {
            "reaped": reaped,
            "watchdog_killed": killed,
            "duration_s": duration,
        }

    def snapshot(self) -> dict:
        """Operator view for ``GET /v1/fleet`` / ``scripts/fleet-top.py``."""
        last_age = (
            self._clock() - self.last_sweep_mono
            if self.last_sweep_mono is not None
            else None
        )
        return {
            "running": self.running,
            "interval_s": self._interval_s,
            "execute_hard_cap_s": self._hard_cap_s,
            "sweeps": self.sweeps_total,
            "reaped": self.reaped_total,
            "watchdog_kills": self.watchdog_kills_total,
            "trimmed": self.trimmed_total,
            "last_sweep_age_s": last_age,
            "inflight": len(self._inflight) if self._inflight is not None else 0,
            "inflight_oldest_age_s": (
                self._inflight.oldest_age_s()
                if self._inflight is not None
                else None
            ),
        }


# --------------------------------------------------------------------- drain


class DrainController:
    """Graceful-drain state shared by both API edges and ``__main__``.

    ``begin()`` is idempotent and fires the registered callbacks exactly
    once (the gRPC server registers its health flip to ``NOT_SERVING``
    there). The edges consult :attr:`draining` *before* admission — new
    sandbox-bound work is rejected retryably — and wrap admitted work in
    :meth:`track` so ``wait_idle`` (and the ``bci_drain_inflight`` gauge)
    can see what the teardown must wait for.
    """

    def __init__(self, metrics=None, retry_after_s: float = 1.0) -> None:
        self.retry_after_s = max(0.0, retry_after_s)
        self._draining = False
        self._in_flight = 0
        self._callbacks: list[Callable[[], None]] = []
        self._idle_event: asyncio.Event | None = None
        if metrics is not None:
            metrics.gauge(
                "bci_drain_inflight",
                "In-flight requests a graceful drain must wait for",
                lambda: self._in_flight,
            )

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def in_flight(self) -> int:
        return self._in_flight

    def on_drain(self, callback: Callable[[], None]) -> None:
        """Register a callback fired when the drain begins; fired
        immediately if the drain already began (late-built servers)."""
        self._callbacks.append(callback)
        if self._draining:
            self._fire(callback)

    def _fire(self, callback: Callable[[], None]) -> None:
        try:
            callback()
        except Exception:
            logger.exception("Drain callback failed")

    def begin(self) -> None:
        if self._draining:
            return
        self._draining = True
        logger.info(
            "Drain started: rejecting new work, %d request(s) in flight",
            self._in_flight,
        )
        for callback in self._callbacks:
            self._fire(callback)
        self._wake_if_idle()

    @contextmanager
    def track(self):
        """Count one admitted request for the duration of its execution."""
        self._in_flight += 1
        try:
            yield
        finally:
            self._in_flight -= 1
            self._wake_if_idle()

    def _wake_if_idle(self) -> None:
        if self._in_flight == 0 and self._idle_event is not None:
            self._idle_event.set()

    async def wait_idle(self, grace_s: float) -> bool:
        """Wait until no tracked request is in flight, bounded by
        ``grace_s``. Returns True when drained, False when the grace
        expired with work still running."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + max(0.0, grace_s)
        while self._in_flight > 0:
            remaining = deadline - loop.time()
            if remaining <= 0:
                return False
            self._idle_event = asyncio.Event()
            try:
                # Short poll ceiling guards the wake-vs-replace race without
                # busy-waiting.
                await asyncio.wait_for(
                    self._idle_event.wait(), timeout=min(remaining, 0.25)
                )
            except (asyncio.TimeoutError, TimeoutError):
                pass
        return True
