"""SLO-aware predictive pool autoscaling (docs/autoscaling.md).

The supervisor's refill keeps the warm pool at a *static* target, so a
traffic step always pays cold-spawn latency until the pool catches up. The
``PoolAutoscaler`` closes the observe→forecast→recommend loop the capacity
tracker and forecaster open:

- **target sizing** — the warm pool must cover one spawn-horizon of
  forecast demand (each execution consumes a single-use sandbox, so at
  ``R`` req/s with spawn latency ``L`` the refill pipeline holds ``R×L``
  sandboxes — Little's law over the horizon) AND the observed concurrency
  high-water (a burst of N simultaneous requests pops N sandboxes at
  once), clamped to ``[APP_AUTOSCALE_MIN, APP_AUTOSCALE_MAX]``;
- **scale up early** — immediately when the forecast demands it, and on a
  fast-window SLO burn (the page pair firing means users are already
  hurting: add capacity without waiting for the forecast to agree);
- **shrink late** — only after ``APP_AUTOSCALE_IDLE_S`` of sustained idle
  (no arrivals at all), and never two shrinks inside the cooldown — the
  hysteresis that keeps recommendations from flapping;
- **modes** (``APP_AUTOSCALE_MODE``): ``off`` = no evaluation; ``advise`` =
  decisions are computed, logged, counted, and emitted as wide events but
  NEVER actuated (the decision log is testable in production before anyone
  trusts it with the pool); ``act`` = the pool backend's refill target is
  overridden, so the existing supervisor replenish loop — and every
  checkout-kicked refill — pre-spawns to the recommendation.

Every scale decision lands exactly once in the bounded decision log
(``GET /v1/autoscale``), in ``bci_autoscale_decisions_total{direction,
reason}``, and as a ``kind="autoscale"`` wide event through the flight
recorder (→ OTLP logs). ``bci_pool_target_size`` is the HPA-consumable
recommendation gauge.
"""

from __future__ import annotations

import logging
import math
import time
from collections import deque
from typing import Callable

logger = logging.getLogger(__name__)

MODES = ("off", "advise", "act")


class PoolAutoscaler:
    """One autoscaler per pool executor (k8s pod groups or native
    processes), evaluated by the supervisor's reconcile sweep.

    The executor contract is duck-typed: ``pool_ready_count`` /
    ``pool_spawning_count`` (current size) and ``pool_target_override``
    (written in ``act`` mode; the backends' refill — the supervisor
    sweep's, and every checkout-kicked one — reads it through their
    ``pool_target`` property).
    """

    def __init__(
        self,
        executor,
        forecaster,
        demand,
        *,
        mode: str = "advise",
        min_size: int = 1,
        max_size: int = 16,
        idle_s: float = 60.0,
        cooldown_s: float = 15.0,
        base_target: int | None = None,
        hw_window_s: float = 60.0,
        slo=None,
        recorder=None,
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
        decision_log_max: int = 128,
    ) -> None:
        if mode not in MODES:
            raise ValueError(f"autoscale mode must be one of {MODES}, got {mode!r}")
        if min_size > max_size:
            # Fail at construction, where the blame is local: silently
            # widening max past the operator's explicit quota cap would
            # scale the pool beyond what they set out to protect.
            raise ValueError(
                f"APP_AUTOSCALE_MIN ({min_size}) must not exceed "
                f"APP_AUTOSCALE_MAX ({max_size})"
            )
        self._executor = executor
        self._forecaster = forecaster
        self._demand = demand
        self.mode = mode
        self._min = max(0, min_size)
        self._max = max_size
        self._idle_s = idle_s
        self._cooldown_s = cooldown_s
        self._hw_window_s = hw_window_s
        self._slo = slo
        self._recorder = recorder
        self._clock = clock
        self._base_target = (
            base_target
            if base_target is not None
            else getattr(executor, "pool_target", self._min)
        )
        if self._base_target > self._max:
            # The operator's configured static pool is the one size we KNOW
            # they want; silently clamping the recommendation below it would
            # misreport bci_pool_target_size and make act mode a downgrade.
            # Raise the effective ceiling instead (loudly).
            logger.warning(
                "Static pool target %d exceeds APP_AUTOSCALE_MAX %d; raising "
                "the effective autoscale ceiling to %d",
                self._base_target, self._max, self._base_target,
            )
            self._max = self._base_target
        # The standing recommendation; starts at the static configured
        # target so advise mode's first decision reads as a delta from
        # what the service would have done anyway.
        self.target = min(self._max, max(self._min, self._base_target))
        self._decisions: deque[dict] = deque(maxlen=max(1, decision_log_max))
        self._seq = 0
        self._last_decision_mono: float | None = None
        self._decisions_total = None
        if metrics is not None:
            metrics.gauge(
                "bci_pool_target_size",
                "Autoscaler-recommended warm pool size (actuated only in "
                "APP_AUTOSCALE_MODE=act; HPA-consumable either way)",
                lambda: self.target,
            )
            self._decisions_total = metrics.counter(
                "bci_autoscale_decisions_total",
                "Pool scaling decisions by direction and reason "
                "(advise mode counts them too — applied=false in the log)",
            )

    # ------------------------------------------------------------ evaluate

    def current_size(self) -> int:
        """Warm + in-flight-spawn sandboxes — what the pool is already
        committed to, the number the target is compared against."""
        ready = getattr(self._executor, "pool_ready_count", 0)
        spawning = getattr(self._executor, "pool_spawning_count", 0)
        return int(ready) + int(spawning)

    def _slo_fast_burning(self) -> bool:
        if self._slo is None or not getattr(self._slo, "objectives", ()):
            return False
        try:
            return bool(self._slo.snapshot().get("fast_burn_alerting"))
        except Exception:
            logger.exception("Autoscaler could not read SLO state")
            return False

    def evaluate(self) -> dict | None:
        """One observe→forecast→recommend pass (the supervisor calls this
        per sweep). Returns the decision dict when the target changed,
        None on hold. Never raises on the sweep path."""
        if self.mode == "off":
            return None
        forecast = self._forecaster.forecast()
        demand_rps = self._demand.rate_rps(10.0)
        needed = max(
            math.ceil(forecast["forecast_rps"] * forecast["horizon_s"]),
            self._demand.concurrency_high_water(self._hw_window_s),
        )
        now = self._clock()
        cooled = (
            self._last_decision_mono is None
            or now - self._last_decision_mono >= self._cooldown_s
        )
        reason = "forecast"
        if self._slo_fast_burning() and needed <= self.target:
            # Users are already burning budget while the forecast says the
            # pool suffices: add capacity beyond it anyway, one notch per
            # cooldown so a long burn ratchets up to max instead of jumping
            # there in one sweep. A forecast-sized jump that merely
            # coincides with a burn keeps reason="forecast" — the decision
            # log must attribute sizes to what actually produced them.
            if not cooled:
                return None
            needed = self.target + 1
            reason = "slo_burn"
        desired = min(self._max, max(self._min, needed))
        if desired > self.target:
            return self._decide("up", desired, reason, forecast, demand_rps)
        if desired < self.target:
            idle_age = self._demand.last_arrival_age_s()
            if idle_age is None or idle_age < self._idle_s or not cooled:
                return None  # shrink only after sustained idle, cooled down
            return self._decide("down", desired, "idle", forecast, demand_rps)
        return None

    def _decide(
        self, direction: str, to_size: int, reason: str, forecast: dict,
        demand_rps: float,
    ) -> dict:
        from_size = self.target
        self.target = to_size
        self._seq += 1
        self._last_decision_mono = self._clock()
        applied = False
        if self.mode == "act":
            self._executor.pool_target_override = to_size
            applied = True
        decision = {
            "decision_id": f"asd-{self._seq}",
            "ts": time.time(),
            "direction": direction,
            "from": from_size,
            "to": to_size,
            "reason": reason,
            "mode": self.mode,
            "applied": applied,
            "forecast_rps": round(forecast["forecast_rps"], 3),
            "horizon_s": round(forecast["horizon_s"], 3),
            "demand_rps": round(demand_rps, 3),
        }
        self._decisions.append(decision)
        if self._decisions_total is not None:
            self._decisions_total.inc(direction=direction, reason=reason)
        if self._recorder is not None:
            # The wide event is a COPY: the recorder stamps its own ring
            # seq on whatever dict it ingests, and the decision log's entry
            # must stay exactly what /v1/autoscale serves.
            self._recorder.record(
                {"kind": "autoscale", "name": "autoscale", **decision}
            )
        logger.info(
            "Autoscale %s: pool target %d -> %d (%s, forecast %.1f rps over "
            "%.1fs horizon, mode=%s)",
            direction, from_size, to_size, reason,
            decision["forecast_rps"], decision["horizon_s"], self.mode,
        )
        # No refill kick here: evaluate() runs inside the supervisor sweep,
        # whose own refill fires right after and reads the new target.
        return decision

    # ------------------------------------------------------------- reading

    def decisions(self, limit: int | None = None) -> list[dict]:
        """Bounded decision log, newest first."""
        out = [dict(d) for d in reversed(self._decisions)]
        if limit is not None and limit >= 0:
            out = out[:limit]
        return out

    def snapshot(self) -> dict:
        return {
            "mode": self.mode,
            "min": self._min,
            "max": self._max,
            "base_target": self._base_target,
            "target": self.target,
            "current_size": self.current_size(),
            "applied_override": getattr(
                self._executor, "pool_target_override", None
            ),
            "idle_s": self._idle_s,
            "cooldown_s": self._cooldown_s,
            "decisions_total": self._seq,
            "last_decision": (
                dict(self._decisions[-1]) if self._decisions else None
            ),
        }


def autoscale_snapshot(
    demand=None, forecaster=None, autoscaler=None, slo=None
) -> dict:
    """The ``GET /v1/autoscale`` document, shared by both transports (and
    the debug bundle) so they can never disagree. Pool-less deployments
    (the in-process local backend) have no autoscaler: the demand and
    forecast sections still answer, the autoscaler section is null.

    ``recommendation`` closes the forecast→fleet-size loop
    (docs/capacity.md): the same demand signal the pool autoscaler sizes
    sandboxes with, restated as a replica count a fleet controller can
    actuate. A single replica reports its OWN capacity as the unit; the
    router's federated ``GET /v1/autoscale`` recomputes the same document
    fleet-wide."""
    from bee_code_interpreter_tpu.observability.forecast import (
        recommend_replicas,
    )

    body: dict = {
        "demand": demand.snapshot() if demand is not None else None,
        "forecast": forecaster.forecast() if forecaster is not None else None,
    }
    if autoscaler is not None:
        snap = autoscaler.snapshot()
        body.update(snap)
        body["decisions"] = autoscaler.decisions()
    else:
        body.update(
            {
                "mode": None,
                "target": None,
                "current_size": None,
                "decisions": [],
                "last_decision": None,
            }
        )
    forecast = body["forecast"]
    demand_doc = body["demand"]
    per_replica = body.get("max") or 8
    burn = False
    if slo is not None:
        burn = bool(slo.snapshot().get("fast_burn_alerting", False))
    body["recommendation"] = recommend_replicas(
        forecast_rps=(forecast or {}).get("forecast_rps", 0.0) or 0.0,
        horizon_s=(forecast or {}).get("horizon_s", 0.0) or 0.0,
        concurrency_high_water=(demand_doc or {}).get(
            "concurrency_high_water_60s", 0.0
        )
        or 0.0,
        per_replica_capacity=per_replica,
        current_replicas=1,
        slo_fast_burn=burn,
    )
    return body
