"""Observability: tracing, fleet lifecycle journal, resource accounting,
profiling, structured logging, telemetry export, SLO burn-rate evaluation,
and the one-call debug bundle.

Dependency-free (no OTel SDK in the image), layered like ``resilience/``:
the primitives live here, the wiring lives at the edges (api/, services/,
runtime/). See docs/observability.md for the operator-facing contract.
"""

from bee_code_interpreter_tpu.observability.accounting import (
    TransferAccounting,
    UsageMeter,
    collect_transfer,
    merge_worker_usage,
    record_transfer,
    record_usage_at_edge,
    register_usage_metrics,
)
from bee_code_interpreter_tpu.observability.capacity import (
    DemandTracker,
)
from bee_code_interpreter_tpu.observability.contprof import (
    ContinuousProfiler,
    collapse_stack,
)
from bee_code_interpreter_tpu.observability.device import (
    DeviceMonitor,
)
from bee_code_interpreter_tpu.observability.forecast import (
    Forecaster,
    recommend_replicas,
)
from bee_code_interpreter_tpu.observability.fleet import (
    FleetJournal,
    find_journal,
    unwrap_executor,
)
from bee_code_interpreter_tpu.observability.flightrecorder import (
    FlightRecorder,
    event_matches,
    register_stream_metrics,
    wide_event_from_trace,
)
from bee_code_interpreter_tpu.observability.loopmon import (
    LoopMonitor,
    task_inventory,
    thread_inventory,
)
from bee_code_interpreter_tpu.observability.logging import JsonLogFormatter
from bee_code_interpreter_tpu.observability.profiling import (
    PROFILE_DIR_ENV,
    SANDBOX_PROFILE_DIR,
    DeviceProfiler,
    ProfilerUnavailable,
    ServingProfiler,
    inject_profile_env,
    profile_artifacts,
)
from bee_code_interpreter_tpu.observability.serving_trace import (
    ServingMonitor,
)
from bee_code_interpreter_tpu.observability.tracing import (
    REQUEST_ID_HEADER,
    TRACEPARENT_HEADER,
    Span,
    Trace,
    Tracer,
    TraceStore,
    activate_trace,
    current_ids,
    current_span,
    current_trace,
    format_traceparent,
    outbound_headers,
    parse_traceparent,
    span,
)

# These three import the resilience package (retry policies, breaker/drain
# types), and resilience/admission.py imports `span` from THIS package — so
# they must come after the tracing import above has bound it, or a
# resilience-first import order deadlocks on the partially-initialized module.
from bee_code_interpreter_tpu.observability.bundle import (  # noqa: E402
    build_debug_bundle,
    executor_health,
)
from bee_code_interpreter_tpu.observability.export import (  # noqa: E402
    TelemetryExporter,
    logs_payload,
    metrics_payload,
    spans_payload,
)
from bee_code_interpreter_tpu.observability.federation import (  # noqa: E402
    FederationPlane,
)
from bee_code_interpreter_tpu.observability.slo import (  # noqa: E402
    Objective,
    SloEngine,
    empty_slo_snapshot,
    parse_objectives,
    record_sli,
)

__all__ = [
    "ContinuousProfiler",
    "DemandTracker",
    "DeviceMonitor",
    "DeviceProfiler",
    "Forecaster",
    "FederationPlane",
    "FleetJournal",
    "FlightRecorder",
    "JsonLogFormatter",
    "LoopMonitor",
    "Objective",
    "PROFILE_DIR_ENV",
    "ProfilerUnavailable",
    "REQUEST_ID_HEADER",
    "SANDBOX_PROFILE_DIR",
    "ServingMonitor",
    "ServingProfiler",
    "SloEngine",
    "activate_trace",
    "TelemetryExporter",
    "TransferAccounting",
    "UsageMeter",
    "build_debug_bundle",
    "collapse_stack",
    "collect_transfer",
    "empty_slo_snapshot",
    "event_matches",
    "executor_health",
    "find_journal",
    "logs_payload",
    "metrics_payload",
    "parse_objectives",
    "register_stream_metrics",
    "spans_payload",
    "task_inventory",
    "thread_inventory",
    "wide_event_from_trace",
    "inject_profile_env",
    "merge_worker_usage",
    "profile_artifacts",
    "recommend_replicas",
    "record_sli",
    "record_transfer",
    "record_usage_at_edge",
    "register_usage_metrics",
    "unwrap_executor",
    "TRACEPARENT_HEADER",
    "Span",
    "Trace",
    "Tracer",
    "TraceStore",
    "current_ids",
    "current_span",
    "current_trace",
    "format_traceparent",
    "outbound_headers",
    "parse_traceparent",
    "span",
]
