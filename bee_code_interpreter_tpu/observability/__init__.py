"""Observability: distributed tracing, trace retention, structured logging.

Dependency-free (no OTel SDK in the image), layered like ``resilience/``:
the primitives live here, the wiring lives at the edges (api/, services/,
runtime/). See docs/observability.md for the operator-facing contract.
"""

from bee_code_interpreter_tpu.observability.logging import JsonLogFormatter
from bee_code_interpreter_tpu.observability.tracing import (
    REQUEST_ID_HEADER,
    TRACEPARENT_HEADER,
    Span,
    Trace,
    Tracer,
    TraceStore,
    current_ids,
    current_span,
    current_trace,
    format_traceparent,
    outbound_headers,
    parse_traceparent,
    span,
)

__all__ = [
    "JsonLogFormatter",
    "REQUEST_ID_HEADER",
    "TRACEPARENT_HEADER",
    "Span",
    "Trace",
    "Tracer",
    "TraceStore",
    "current_ids",
    "current_span",
    "current_trace",
    "format_traceparent",
    "outbound_headers",
    "parse_traceparent",
    "span",
]
