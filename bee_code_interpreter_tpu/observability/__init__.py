"""Observability: tracing, fleet lifecycle journal, resource accounting,
profiling, structured logging.

Dependency-free (no OTel SDK in the image), layered like ``resilience/``:
the primitives live here, the wiring lives at the edges (api/, services/,
runtime/). See docs/observability.md for the operator-facing contract.
"""

from bee_code_interpreter_tpu.observability.accounting import (
    TransferAccounting,
    UsageMeter,
    collect_transfer,
    merge_worker_usage,
    record_transfer,
    record_usage_at_edge,
    register_usage_metrics,
)
from bee_code_interpreter_tpu.observability.fleet import (
    FleetJournal,
    find_journal,
    unwrap_executor,
)
from bee_code_interpreter_tpu.observability.logging import JsonLogFormatter
from bee_code_interpreter_tpu.observability.profiling import (
    PROFILE_DIR_ENV,
    SANDBOX_PROFILE_DIR,
    ProfilerUnavailable,
    ServingProfiler,
    inject_profile_env,
    profile_artifacts,
)
from bee_code_interpreter_tpu.observability.tracing import (
    REQUEST_ID_HEADER,
    TRACEPARENT_HEADER,
    Span,
    Trace,
    Tracer,
    TraceStore,
    current_ids,
    current_span,
    current_trace,
    format_traceparent,
    outbound_headers,
    parse_traceparent,
    span,
)

__all__ = [
    "FleetJournal",
    "JsonLogFormatter",
    "PROFILE_DIR_ENV",
    "ProfilerUnavailable",
    "REQUEST_ID_HEADER",
    "SANDBOX_PROFILE_DIR",
    "ServingProfiler",
    "TransferAccounting",
    "UsageMeter",
    "collect_transfer",
    "find_journal",
    "inject_profile_env",
    "merge_worker_usage",
    "profile_artifacts",
    "record_transfer",
    "record_usage_at_edge",
    "register_usage_metrics",
    "unwrap_executor",
    "TRACEPARENT_HEADER",
    "Span",
    "Trace",
    "Tracer",
    "TraceStore",
    "current_ids",
    "current_span",
    "current_trace",
    "format_traceparent",
    "outbound_headers",
    "parse_traceparent",
    "span",
]
