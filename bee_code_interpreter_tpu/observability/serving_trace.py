"""Serving-engine deep observability (docs/observability.md "Serving
observability").

The continuous batcher (models/serving.py) had only aggregate gauges: an
operator could see occupancy fall but not WHICH request stalled admission,
what a prompt's prefix-cache credit was, or how a speculative round's
accepts distributed across the batch. :class:`ServingMonitor` is the
per-request + per-step layer over the same signal stack the control plane
already uses — no parallel pipeline:

- **Per-request lifecycle trace**: every generation request gets a span
  tree (``queued`` → ``prefill`` [/ ``prefill_chunk`` windows] →
  ``decode``) on its own :class:`~.tracing.Trace`, landed in the shared
  ``TraceStore`` so ``GET /v1/traces/{id}`` serves batcher requests next to
  executor requests.
- **One ``kind="serving"`` wide event per finished request** — trace-id
  correlated with the trace above and with the ``bci_serving_ttft_seconds``
  exemplar (the batcher observes TTFT under the request's activated trace)
  — recorded into the flight recorder, whose OTLP-logs sink ships it with
  the exporter's exact drop accounting.
- **A bounded ring of step records**: occupancy, free/parked/held pages,
  prefill vs decode token counts, speculative accept/reject counts, page
  churn, and step wall time — served raw at ``GET /v1/serving`` so a
  tokens/sec dip can be read step by step instead of inferred from gauges.
- **KV-cache telemetry** via the batcher's ``kv_telemetry()``
  (ops/paged_kv_cache.pool_telemetry): slot-level internal fragmentation
  and prefix-chain reuse hits/misses.

The monitor is duck-typed from the batcher/engine side (they call ``on_*``
hooks when one is attached and pay nothing otherwise), so ``models/`` never
imports this package. Hooks may fire from a worker thread (``POST
/v1/profile`` steps the engine in ``asyncio.to_thread``); all record state
is lock-guarded and flight-recorder delivery hops to the loop when the
caller isn't on it.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque
from contextlib import contextmanager

from bee_code_interpreter_tpu.observability.tracing import (
    Trace,
    activate_trace,
)

# finish reason -> wide-event outcome. Client-visible completions (eos /
# stop / length / constraint) are "ok"; the rest name their failure mode so
# `GET /v1/events?outcome=...` and the OTLP-logs severity mapping can
# separate normal retirement from trouble.
_FINISH_OUTCOME = {
    "eos": "ok",
    "stop": "ok",
    "length": "ok",
    "constraint": "ok",
    "error": "error",
    "cancelled": "cancelled",
    "preempted": "preempted",
}


class _RequestRecord:
    """Mutable per-request state while a generation request is live."""

    __slots__ = (
        "req", "trace", "prefill_span", "decode_span", "t_submit",
        "submit_unix", "prompt_tokens", "max_new_tokens", "pages",
        "prefix_pages", "adapter", "speculative", "interleaved",
        "prefill_chunks", "prefill_tokens", "spec_accepted",
        "spec_rejected", "queued_ms", "requeues", "ttft_ms",
        "output_tokens", "finish", "outcome", "duration_ms", "error",
    )

    def __init__(self, req: int, trace: Trace, t_submit: float) -> None:
        self.req = req
        self.trace = trace
        self.prefill_span = None
        self.decode_span = None
        self.t_submit = t_submit
        self.submit_unix = trace.root.start_unix
        self.prompt_tokens = 0
        self.max_new_tokens = 0
        self.pages = 0
        self.prefix_pages = 0
        self.adapter = None
        self.speculative = False
        self.interleaved = False
        self.prefill_chunks = 0
        self.prefill_tokens = 0
        self.spec_accepted = 0
        self.spec_rejected = 0
        self.queued_ms = None
        self.requeues = 0
        self.ttft_ms = None
        self.output_tokens = 0
        self.finish = None
        self.outcome = None
        self.duration_ms = None
        self.error = None

    def to_dict(self, active: bool) -> dict:
        return {
            "request_id": self.req,
            "trace_id": self.trace.trace_id,
            "ts": self.submit_unix,
            "active": active,
            "prompt_tokens": self.prompt_tokens,
            "max_new_tokens": self.max_new_tokens,
            "output_tokens": self.output_tokens,
            "pages": self.pages,
            "prefix_hit_pages": self.prefix_pages,
            "adapter": self.adapter,
            "speculative": self.speculative,
            "interleaved": self.interleaved,
            "prefill_chunks": self.prefill_chunks,
            "spec_accepted": self.spec_accepted,
            "spec_rejected": self.spec_rejected,
            "queued_ms": self.queued_ms,
            "requeues": self.requeues,
            "ttft_ms": self.ttft_ms,
            "finish": self.finish,
            "outcome": self.outcome,
            "duration_ms": (
                self.duration_ms
                if self.duration_ms is not None
                else (time.monotonic() - self.t_submit) * 1000.0
            ),
            "error": self.error,
        }


class ServingMonitor:
    """Per-request lifecycle tracing + step/KV-cache telemetry for the
    serving engine. Constructed by the composition root next to the flight
    recorder (metrics register immediately; gauges read 0 until an engine
    attaches); :meth:`attach` binds a ``models.engine.Engine`` or bare
    ``ContinuousBatcher`` and injects the monitor into its hooks.
    """

    def __init__(
        self,
        *,
        metrics=None,
        store=None,  # tracing.TraceStore shared with the edges
        recorder=None,  # flightrecorder.FlightRecorder
        max_steps: int = 512,
        max_requests: int = 256,
    ) -> None:
        self._store = store
        self._recorder = recorder
        self._lock = threading.Lock()
        self._live: dict[int, _RequestRecord] = {}
        self._done: deque[_RequestRecord] = deque(maxlen=max(1, max_requests))
        self._steps: deque[dict] = deque(maxlen=max(1, max_steps))
        self._step_seq = 0
        self._tickets: dict[int, tuple[float, int]] = {}  # ticket -> (t, requeues)
        # queue wait staged by on_ticket_admitting for the on_submit fired
        # inside the engine's synchronous batcher.submit call (one slot:
        # admissions cannot interleave)
        self._pending_admission: tuple[float, int] | None = None
        self._engine = None
        self._batcher = None
        self._loop: asyncio.AbstractEventLoop | None = None
        # lifetime totals (survive record-ring eviction)
        self._spec_accepted_total = 0
        self._spec_rejected_total = 0
        self._finished_total = 0
        self._rejected_total = 0
        self._requeued_total = 0
        self._preempted_total = 0
        self._requests_total = None
        self._request_seconds = None
        self._preemptions_total = None
        self._spec_tokens_total = None
        if metrics is not None:
            self._requests_total = metrics.counter(
                "bci_serving_requests_total",
                "Serving requests finished, by done reason",
            )
            self._request_seconds = metrics.histogram(
                "bci_serving_request_seconds",
                "Serving request wall time, queue wait included",
            )
            self._preemptions_total = metrics.counter(
                "bci_serving_preemptions_total",
                "Mid-prefill admissions evicted back to the queue",
            )
            self._spec_tokens_total = metrics.counter(
                "bci_serving_spec_tokens_total",
                "Speculative draft tokens verified, by result",
            )
            metrics.gauge(
                "bci_serving_spec_accept_ratio",
                "Draft tokens accepted / proposed (0 with no speculative "
                "traffic yet)",
                self.spec_accept_ratio,
            )
            metrics.gauge(
                "bci_serving_prefix_hit_ratio",
                "Prefix-cache lookups that reused at least one page (0-1)",
                self.prefix_hit_ratio,
            )
            metrics.gauge(
                "bci_serving_page_fragmentation",
                "Internal fragmentation of allocated KV pages: 1 - "
                "used/allocated slots over active rows",
                self.page_fragmentation,
            )

    # ------------------------------------------------------------ wiring

    def attach(self, target) -> None:
        """Bind a ``models.engine.Engine`` (or a bare ``ContinuousBatcher``)
        and inject this monitor into its hooks. Call BEFORE submitting —
        requests already in flight are not traced retroactively."""
        batcher = getattr(target, "batcher", target)
        self._engine = target if batcher is not target else None
        self._batcher = batcher
        batcher.set_monitor(self)
        if self._engine is not None:
            self._engine.set_monitor(self)
        try:
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            pass

    @property
    def available(self) -> bool:
        """True once an engine/batcher is attached — the ``POST /v1/profile
        target=serving`` gate (501 when nothing is attached)."""
        return self._batcher is not None

    def step(self) -> None:
        """One engine (or batcher) step — the stepper surface
        :class:`~.profiling.ServingProfiler` captures through."""
        if self._engine is not None:
            self._engine.step()
        elif self._batcher is not None:
            self._batcher.step()
        else:
            raise RuntimeError("no serving engine attached")

    # ----------------------------------------------------- gauge callbacks

    def spec_accept_ratio(self) -> float:
        proposed = self._spec_accepted_total + self._spec_rejected_total
        return self._spec_accepted_total / proposed if proposed else 0.0

    def prefix_hit_ratio(self) -> float:
        if self._batcher is None:
            return 0.0
        stats = self._batcher.prefix_stats
        lookups = stats.get("lookups", 0)
        return stats.get("hits", 0) / lookups if lookups else 0.0

    def page_fragmentation(self) -> float:
        if self._batcher is None:
            return 0.0
        return float(self._batcher.kv_telemetry()["fragmentation"])

    # ------------------------------------------------------ batcher hooks

    def on_submit(
        self,
        req: int,
        *,
        prompt_tokens: int,
        max_new_tokens: int,
        pages: int,
        prefix_pages: int,
        adapter: int | None,
        speculative: bool,
        interleaved: bool,
    ) -> None:
        trace = Trace(None, "serving.request", request_id=f"serving-{req}")
        rec = _RequestRecord(req, trace, time.monotonic())
        rec.prompt_tokens = prompt_tokens
        rec.max_new_tokens = max_new_tokens
        rec.pages = pages
        rec.prefix_pages = prefix_pages
        rec.adapter = adapter
        rec.speculative = speculative
        rec.interleaved = interleaved
        with self._lock:
            pending = self._pending_admission
            self._pending_admission = None
            if pending is not None:
                # the request's wall clock starts at ENGINE intake:
                # backdate the root and hang the queued span off it BEFORE
                # anything else happens, so duration_ms and TTFT are the
                # user-perceived numbers on BOTH admission paths (the
                # blocking path fixes TTFT inside this very submit call)
                t_queued, requeues = pending
                wait_s = max(0.0, rec.t_submit - t_queued)
                trace.root.start_mono -= wait_s
                trace.root.start_unix -= wait_s
                rec.submit_unix = trace.root.start_unix
                rec.t_submit -= wait_s
                rec.requeues = requeues
                rec.queued_ms = wait_s * 1000.0
                s = trace.start_span(
                    "queued", parent_id=trace.root.span_id
                )
                s.start_mono -= wait_s
                s.start_unix -= wait_s
                if requeues:
                    s.attributes["requeues"] = str(requeues)
                trace.end_span(s)
            rec.prefill_span = trace.start_span(
                "prefill", parent_id=trace.root.span_id
            )
            self._live[req] = rec

    def on_prefill_window(
        self, req: int, *, tokens: int, duration_s: float
    ) -> None:
        with self._lock:
            rec = self._live.get(req)
            if rec is None:
                return
            rec.prefill_chunks += 1
            rec.prefill_tokens += tokens
            parent = rec.prefill_span or rec.trace.root
            s = rec.trace.start_span("prefill_chunk", parent_id=parent.span_id)
            # backdate: the window already ran (the batcher timed it)
            s.start_mono -= duration_s
            s.start_unix -= duration_s
            s.attributes["tokens"] = str(tokens)
            rec.trace.end_span(s)

    def on_first_token(self, req: int) -> None:
        with self._lock:
            rec = self._live.get(req)
            if rec is None:
                return
            rec.ttft_ms = (time.monotonic() - rec.t_submit) * 1000.0
            if rec.prefill_span is not None:
                rec.prefill_span.attributes["chunks"] = str(
                    rec.prefill_chunks or 1
                )
                rec.trace.end_span(rec.prefill_span)
            rec.decode_span = rec.trace.start_span(
                "decode", parent_id=rec.trace.root.span_id
            )

    def on_commit(self, req: int, *, accepted: int, rejected: int) -> None:
        with self._lock:
            self._spec_accepted_total += accepted
            self._spec_rejected_total += rejected
            rec = self._live.get(req)
            if rec is not None:
                rec.spec_accepted += accepted
                rec.spec_rejected += rejected
        if self._spec_tokens_total is not None:
            if accepted:
                self._spec_tokens_total.inc(accepted, result="accepted")
            if rejected:
                self._spec_tokens_total.inc(rejected, result="rejected")

    def on_done(
        self, req: int, reason: str, *, tokens: int, error: str | None = None
    ) -> None:
        with self._lock:
            rec = self._live.pop(req, None)
            if rec is None:
                return
            rec.finish = reason
            rec.outcome = _FINISH_OUTCOME.get(reason, reason)
            rec.output_tokens = tokens
            rec.error = error
            status = "error" if rec.outcome == "error" else "ok"
            if rec.prefill_span is not None and rec.prefill_span.duration_s is None:
                # never produced a first token (error/cancel mid-prefill)
                rec.trace.end_span(rec.prefill_span, status=status)
            if rec.decode_span is not None:
                rec.decode_span.attributes["tokens"] = str(tokens)
                rec.trace.end_span(rec.decode_span)
            rec.trace.end_span(rec.trace.root, status=status, error=error)
            rec.duration_ms = rec.trace.root.duration_s * 1000.0
            self._done.append(rec)
            self._finished_total += 1
            if reason == "preempted":
                self._preempted_total += 1
        if self._requests_total is not None:
            self._requests_total.inc(outcome=reason)
        if self._request_seconds is not None:
            # observed under the request's own trace so the exemplar on the
            # duration histogram jumps straight to /v1/traces/{id}
            with activate_trace(rec.trace):
                self._request_seconds.observe(rec.trace.root.duration_s)
        if self._store is not None:
            self._store.add(rec.trace)
        self._emit(self._wide_event(rec))

    def on_preempt(self, req: int) -> None:
        if self._preemptions_total is not None:
            self._preemptions_total.inc()
        self.on_done(req, "preempted", tokens=0)

    def on_step(self, record: dict) -> None:
        with self._lock:
            self._step_seq += 1
            record["seq"] = self._step_seq
            record["ts"] = time.time()
            if self._engine is not None:
                record["queue_depth"] = self._engine.pending
            self._steps.append(record)

    # ------------------------------------------------------- engine hooks

    def on_ticket_queued(self, ticket: int) -> None:
        with self._lock:
            prior = self._tickets.get(ticket)
            self._tickets[ticket] = (
                time.monotonic(), prior[1] if prior else 0
            )

    def on_ticket_requeued(self, ticket: int) -> None:
        with self._lock:
            # a CapacityError mid-admission bounces AFTER on_ticket_admitting
            # staged the wait: recover the original clock from the slot so
            # the eventual queued span spans the WHOLE wait
            entry = self._tickets.get(ticket) or self._pending_admission
            self._pending_admission = None
            t, n = entry if entry is not None else (time.monotonic(), 0)
            self._tickets[ticket] = (t, n + 1)
            self._requeued_total += 1
        self._emit(
            {
                "kind": "serving",
                "name": "serving.requeue",
                "outcome": "requeued",
                "ticket": ticket,
            }
        )

    def on_ticket_admitting(self, ticket: int) -> None:
        """The engine is about to hand this ticket to the batcher: stage
        its queue wait so the ``on_submit`` fired INSIDE that synchronous
        call can start the request's clock at engine intake — TTFT and
        duration_ms include queue wait on both admission paths (blocking
        submit fixes TTFT before the call returns, so backdating after it
        would be too late)."""
        with self._lock:
            self._pending_admission = self._tickets.pop(ticket, None)

    def on_ticket_rejected(self, reason: str) -> None:
        with self._lock:
            self._rejected_total += 1
        self._emit(
            {
                "kind": "serving",
                "name": "serving.reject",
                "outcome": "rejected",
                "reason": reason,
            }
        )

    def on_ticket_failed(self, ticket: int, error: str) -> None:
        with self._lock:
            self._tickets.pop(ticket, None)
            self._pending_admission = None
        self._emit(
            {
                "kind": "serving",
                "name": "serving.admit_error",
                "outcome": "error",
                "ticket": ticket,
                "error": error,
            }
        )

    def on_ticket_cancelled(self, ticket: int) -> None:
        with self._lock:
            self._tickets.pop(ticket, None)

    # ------------------------------------------------------------ queries

    @contextmanager
    def exemplar_context(self, req: int):
        """Ambient-trace context for a live request, so a histogram
        observation made inside it (the batcher's TTFT) records this
        request's trace id as its exemplar."""
        with self._lock:
            rec = self._live.get(req)
        if rec is None:
            yield None
            return
        with activate_trace(rec.trace):
            yield rec.trace

    def snapshot(self, steps: int = 32) -> dict:
        """The ``GET /v1/serving`` body: engine/batcher aggregates, KV-cache
        telemetry, lifetime totals, and the last ``steps`` step records."""
        with self._lock:
            live = [r.to_dict(active=True) for r in self._live.values()]
            recorded = len(self._done)
            recent_steps = (
                list(self._steps)[-steps:] if steps > 0 else []
            )
            totals = {
                "finished": self._finished_total,
                "rejected": self._rejected_total,
                "requeued": self._requeued_total,
                "preempted": self._preempted_total,
                "spec_accepted": self._spec_accepted_total,
                "spec_rejected": self._spec_rejected_total,
            }
        body: dict = {
            "attached": self.available,
            "totals": {
                **totals,
                "spec_accept_ratio": self.spec_accept_ratio(),
                "prefix_hit_ratio": self.prefix_hit_ratio(),
            },
            "requests": {"active": live, "recorded": recorded},
            "steps": {
                "recorded": self._step_seq,
                "retained": len(self._steps),
                "last": recent_steps,
            },
        }
        if self._batcher is not None:
            body["batcher"] = self._batcher.stats
            body["kv_cache"] = self._batcher.kv_telemetry()
        if self._engine is not None:
            body["queue_depth"] = self._engine.pending
        return body

    def requests(
        self,
        *,
        outcome: str | None = None,
        finish: str | None = None,
        adapter: int | None = None,
        active: bool | None = None,
        min_duration_ms: float | None = None,
        limit: int | None = None,
    ) -> list[dict]:
        """Filtered per-request records, newest first (live requests before
        finished ones) — the ``GET /v1/serving/requests`` body."""
        if limit is not None and limit <= 0:
            return []  # same limit semantics as FlightRecorder.events
        with self._lock:
            rows = [r.to_dict(active=True) for r in self._live.values()]
            rows += [r.to_dict(active=False) for r in reversed(self._done)]
        out: list[dict] = []
        for row in rows:
            if outcome is not None and row["outcome"] != outcome:
                continue
            if finish is not None and row["finish"] != finish:
                continue
            if adapter is not None and row["adapter"] != adapter:
                continue
            if active is not None and row["active"] != active:
                continue
            if min_duration_ms is not None and (
                row["duration_ms"] is None
                or row["duration_ms"] < min_duration_ms
            ):
                continue
            out.append(row)
            if limit is not None and len(out) >= limit:
                break
        return out

    # ------------------------------------------------------------ private

    def _wide_event(self, rec: _RequestRecord) -> dict:
        serving = {
            "prompt_tokens": rec.prompt_tokens,
            "output_tokens": rec.output_tokens,
            "max_new_tokens": rec.max_new_tokens,
            "pages": rec.pages,
            "prefix_hit_pages": rec.prefix_pages,
            "adapter": rec.adapter,
            "speculative": rec.speculative,
            "interleaved": rec.interleaved,
            "spec_accepted": rec.spec_accepted,
            "spec_rejected": rec.spec_rejected,
            "requeues": rec.requeues,
            "ttft_ms": rec.ttft_ms,
            "finish": rec.finish,
        }
        event: dict = {
            "kind": "serving",
            "ts": rec.submit_unix,
            "name": "serving.request",
            "trace_id": rec.trace.trace_id,
            "request_id": rec.trace.request_id,
            "outcome": rec.outcome,
            "duration_ms": rec.duration_ms,
            "timings_ms": rec.trace.stage_ms(),
            "serving": serving,
        }
        if rec.error is not None:
            event["error"] = rec.error
        return event

    def arm_loop(self, loop: asyncio.AbstractEventLoop | None = None) -> None:
        """Bind the loop wide events are delivered on when a hook fires
        off-loop. ``attach()`` arms it opportunistically and ``_emit``
        refreshes it whenever it runs on-loop, but a monitor attached
        BEFORE the loop exists (sync composition) needs this explicit call
        — ``ApplicationContext.start_observability`` makes it."""
        self._loop = (
            loop if loop is not None else asyncio.get_running_loop()
        )

    def _emit(self, event: dict) -> None:
        if self._recorder is None:
            return
        try:
            # remember the loop whenever one is running here, so hooks
            # that later fire off-loop know where to deliver
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            # off-loop caller (profiler capture thread, bench): hand the
            # event to the recorder's loop — its follower queues are
            # asyncio objects a foreign thread must not poke directly
            loop = self._loop
            if loop is not None and loop.is_running():
                loop.call_soon_threadsafe(self._recorder.record, event)
                return
            # no loop was ever armed: nothing async can be following the
            # recorder either (subscribing requires that loop), so the
            # direct call only touches the ring
        self._recorder.record(event)
