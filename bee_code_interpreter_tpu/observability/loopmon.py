"""Event-loop health: lag probe, stall detector, live task inventory.

The service is ONE asyncio loop; anything that blocks it — an accidental
sync call, a pathological parse, a GC storm — stalls every in-flight
request at once, and nothing in the request-scoped telemetry can see it
(the stalled requests just look uniformly slow). The
:class:`LoopMonitor` measures the loop itself: a probe task arms a timer,
sleeps, and reads how late the wakeup was. The lag feeds
``bci_event_loop_lag_seconds``; a wakeup later than the stall threshold
additionally captures an asyncio task-stack dump — who was running, who
was waiting, where — into a ``kind="loop_stall"`` wide event, and keeps
the latest dump for ``GET /v1/debug/tasks``.

The probe math is clock-injectable (``clock=``) so tests drive arm/tick
by hand with a ManualClock; production uses the loop's own time via the
background task.
"""

from __future__ import annotations

import asyncio
import logging
import sys
import time
from pathlib import Path

logger = logging.getLogger(__name__)

# Loop lag lives decades below request latency: sub-ms when healthy, tens
# of ms under pressure, seconds only when something is very wrong.
LAG_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0,
)

_REPO_ROOT = str(Path(__file__).resolve().parent.parent.parent)


def _short_path(filename: str) -> str:
    if filename.startswith(_REPO_ROOT):
        return filename[len(_REPO_ROOT):].lstrip("/")
    return filename


def task_inventory(max_tasks: int = 256, max_frames: int = 8) -> dict:
    """The live asyncio task set with per-task (truncated) stacks — the
    "what is the loop doing right now" answer ``GET /v1/debug/tasks``
    serves. Outside a running loop (scripts, teardown) it answers honestly
    empty instead of raising."""
    try:
        tasks = asyncio.all_tasks()
    except RuntimeError:
        return {"count": 0, "truncated": False, "tasks": []}
    inventory = []
    for task in list(tasks)[:max_tasks]:
        coro = task.get_coro()
        entry: dict = {
            "name": task.get_name(),
            "coro": getattr(coro, "__qualname__", None) or repr(coro)[:120],
            "done": task.done(),
        }
        try:
            frames = task.get_stack(limit=max_frames)
        except RuntimeError:
            frames = []
        entry["stack"] = [
            f"{_short_path(f.f_code.co_filename)}:{f.f_lineno} "
            f"{f.f_code.co_name}"
            for f in frames
        ]
        inventory.append(entry)
    return {
        "count": len(tasks),
        "truncated": len(tasks) > max_tasks,
        "tasks": inventory,
    }


class LoopMonitor:
    """Lag probe + stall detector over the running event loop.

    ``arm()`` notes when the next wakeup *should* happen; ``tick()``
    measures how late it actually was. The background task does exactly
    that on a cadence; tests call the pair directly under a ManualClock.
    """

    def __init__(
        self,
        *,
        interval_s: float = 0.25,
        stall_threshold_s: float = 0.5,
        recorder=None,  # FlightRecorder for kind="loop_stall" events
        metrics=None,
        clock=time.monotonic,
        max_stall_tasks: int = 64,
    ) -> None:
        self._interval_s = max(0.01, interval_s)
        self.enabled = interval_s > 0
        self._stall_threshold_s = stall_threshold_s
        self._recorder = recorder
        self._clock = clock
        self._max_stall_tasks = max_stall_tasks
        self._expected: float | None = None
        self._task: asyncio.Task | None = None
        self.probes = 0
        self.stalls = 0
        self.last_lag_s = 0.0
        self.max_lag_s = 0.0
        self.last_probe_unix: float | None = None
        self.last_stall: dict | None = None
        self._lag_seconds = None
        self._stalls_total = None
        if metrics is not None:
            self._lag_seconds = metrics.histogram(
                "bci_event_loop_lag_seconds",
                "How late the event-loop lag probe's wakeups fire: the time "
                "every in-flight request was stalled on top of its own work",
                buckets=LAG_BUCKETS,
            )
            self._stalls_total = metrics.counter(
                "bci_loop_stalls_total",
                "Event-loop stalls (lag over the configured threshold) that "
                "triggered a task-stack capture",
            )

    # -------------------------------------------------------------- probe

    def arm(self) -> None:
        """Note when the next :meth:`tick` *should* run (now + interval)."""
        self._expected = self._clock() + self._interval_s

    def tick(self) -> float:
        """Measure how late this wakeup was relative to :meth:`arm`;
        record the lag and run stall detection. Returns the lag."""
        now = self._clock()
        lag = max(0.0, now - self._expected) if self._expected is not None else 0.0
        self._expected = None
        self.probes += 1
        self.last_lag_s = lag
        self.max_lag_s = max(self.max_lag_s, lag)
        self.last_probe_unix = time.time()
        if self._lag_seconds is not None:
            self._lag_seconds.observe(lag)
        if self._stall_threshold_s > 0 and lag >= self._stall_threshold_s:
            self._on_stall(lag)
        return lag

    def _on_stall(self, lag: float) -> None:
        self.stalls += 1
        if self._stalls_total is not None:
            self._stalls_total.inc()
        dump = task_inventory(max_tasks=self._max_stall_tasks)
        self.last_stall = {
            "ts": time.time(),
            "lag_s": lag,
            "threshold_s": self._stall_threshold_s,
            "tasks": dump,
        }
        logger.warning(
            "Event loop stalled %.3fs (threshold %.3fs); captured %d task "
            "stack(s)",
            lag,
            self._stall_threshold_s,
            dump["count"],
        )
        if self._recorder is not None:
            self._recorder.record(
                {
                    "kind": "loop_stall",
                    "outcome": "stall",
                    "duration_ms": lag * 1000.0,
                    "lag_s": lag,
                    "threshold_s": self._stall_threshold_s,
                    "tasks": dump,
                }
            )

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        """Start the background probe (requires a running loop); a no-op
        when the monitor is disabled (interval 0)."""
        if not self.enabled:
            return
        if self._task is None or self._task.done():
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while True:
            self.arm()
            await asyncio.sleep(self._interval_s)
            self.tick()

    # ----------------------------------------------------------- operator

    @property
    def running(self) -> bool:
        return self._task is not None and not self._task.done()

    def snapshot(self) -> dict:
        """Monitor state for ``/healthz?verbose=1`` / the debug bundle /
        ``GET /v1/debug/tasks``."""
        return {
            "enabled": self.enabled,
            "running": self.running,
            "interval_s": self._interval_s,
            "stall_threshold_s": self._stall_threshold_s,
            "probes": self.probes,
            "last_lag_ms": self.last_lag_s * 1000.0,
            "max_lag_ms": self.max_lag_s * 1000.0,
            "stalls": self.stalls,
            "last_stall": self.last_stall,
        }


def thread_inventory(max_frames: int = 8) -> list[dict]:
    """Every OS thread's current (truncated) stack via
    ``sys._current_frames`` — the non-asyncio half of "what is this
    process doing", served next to the task inventory."""
    out = []
    for thread_id, frame in sys._current_frames().items():
        stack = []
        f = frame
        while f is not None and len(stack) < max_frames:
            stack.append(
                f"{_short_path(f.f_code.co_filename)}:{f.f_lineno} "
                f"{f.f_code.co_name}"
            )
            f = f.f_back
        out.append({"thread_id": thread_id, "stack": stack})
    return out
