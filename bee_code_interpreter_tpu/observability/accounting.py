"""Per-execution resource accounting (docs/observability.md).

Answers "what did this request cost" at three layers without any external
agent:

- **In the sandbox** (``runtime/executor_core.py``): every execution is
  measured with ``resource.getrusage(RUSAGE_CHILDREN)`` deltas + wall clock
  + workspace byte deltas and returns a ``usage`` block on the wire next to
  stdout/stderr.
- **On the data plane** (``services/executor_http_driver.py``): upload and
  download byte counts are collected into an ambient per-request
  :class:`TransferAccounting` (contextvars, same no-op-off-the-request-path
  design as ``tracing.span``), because only the driver sees the streamed
  bytes.
- **At the edge** (``api/http_server.py`` / ``api/grpc_server.py``): the
  merged block lands in ``ExecuteResponse.usage``, on the request's root
  trace span as ``usage.*`` attributes, and in the
  ``bci_execution_cpu_seconds`` / ``bci_execution_peak_rss_bytes``
  histograms — so the per-request view and the Prometheus view agree by
  construction.

Semantics worth knowing:

- ``cpu_user_s`` / ``cpu_system_s`` are *deltas* over the execution (they
  include a dependency install the execution triggered — pip time is part
  of what the request cost).
- ``max_rss_bytes`` is the kernel's child high-water mark, not a delta
  (RUSAGE maxrss cannot be differenced); in a single-use sandbox that IS
  the execution's peak, which is the deployment this exists for. In the
  in-process local backend (dev / fallback mode) many executions share one
  measuring process, so overlapping requests can cross-attribute CPU and
  the RSS figure is the process-lifetime peak — approximate, by design.
- Gang executions (multi-host pod groups) merge per-worker blocks:
  CPU sums, RSS takes the max, wall takes the max (SPMD workers run
  concurrently).
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass

try:  # POSIX only; the service targets Linux but must import anywhere
    import resource as _resource
except ImportError:  # pragma: no cover - non-POSIX dev machine
    _resource = None

# RSS buckets (bytes): 16 MiB .. 64 GiB — a python hello-world child sits
# near the bottom, a TPU-host model load near the top.
RSS_BUCKETS = tuple(float(1 << p) for p in range(24, 37, 2))

# Keys the edge copies onto the trace root span (attributes are strings).
_SPAN_USAGE_KEYS = (
    "wall_s",
    "cpu_user_s",
    "cpu_system_s",
    "max_rss_bytes",
    "workspace_bytes_written",
    "files_changed",
    "uploaded_bytes",
    "uploaded_files",
    "downloaded_bytes",
    "downloaded_files",
)


class UsageMeter:
    """Measures one sandbox execution: rusage-children delta + wall clock.

    Usage::

        meter = UsageMeter()           # snapshot taken here
        ... run the subprocess ...
        usage = meter.finish(...)      # delta + workspace accounting
    """

    def __init__(self) -> None:
        self._t0 = time.monotonic()
        self._ru0 = (
            _resource.getrusage(_resource.RUSAGE_CHILDREN)
            if _resource is not None
            else None
        )

    def finish(
        self,
        workspace_bytes_written: int = 0,
        files_changed: int = 0,
        deps_installed: list[str] | None = None,
    ) -> dict:
        usage: dict = {
            "wall_s": time.monotonic() - self._t0,
            "workspace_bytes_written": workspace_bytes_written,
            "files_changed": files_changed,
            "deps_installed": list(deps_installed or []),
        }
        if self._ru0 is not None:
            ru1 = _resource.getrusage(_resource.RUSAGE_CHILDREN)
            usage["cpu_user_s"] = max(0.0, ru1.ru_utime - self._ru0.ru_utime)
            usage["cpu_system_s"] = max(0.0, ru1.ru_stime - self._ru0.ru_stime)
            # ru_maxrss is KiB on Linux; a high-water mark, not a delta.
            usage["max_rss_bytes"] = ru1.ru_maxrss * 1024
        return usage


def merge_worker_usage(blocks: list[dict | None]) -> dict:
    """Aggregate per-worker ``usage`` blocks from an SPMD gang into one
    request-level block: CPU sums (total compute paid), RSS and wall take
    the max (workers run concurrently), byte counts sum (each worker wrote
    its own outputs). Missing blocks (an old executor server) drop out."""
    merged: dict = {}
    deps: list[str] = []
    for block in blocks:
        if not block:
            continue
        for key in ("cpu_user_s", "cpu_system_s"):
            if key in block:
                merged[key] = merged.get(key, 0.0) + float(block[key])
        for key in ("max_rss_bytes", "wall_s"):
            if key in block:
                merged[key] = max(merged.get(key, 0), block[key])
        for key in ("workspace_bytes_written", "files_changed"):
            if key in block:
                merged[key] = merged.get(key, 0) + int(block[key])
        for dep in block.get("deps_installed", ()):
            if dep not in deps:
                deps.append(dep)
    if deps or merged:
        merged["deps_installed"] = deps
    return merged


# ------------------------------------------------- data-plane byte accounting

_current_transfer: ContextVar["TransferAccounting | None"] = ContextVar(
    "bci_transfer_accounting", default=None
)


@dataclass
class TransferAccounting:
    """Bytes/files moved over the sandbox data plane for one execution."""

    uploaded_bytes: int = 0
    uploaded_files: int = 0
    downloaded_bytes: int = 0
    downloaded_files: int = 0

    def as_dict(self) -> dict:
        return {
            "uploaded_bytes": self.uploaded_bytes,
            "uploaded_files": self.uploaded_files,
            "downloaded_bytes": self.downloaded_bytes,
            "downloaded_files": self.downloaded_files,
        }


@contextmanager
def collect_transfer():
    """Open an ambient transfer-accounting scope for one execution; the
    HTTP driver's upload/download calls report into it. Scopes nest per
    asyncio task context, so interleaved requests never cross-count."""
    acct = TransferAccounting()
    token = _current_transfer.set(acct)
    try:
        yield acct
    finally:
        _current_transfer.reset(token)


def record_transfer(direction: str, nbytes: int) -> None:
    """Report one completed data-plane file move into the ambient scope;
    a no-op when no execution is being accounted (direct driver use)."""
    acct = _current_transfer.get()
    if acct is None:
        return
    if direction == "upload":
        acct.uploaded_bytes += nbytes
        acct.uploaded_files += 1
    else:
        acct.downloaded_bytes += nbytes
        acct.downloaded_files += 1


# -------------------------------------------------------------- edge wiring


def register_usage_metrics(metrics):
    """The edge's execution-cost histograms (shared HTTP/gRPC; the registry
    dedups by name). Returns (cpu_seconds, peak_rss_bytes)."""
    cpu = metrics.histogram(
        "bci_execution_cpu_seconds",
        "Per-execution sandbox CPU time (user+system, children delta)",
    )
    rss = metrics.histogram(
        "bci_execution_peak_rss_bytes",
        "Per-execution sandbox peak RSS high-water mark",
        buckets=RSS_BUCKETS,
    )
    return cpu, rss


def record_usage_at_edge(usage: dict | None, trace, cpu_hist, rss_hist) -> None:
    """Land one execution's ``usage`` block at the edge: observe the cost
    histograms, mirror the figures onto the request's root span so the
    trace view and the response body report identical numbers, and meter
    them into the ambient tenant's usage rollup (docs/tenancy.md) — one
    call site for all three, so they can never disagree."""
    if not usage:
        return
    from bee_code_interpreter_tpu.tenancy.context import meter_ambient_usage

    meter_ambient_usage(usage)
    if cpu_hist is not None and (
        "cpu_user_s" in usage or "cpu_system_s" in usage
    ):
        cpu_hist.observe(
            float(usage.get("cpu_user_s", 0.0))
            + float(usage.get("cpu_system_s", 0.0))
        )
    if rss_hist is not None and usage.get("max_rss_bytes"):
        rss_hist.observe(float(usage["max_rss_bytes"]))
    if trace is not None:
        for key in _SPAN_USAGE_KEYS:
            if key in usage:
                trace.root.attributes[f"usage.{key}"] = str(usage[key])
