"""Capacity observability: per-second demand telemetry (docs/autoscaling.md).

The fleet journal answers "what did each sandbox do" and the SLO engine
answers "are we failing users"; neither answers "how much work is ARRIVING
and is the warm pool sized for it". The ``DemandTracker`` is that missing
signal: a bounded ring of per-second buckets — arrival rate, admission queue
wait, shed count, concurrency high-water, warm-pop vs cold-spawn outcomes —
fed from the hooks the service already has:

- the shared :class:`~..resilience.admission.AdmissionController` (one gate
  for BOTH API edges) reports arrivals, sheds, queue waits, and the
  in-flight high-water mark;
- the :class:`~.fleet.FleetJournal` sink reports every pool checkout
  (``assigned`` with ``warm_pop``/``cold_spawn``) and every spawn latency
  (``ready`` with ``spawn_s``) — the tracker keeps a bounded sample ring of
  spawn latencies so the forecaster can size its horizon from OBSERVED
  spawn behavior, not a config constant.

Everything is loop-local, O(1) per recorded event, and clock-injectable so
the chaos/autoscale suites drive time deterministically. Served as the
``demand`` section of ``GET /v1/autoscale`` and as the ``bci_demand_rps`` /
``bci_warm_pop_ratio`` gauges.
"""

from __future__ import annotations

import math
import time
from collections import deque
from typing import Callable


class _DemandBucket:
    """One second of demand history."""

    __slots__ = (
        "arrivals",
        "sheds",
        "admitted",
        "queue_wait_sum",
        "queue_wait_max",
        "concurrency_hw",
        "warm_pops",
        "cold_spawns",
    )

    def __init__(self) -> None:
        self.arrivals = 0
        self.sheds = 0
        self.admitted = 0
        self.queue_wait_sum = 0.0
        self.queue_wait_max = 0.0
        self.concurrency_hw = 0
        self.warm_pops = 0
        self.cold_spawns = 0


class DemandTracker:
    """Bounded per-second demand ring + spawn-latency sample ring.

    Writers (the admission gate, the fleet-journal sink) call the
    ``record_*`` / ``on_fleet_event`` hooks; readers (the forecaster, the
    autoscaler, ``GET /v1/autoscale``) call the windowed accessors. Windows
    are trailing: a bucket belongs to ``window_s`` while its second starts
    within the last ``window_s`` seconds.
    """

    def __init__(
        self,
        *,
        window_s: float = 120.0,
        spawn_samples: int = 64,
        metrics=None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._window_s = max(2.0, window_s)
        self._clock = clock
        self._buckets: dict[int, _DemandBucket] = {}
        self._spawn_s: deque[float] = deque(maxlen=max(1, spawn_samples))
        self._last_arrival_mono: float | None = None
        self.arrivals_total = 0
        self.sheds_total = 0
        # Lifetime per-tenant demand (docs/tenancy.md): labels arrive
        # already bounded (the registry collapses unknown ids), so these
        # maps cannot grow past the tenant-label cap.
        self.arrivals_by_tenant: dict[str, int] = {}
        self.sheds_by_tenant: dict[str, int] = {}
        if metrics is not None:
            metrics.gauge(
                "bci_demand_rps",
                "Observed sandbox-bound request arrival rate (trailing 10s)",
                lambda: self.rate_rps(10.0),
            )
            metrics.gauge(
                "bci_warm_pop_ratio",
                "Pool checkouts served by a warm sandbox over the trailing "
                "60s (1.0 with no checkouts: nothing was missed)",
                lambda: self.warm_pop_ratio(60.0),
            )

    # ------------------------------------------------------------- writers

    def _bucket(self) -> _DemandBucket:
        idx = int(self._clock())
        bucket = self._buckets.get(idx)
        if bucket is None:
            self._prune(idx)
            bucket = self._buckets[idx] = _DemandBucket()
        return bucket

    def _prune(self, now_idx: int) -> None:
        horizon = now_idx - int(self._window_s) - 1
        for idx in [i for i in self._buckets if i < horizon]:
            del self._buckets[idx]

    def record_arrival(self, tenant: str | None = None) -> None:
        """One sandbox-bound request reached the admission gate (either
        edge; shed or admitted, it is demand either way). ``tenant`` is the
        bounded-cardinality tenant label when the edge resolved one."""
        self._bucket().arrivals += 1
        self.arrivals_total += 1
        if tenant is not None:
            self.arrivals_by_tenant[tenant] = (
                self.arrivals_by_tenant.get(tenant, 0) + 1
            )
        self._last_arrival_mono = self._clock()

    def record_shed(self, tenant: str | None = None) -> None:
        self._bucket().sheds += 1
        self.sheds_total += 1
        if tenant is not None:
            self.sheds_by_tenant[tenant] = (
                self.sheds_by_tenant.get(tenant, 0) + 1
            )

    def record_admitted(self, queue_wait_s: float, in_flight: int) -> None:
        """One request got past the gate after ``queue_wait_s`` in the
        queue, with ``in_flight`` requests (itself included) now running —
        the per-second high-water of that count is the concurrency the pool
        must cover."""
        bucket = self._bucket()
        bucket.admitted += 1
        if not math.isfinite(queue_wait_s):
            # A NaN/inf wait (a clock that jumped, a poisoned caller) must
            # not poison the whole window's avg/max — drop the sample, keep
            # the admission count.
            queue_wait_s = 0.0
        queue_wait_s = max(0.0, queue_wait_s)
        bucket.queue_wait_sum += queue_wait_s
        bucket.queue_wait_max = max(bucket.queue_wait_max, queue_wait_s)
        bucket.concurrency_hw = max(bucket.concurrency_hw, in_flight)

    def on_fleet_event(self, event: dict) -> None:
        """FleetJournal sink: checkout outcomes (warm vs cold) and observed
        spawn latencies. Cheap and exception-free — it runs inside
        ``FleetJournal.record`` on the request path."""
        state = event.get("state")
        if state == "assigned":
            bucket = self._bucket()
            if event.get("reason") == "warm_pop":
                bucket.warm_pops += 1
            else:
                bucket.cold_spawns += 1
        elif state == "ready" and event.get("spawn_s") is not None:
            try:
                spawn_s = float(event["spawn_s"])
            except (TypeError, ValueError):
                return
            # The sample ring feeds the forecaster's horizon: one NaN/inf
            # (or a negative from a clock step) would make every quantile —
            # and therefore the scaling horizon — garbage for the next 64
            # spawns. Refuse the sample, not just the crash.
            if math.isfinite(spawn_s) and spawn_s >= 0.0:
                self._spawn_s.append(spawn_s)

    # ------------------------------------------------------------- readers

    def _clamp_window(self, window_s: float) -> float:
        """Windows are trailing seconds within the retained ring. A NaN or
        non-positive request would otherwise leak into a division and come
        back as NaN on a gauge — clamp to [0, retained window] instead."""
        if not math.isfinite(window_s) or window_s <= 0.0:
            return 0.0
        return min(window_s, self._window_s)

    def _window_buckets(self, window_s: float) -> list[_DemandBucket]:
        # A bucket belongs while its second STARTS within the window (the
        # class contract): end-inside inclusion would sum up to one extra
        # bucket and overstate every rate by up to 1/window_s.
        floor = self._clock() - self._clamp_window(window_s)
        return [b for idx, b in self._buckets.items() if idx >= floor]

    def rate_rps(self, window_s: float = 10.0) -> float:
        window_s = self._clamp_window(window_s)
        arrivals = sum(b.arrivals for b in self._window_buckets(window_s))
        return arrivals / window_s if window_s > 0 else 0.0

    def shed_count(self, window_s: float = 60.0) -> int:
        return sum(b.sheds for b in self._window_buckets(window_s))

    def concurrency_high_water(self, window_s: float = 60.0) -> int:
        buckets = self._window_buckets(window_s)
        return max((b.concurrency_hw for b in buckets), default=0)

    def warm_pop_ratio(self, window_s: float = 60.0) -> float:
        """Checkouts served warm over the window; 1.0 with no checkouts
        (an idle pool missed nothing — the recovered state, not NaN)."""
        buckets = self._window_buckets(window_s)
        warm = sum(b.warm_pops for b in buckets)
        cold = sum(b.cold_spawns for b in buckets)
        total = warm + cold
        return warm / total if total else 1.0

    def queue_wait(self, window_s: float = 60.0) -> dict:
        buckets = self._window_buckets(window_s)
        admitted = sum(b.admitted for b in buckets)
        wait_sum = sum(b.queue_wait_sum for b in buckets)
        wait_max = max((b.queue_wait_max for b in buckets), default=0.0)
        return {
            "admitted": admitted,
            "avg_ms": (wait_sum / admitted * 1000.0) if admitted else 0.0,
            "max_ms": wait_max * 1000.0,
        }

    def last_arrival_age_s(self) -> float | None:
        """Seconds since the last arrival; None when none was ever seen.
        The autoscaler's "sustained idle" clock."""
        if self._last_arrival_mono is None:
            return None
        return self._clock() - self._last_arrival_mono

    def completed_series(self) -> list[int]:
        """Dense per-second arrival counts, oldest→newest, over the
        retained window, EXCLUDING the current (incomplete) second — the
        forecaster's EWMA input. Missing seconds between observed buckets
        count as zero; seconds before the first observation are not data."""
        now_idx = int(self._clock())
        floor = now_idx - int(self._window_s)
        indices = [i for i in self._buckets if floor <= i < now_idx]
        if not indices:
            return []
        start = min(indices)
        return [
            self._buckets[i].arrivals if i in self._buckets else 0
            for i in range(start, now_idx)
        ]

    def peak_rps(self, window_s: float = 60.0) -> float:
        """Largest single-second arrival count over the window, current
        partial second included — the envelope a forecast must not sit
        under while a burst is still in flight."""
        buckets = self._window_buckets(window_s)
        return float(max((b.arrivals for b in buckets), default=0))

    def spawn_latency_quantile(self, q: float) -> float | None:
        """Observed sandbox spawn latency quantile (from the fleet
        journal's ``ready`` events); None before the first spawn."""
        if not self._spawn_s:
            return None
        if not math.isfinite(q):
            q = 1.0
        q = min(1.0, max(0.0, q))
        ordered = sorted(self._spawn_s)
        idx = min(len(ordered) - 1, int(q * len(ordered)))
        return ordered[idx]

    def snapshot(self) -> dict:
        """The ``demand`` section of ``GET /v1/autoscale``."""
        return {
            "rps_10s": self.rate_rps(10.0),
            "rps_60s": self.rate_rps(60.0),
            "peak_rps_60s": self.peak_rps(60.0),
            "warm_pop_ratio_60s": self.warm_pop_ratio(60.0),
            "sheds_60s": self.shed_count(60.0),
            "concurrency_high_water_60s": self.concurrency_high_water(60.0),
            "queue_wait_60s": self.queue_wait(60.0),
            "spawn_p50_s": self.spawn_latency_quantile(0.5),
            "spawn_p95_s": self.spawn_latency_quantile(0.95),
            "spawn_samples": len(self._spawn_s),
            "last_arrival_age_s": self.last_arrival_age_s(),
            "arrivals_total": self.arrivals_total,
            "sheds_total": self.sheds_total,
            "by_tenant": {
                tenant: {
                    "arrivals": arrivals,
                    "sheds": self.sheds_by_tenant.get(tenant, 0),
                }
                for tenant, arrivals in sorted(self.arrivals_by_tenant.items())
            },
            "window_s": self._window_s,
        }
