"""Flight recorder: one canonical wide event per request (docs/observability.md).

Traces answer "where did THIS request spend its time" and metrics answer
"how is the fleet doing in aggregate"; neither answers "show me everything
that happened to the executions matching X" without joining three APIs by
hand. The flight recorder is that third signal: every execution, session
lifecycle op, and stream emits ONE wide event — ids, outcome, stage
timings, usage, analysis findings, replay/hedge outcomes, SLO
classification, session and stream context — into a bounded in-memory ring
with optional size-rotated ndjson segment files.

Event sources:

- **Requests** — a :class:`~.tracing.Tracer` sink (:meth:`FlightRecorder.
  record_trace`) fires on every finished trace; the event is assembled from
  the root span plus the edge annotations the request path stamped on it
  (``outcome``/``sli``/``session``/``usage.*``/``replays``/``hedge``/
  ``stream.*``) and the analysis stage span's findings.
- **Session lifecycle** — the :class:`~..sessions.manager.SessionManager`
  emits ``kind="session"`` events for created/released/expired leases
  (sweep-driven expiries have no request to ride on).
- **Loop stalls** — the :class:`~.loopmon.LoopMonitor` emits
  ``kind="loop_stall"`` events carrying the asyncio task-stack dump it
  captured when event-loop lag blew its threshold.

Delivery is drop-not-block everywhere: the in-memory ring evicts oldest
(retention, accounted nowhere — that is what a ring is), SSE followers with
a full queue lose events (``bci_events_dropped_total{reason="follower"}``),
the disk-write queue drops beyond its bound (``reason="write_queue_full"``),
and the OTLP logs sink inherits the telemetry exporter's exact accounting.
"""

from __future__ import annotations

import asyncio
import json
import logging
import threading
import time
from collections import deque
from pathlib import Path

logger = logging.getLogger(__name__)

# Keys the edges stamp on the root span that the wide event lifts into
# first-class fields (everything else stays under "attributes").
_LIFTED_ROOT_KEYS = frozenset(
    {"outcome", "sli", "session", "replays", "hedge", "tenant"}
)
_SEGMENT_PREFIX = "events-"
_SEGMENT_SUFFIX = ".ndjson"


def register_stream_metrics(metrics):
    """The production streaming metrics both edges record (the numbers
    bench.py could previously only measure offline): time-to-first-chunk
    and chunks delivered, labeled by transport. Registry name-dedup makes
    this safe to call from both edges."""
    from bee_code_interpreter_tpu.utils.metrics import TOKEN_LATENCY_BUCKETS

    ttfb = metrics.histogram(
        "bci_stream_ttfb_seconds",
        "Streaming executions: start to first output chunk, by transport",
        buckets=TOKEN_LATENCY_BUCKETS,
    )
    chunks = metrics.counter(
        "bci_stream_chunks_total",
        "Streaming output chunks delivered to clients, by transport",
    )
    return ttfb, chunks


def _float_or_none(value) -> float | None:
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


def event_matches(
    event: dict,
    *,
    kind: str | None = None,
    outcome: str | None = None,
    session: str | None = None,
    tenant: str | None = None,
    min_duration_ms: float | None = None,
    since: float | None = None,
) -> bool:
    """The ONE filter predicate for wide events — the ring query and the
    live SSE tail must accept identical events for identical filters."""
    if kind is not None and event.get("kind") != kind:
        return False
    if outcome is not None and event.get("outcome") != outcome:
        return False
    if session is not None and event.get("session") != session:
        return False
    if tenant is not None and event.get("tenant") != tenant:
        return False
    if min_duration_ms is not None:
        duration = event.get("duration_ms")
        if duration is None or duration < min_duration_ms:
            return False
    if since is not None and event.get("ts", 0.0) <= since:
        return False
    return True


def wide_event_from_trace(trace) -> dict:
    """Assemble the canonical wide event for one finished trace. Root-span
    annotations become first-class fields; the analysis stage span
    contributes the gate's findings; everything else the request stamped
    stays under ``attributes`` so nothing is lost to the schema."""
    root = trace.root
    attrs = dict(root.attributes)
    usage = {}
    stream = {}
    extra = {}
    for key, value in attrs.items():
        if key.startswith("usage."):
            usage[key[len("usage."):]] = _float_or_none(value)
        elif key.startswith("stream."):
            stream[key[len("stream."):]] = _float_or_none(value)
        elif key not in _LIFTED_ROOT_KEYS:
            extra[key] = value
    analysis = {}
    for s in trace.spans:
        if s is root:
            continue
        for key, value in s.attributes.items():
            if key.startswith("analysis."):
                analysis[key[len("analysis."):]] = value
    event: dict = {
        "kind": "request",
        "ts": root.start_unix,
        "name": trace.name,
        "trace_id": trace.trace_id,
        "request_id": trace.request_id,
        "status": root.status,
        "outcome": attrs.get("outcome") or (
            "error" if root.status == "error" else "ok"
        ),
        "duration_ms": (
            root.duration_s * 1000.0 if root.duration_s is not None else None
        ),
        "timings_ms": trace.stage_ms(),
        "session": attrs.get("session"),
        "tenant": attrs.get("tenant"),
        "sli": attrs.get("sli"),
        "replays": int(_float_or_none(attrs.get("replays", 0)) or 0),
        "hedge": attrs.get("hedge"),
        "usage": usage or None,
        "stream": stream or None,
        "analysis": analysis or None,
        "attributes": extra or None,
    }
    return event


class FlightRecorder:
    """Bounded wide-event ring + optional ndjson segment files + live sinks.

    ``record()`` is the one ingest point: O(1) on the request path (a dict
    append plus non-blocking fan-out), never I/O. Disk persistence, when a
    directory is configured, happens on a background flusher task that
    drains a bounded pending queue through ``asyncio.to_thread``.
    """

    def __init__(
        self,
        *,
        max_events: int = 512,
        dir: str | None = None,
        segment_bytes: int = 1 << 20,
        max_segments: int = 4,
        follower_queue_max: int = 256,
        write_queue_max: int = 1024,
        flush_interval_s: float = 0.5,
        metrics=None,
    ) -> None:
        self._ring: deque[dict] = deque(maxlen=max(1, max_events))
        self._dir = Path(dir) if dir else None
        self._segment_bytes = max(1, segment_bytes)
        self._max_segments = max(1, max_segments)
        self._follower_queue_max = follower_queue_max
        self._write_queue_max = write_queue_max
        self._flush_interval_s = flush_interval_s
        self._seq = 0
        self._segment_seq = 0
        self._segment_path: Path | None = None
        self._followers: set[asyncio.Queue] = set()
        self._pending: deque[dict] = deque()
        self._sinks: list = []
        self._task: asyncio.Task | None = None
        self._wake: asyncio.Event | None = None
        # The ring is appended from the loop; scripts/tests may read from
        # other threads — guard the ring walk, not the hot append.
        self._lock = threading.Lock()
        self._emitted_total = None
        self._dropped_total = None
        if metrics is not None:
            self._emitted_total = metrics.counter(
                "bci_events_emitted_total",
                "Wide events recorded by the flight recorder, by kind",
            )
            self._dropped_total = metrics.counter(
                "bci_events_dropped_total",
                "Wide events dropped instead of blocking (slow SSE follower, "
                "full disk-write queue), by reason",
            )

    # ------------------------------------------------------------ ingest

    def record_trace(self, trace) -> None:
        """Tracer sink: one wide event per finished trace."""
        self.record(wide_event_from_trace(trace))

    def record(self, event: dict) -> None:
        """Ingest one wide event (cheap, non-blocking, no I/O). Missing
        ``ts``/``kind`` are filled; ``seq`` is stamped here — a total order
        the ``since`` filter and the tail script can resume from."""
        self._seq += 1
        event.setdefault("kind", "event")
        event.setdefault("ts", time.time())
        event["seq"] = self._seq
        with self._lock:
            self._ring.append(event)
        if self._emitted_total is not None:
            self._emitted_total.inc(kind=event["kind"])
        for queue in list(self._followers):
            try:
                queue.put_nowait(event)
            except asyncio.QueueFull:
                if self._dropped_total is not None:
                    self._dropped_total.inc(reason="follower")
        if self._dir is not None:
            if len(self._pending) >= self._write_queue_max:
                if self._dropped_total is not None:
                    self._dropped_total.inc(reason="write_queue_full")
            else:
                self._pending.append(event)
                if self._wake is not None:
                    self._wake.set()
        for sink in self._sinks:
            # A broken sink must never fail the request that emitted this.
            try:
                sink(event)
            except Exception:
                logger.exception("wide-event sink %r failed", sink)

    def add_sink(self, sink) -> None:
        """Register a callable invoked with each recorded event (the OTLP
        logs exporter's ``enqueue_log``). Sinks must be cheap and
        non-blocking — they run on the request path."""
        self._sinks.append(sink)

    # ------------------------------------------------------------- query

    def events(
        self,
        *,
        kind: str | None = None,
        outcome: str | None = None,
        session: str | None = None,
        tenant: str | None = None,
        min_duration_ms: float | None = None,
        since: float | None = None,
        limit: int | None = None,
    ) -> list[dict]:
        """Filtered view of the ring, newest first. ``since`` is a unix
        timestamp lower bound (strictly after); ``min_duration_ms`` keeps
        events whose ``duration_ms`` is known and at least the bound."""
        if limit is not None and limit <= 0:
            return []
        with self._lock:
            snapshot = list(self._ring)
        out: list[dict] = []
        for event in reversed(snapshot):
            if not event_matches(
                event,
                kind=kind,
                outcome=outcome,
                session=session,
                tenant=tenant,
                min_duration_ms=min_duration_ms,
                since=since,
            ):
                continue
            out.append(event)
            if limit is not None and len(out) >= limit:
                break
        return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # ------------------------------------------------------------ follow

    def subscribe(self) -> asyncio.Queue:
        """A live tail (the SSE ``?follow=1`` feed): events recorded from
        now on land in the returned queue; a slow consumer loses events
        (accounted) rather than backing up the recorder."""
        queue: asyncio.Queue = asyncio.Queue(maxsize=self._follower_queue_max)
        self._followers.add(queue)
        return queue

    def unsubscribe(self, queue: asyncio.Queue) -> None:
        self._followers.discard(queue)

    # -------------------------------------------------------------- disk

    def start(self) -> None:
        """Start the background disk flusher (requires a running loop);
        a no-op when no segment directory is configured."""
        if self._dir is None:
            return
        if self._task is None or self._task.done():
            self._wake = asyncio.Event()
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        if self._dir is not None and self._pending:
            # Final flush is small (bounded queue) and teardown-critical:
            # run it to_thread like the loop did.
            await asyncio.to_thread(self.flush_to_disk)

    async def _run(self) -> None:
        assert self._wake is not None
        while True:
            try:
                await asyncio.wait_for(
                    self._wake.wait(), timeout=self._flush_interval_s
                )
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            if self._pending:
                try:
                    await asyncio.to_thread(self.flush_to_disk)
                except Exception:  # the flusher must survive a bad disk
                    logger.exception("wide-event segment write failed")

    def flush_to_disk(self) -> int:
        """Drain the pending queue into the current ndjson segment,
        rotating when it exceeds the size bound (sync — called off-loop by
        the flusher, directly by tests)."""
        if self._dir is None:
            return 0
        lines: list[str] = []
        while self._pending:
            lines.append(json.dumps(self._pending.popleft(), default=str))
        if not lines:
            return 0
        self._dir.mkdir(parents=True, exist_ok=True)
        path = self._current_segment()
        with path.open("a", encoding="utf-8") as f:
            f.write("\n".join(lines) + "\n")
        if path.stat().st_size >= self._segment_bytes:
            self._rotate()
        return len(lines)

    def _current_segment(self) -> Path:
        if self._segment_path is None:
            existing = self.segment_paths()
            if existing:
                last = existing[-1]
                self._segment_seq = int(
                    last.name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)]
                )
                self._segment_path = last
            else:
                self._segment_path = self._segment_name(self._segment_seq)
        return self._segment_path

    def _segment_name(self, seq: int) -> Path:
        assert self._dir is not None
        return self._dir / f"{_SEGMENT_PREFIX}{seq:06d}{_SEGMENT_SUFFIX}"

    def _rotate(self) -> None:
        self._segment_seq += 1
        self._segment_path = self._segment_name(self._segment_seq)
        # The new active segment (created on the next flush) counts toward
        # the bound: keep max_segments - 1 existing files so the documented
        # "at most events_segments files" holds once it materializes.
        keep = self._max_segments - 1
        stale_segments = (
            self.segment_paths()[:-keep] if keep else self.segment_paths()
        )
        for stale in stale_segments:
            try:
                stale.unlink()
            except OSError:
                logger.warning("could not remove stale segment %s", stale)

    def segment_paths(self) -> list[Path]:
        """Existing segment files, oldest first."""
        if self._dir is None or not self._dir.exists():
            return []
        return sorted(
            p
            for p in self._dir.glob(f"{_SEGMENT_PREFIX}*{_SEGMENT_SUFFIX}")
            if p.name[len(_SEGMENT_PREFIX):-len(_SEGMENT_SUFFIX)].isdigit()
        )

    # ---------------------------------------------------------- operator

    def snapshot(self) -> dict:
        """Recorder state for the debug bundle / verbose health."""
        return {
            "retained": len(self),
            "emitted": self._seq,
            "followers": len(self._followers),
            "pending_write": len(self._pending),
            "segment_dir": str(self._dir) if self._dir is not None else None,
            "segments": [p.name for p in self.segment_paths()],
        }
