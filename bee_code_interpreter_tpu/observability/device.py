"""Accelerator observability (docs/observability.md "Accelerator
observability").

The control plane has been watchable end to end since PR 2–18; the
accelerator tree was runtime-blind. :class:`DeviceMonitor` is the
runtime-signal layer for ``models/``/``ops/``/``parallel/`` — three
signals, all CPU-deterministic so tier-1 needs no TPU:

- **Compile/retrace tracking**: the batcher's jit entry points are wrapped
  in :class:`~bee_code_interpreter_tpu.utils.jitwatch.TrackedJit`, which
  duck-calls :meth:`on_compile` on every XLA compilation. Each one becomes
  exactly one ``kind="compile"`` wide event in the flight recorder, a
  ``bci_compile_total{trigger}`` increment, a ``bci_compile_seconds``
  observation, and — when it fired under an active request trace (the
  batcher activates the request's trace around admission) — a backdated
  ``xla.compile`` span inside that request's span tree, all naming the
  same trace_id. A TTFT spike caused by a mid-stream retrace is therefore
  visible in three correlated places, not zero.
- **Device-memory accounting**: a periodic sampler over
  ``device.memory_stats()`` where the backend provides it (TPU), degrading
  to a live-buffer byte estimate from ``jax.live_arrays()`` on CPU (rows
  marked ``estimated``), published as ``bci_device_hbm_bytes{kind=
  live|peak|limit}`` per device. The paged-KV pool occupancy joins the
  snapshot from the attached batcher's ``kv_telemetry()`` (PR 9
  ``pool_telemetry``) so "how full is HBM" and "how full is the KV pool"
  read from one call.
- **Mesh-aware step telemetry**: the batcher (and the MULTICHIP dryrun)
  report per-step wall time tagged with the mesh's shape key
  (``parallel.mesh.mesh_shape_key``), aggregated per shape — the
  tokens/sec-vs-mesh-shape curve ROADMAP item 4 is verified against.

Served at ``GET /v1/accelerator`` (+ gRPC
``ObservabilityService/GetAccelerator``, a debug-bundle section, and an
``accelerator`` summary on ``/v1/fleet`` for router placement). Like
``ServingMonitor``, the monitor is duck-typed from the models/ side: the
batcher calls hooks when one is attached and pays a single None check
otherwise, so ``models/`` never imports this package.
"""

from __future__ import annotations

import asyncio
import threading
import time
from collections import deque

from bee_code_interpreter_tpu.observability.tracing import current_trace

# histogram buckets for compile wall time: compiles run 10 ms (tiny CPU
# programs) to minutes (big sharded models) — the serving-latency buckets
# top out far too low to see them
COMPILE_SECONDS_BUCKETS = (
    0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def _device_key(device) -> str:
    return f"{device.platform}:{device.id}"


class DeviceMonitor:
    """Compile/retrace tracking, device-memory accounting, and per-mesh-
    shape step telemetry. Constructed by the composition root next to the
    other monitors (metrics register immediately; the constructor takes
    one memory sample so the HBM gauges exist before the sampler starts);
    :meth:`attach` binds a ``models.engine.Engine`` or bare
    ``ContinuousBatcher`` and injects the monitor into its tracked jits.
    """

    def __init__(
        self,
        *,
        metrics=None,
        recorder=None,  # flightrecorder.FlightRecorder
        sample_interval_s: float = 10.0,
        max_compiles: int = 256,
    ) -> None:
        self._recorder = recorder
        self._metrics = metrics
        self._sample_interval_s = sample_interval_s
        self._lock = threading.Lock()
        self._compiles: deque[dict] = deque(maxlen=max(1, max_compiles))
        self._compile_seq = 0
        self._compile_by_trigger: dict[str, int] = {}
        # function name -> per-function compile ledger; the signature list
        # is the per-function signature SET (insertion-ordered), so a
        # retrace names the shape/dtype that caused it next to every shape
        # seen before
        self._functions: dict[str, dict] = {}
        self._mesh: dict | None = None
        self._shapes: dict[str, dict] = {}
        self._memory: list[dict] = []
        self._memory_unix: float | None = None
        self._memory_samples = 0
        self._peak_estimate: dict[str, int] = {}
        self._gauged: set[tuple[str, str]] = set()
        self._engine = None
        self._batcher = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._sampler_task: asyncio.Task | None = None
        self._compile_total = None
        self._compile_seconds = None
        self._step_seconds = None
        if metrics is not None:
            self._compile_total = metrics.counter(
                "bci_compile_total",
                "XLA compilations observed by the tracked jits, by trigger "
                "(first_call|retrace)",
            )
            self._compile_seconds = metrics.histogram(
                "bci_compile_seconds",
                "Wall time of one XLA compilation (the stall the caller felt)",
                buckets=COMPILE_SECONDS_BUCKETS,
            )
            self._step_seconds = metrics.histogram(
                "bci_device_step_seconds",
                "Batcher/dryrun step wall time, by mesh shape",
            )
        # one eager sample: the HBM gauges must exist (and the snapshot
        # must be complete) before — or without — the background sampler
        self.sample_memory()

    # ------------------------------------------------------------ wiring

    def attach(self, target) -> None:
        """Bind a ``models.engine.Engine`` (or a bare ``ContinuousBatcher``)
        so its tracked jits report compiles here, its step timings land in
        the per-shape aggregates, and the snapshot joins its KV-pool
        telemetry + mesh descriptor."""
        batcher = getattr(target, "batcher", target)
        self._engine = target if batcher is not target else None
        self._batcher = batcher
        batcher.set_device_monitor(self)
        try:
            from bee_code_interpreter_tpu.parallel.mesh import mesh_descriptor

            self.set_mesh(mesh_descriptor(getattr(batcher, "mesh", None)))
        except Exception:
            # descriptor is best-effort: a mock batcher (tests) or an
            # import-stripped image must not break attachment
            pass
        try:
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            pass

    @property
    def available(self) -> bool:
        return self._batcher is not None

    def set_mesh(self, descriptor: dict | None) -> None:
        """Record the current mesh context (``parallel.mesh
        .mesh_descriptor``); subsequent compiles and step records carry its
        shape key."""
        with self._lock:
            self._mesh = descriptor

    def arm_loop(self, loop: asyncio.AbstractEventLoop | None = None) -> None:
        """Bind the loop wide events are delivered on when ``on_compile``
        fires off-loop (profiler capture threads, the bench) — same
        contract as ``ServingMonitor.arm_loop``."""
        self._loop = loop if loop is not None else asyncio.get_running_loop()

    def start(self) -> None:
        """Start the periodic memory sampler (must be called from a running
        loop; ``ApplicationContext.start_observability`` does). Also arms
        the event-delivery loop."""
        self.arm_loop()
        if self._sampler_task is None or self._sampler_task.done():
            self._sampler_task = asyncio.get_running_loop().create_task(
                self._sample_loop(), name="device-monitor-sampler"
            )

    def stop(self) -> None:
        if self._sampler_task is not None:
            self._sampler_task.cancel()
            self._sampler_task = None

    async def _sample_loop(self) -> None:
        while True:
            await asyncio.sleep(self._sample_interval_s)
            # off the loop: memory_stats is a backend call and the CPU
            # degradation walks every live buffer
            await asyncio.to_thread(self.sample_memory)

    # ----------------------------------------------------- compile hook

    def on_compile(
        self,
        name: str,
        *,
        signature: str,
        duration_ms: float,
        trigger: str,
    ) -> None:
        """One XLA compilation happened (TrackedJit calls this). Exactly one
        wide event + one counter increment + (when a request trace is
        active) one backdated ``xla.compile`` span, all naming the same
        trace_id."""
        trace = current_trace()
        trace_id = request_id = None
        if trace is not None:
            duration_s = duration_ms / 1000.0
            s = trace.start_span(
                "xla.compile",
                parent_id=trace.root.span_id,
                attributes={
                    "function": name,
                    "signature": signature,
                    "trigger": trigger,
                },
            )
            # backdate: the compile already happened (the wrapper timed it)
            s.start_mono -= duration_s
            s.start_unix -= duration_s
            trace.end_span(s)
            trace_id, request_id = trace.trace_id, trace.request_id
        with self._lock:
            self._compile_seq += 1
            self._compile_by_trigger[trigger] = (
                self._compile_by_trigger.get(trigger, 0) + 1
            )
            fn = self._functions.setdefault(
                name,
                {
                    "compiles": 0,
                    "triggers": {},
                    "signatures": [],
                    "last_compile_ms": None,
                },
            )
            fn["compiles"] += 1
            fn["triggers"][trigger] = fn["triggers"].get(trigger, 0) + 1
            if signature not in fn["signatures"]:
                fn["signatures"].append(signature)
            fn["last_compile_ms"] = duration_ms
            mesh_shape = self._mesh["shape"] if self._mesh else None
            record = {
                "seq": self._compile_seq,
                "ts": time.time(),
                "function": name,
                "signature": signature,
                "trigger": trigger,
                "duration_ms": duration_ms,
                "mesh": mesh_shape,
                "trace_id": trace_id,
            }
            self._compiles.append(record)
        if self._compile_total is not None:
            self._compile_total.inc(trigger=trigger)
        if self._compile_seconds is not None:
            # observed while the request's trace is still ambient, so the
            # OpenMetrics exemplar names the same trace_id as the event
            self._compile_seconds.observe(duration_ms / 1000.0)
        event: dict = {
            "kind": "compile",
            "name": "xla.compile",
            "outcome": "ok",
            "function": name,
            "signature": signature,
            "trigger": trigger,
            "duration_ms": duration_ms,
            "mesh": mesh_shape,
        }
        if trace_id is not None:
            event["trace_id"] = trace_id
        if request_id is not None:
            event["request_id"] = request_id
        self._emit(event)

    # -------------------------------------------------------- step hook

    def record_step(self, duration_ms: float, shape: str | None = None) -> None:
        """One batcher/dryrun step finished under mesh shape ``shape``
        (default: the attached mesh's shape key). Aggregated per shape —
        the raw ring stays the ServingMonitor's job."""
        with self._lock:
            if shape is None:
                shape = self._mesh["shape"] if self._mesh else "1"
            agg = self._shapes.setdefault(
                shape,
                {
                    "steps": 0,
                    "total_ms": 0.0,
                    "min_ms": None,
                    "max_ms": 0.0,
                    "last_ms": 0.0,
                },
            )
            agg["steps"] += 1
            agg["total_ms"] += duration_ms
            agg["min_ms"] = (
                duration_ms
                if agg["min_ms"] is None
                else min(agg["min_ms"], duration_ms)
            )
            agg["max_ms"] = max(agg["max_ms"], duration_ms)
            agg["last_ms"] = duration_ms
        if self._step_seconds is not None:
            self._step_seconds.observe(duration_ms / 1000.0, mesh=shape)

    # ----------------------------------------------------- memory sampler

    def sample_memory(self) -> list[dict]:
        """One device-memory sample: ``memory_stats()`` where the backend
        provides it (TPU), else the live-buffer estimate (CPU — rows
        marked ``estimated``, peak tracked as a running max, no limit).
        Registers the per-(device, kind) ``bci_device_hbm_bytes`` gauge
        series on first sight."""
        try:
            import jax

            devices = jax.devices()
        except Exception:
            return []
        rows: list[dict] = []
        live_estimate: dict[str, int] | None = None
        for device in devices:
            try:
                stats = device.memory_stats()
            except Exception:
                stats = None
            key = _device_key(device)
            if stats:
                live = int(stats.get("bytes_in_use", 0))
                rows.append(
                    {
                        "device": key,
                        "platform": device.platform,
                        "live_bytes": live,
                        "peak_bytes": int(
                            stats.get("peak_bytes_in_use", live)
                        ),
                        "limit_bytes": (
                            int(stats["bytes_limit"])
                            if "bytes_limit" in stats
                            else None
                        ),
                        "estimated": False,
                    }
                )
                continue
            if live_estimate is None:
                live_estimate = {}
                for arr in jax.live_arrays():
                    try:
                        arr_devices = list(arr.devices())
                    except Exception:
                        continue
                    if not arr_devices:
                        continue
                    # a sharded array's nbytes is the GLOBAL size: spread
                    # it evenly over its devices for the per-device view
                    per_device = int(
                        getattr(arr, "nbytes", 0) or 0
                    ) // len(arr_devices)
                    for arr_device in arr_devices:
                        dk = _device_key(arr_device)
                        live_estimate[dk] = (
                            live_estimate.get(dk, 0) + per_device
                        )
            live = live_estimate.get(key, 0)
            peak = max(self._peak_estimate.get(key, 0), live)
            self._peak_estimate[key] = peak
            rows.append(
                {
                    "device": key,
                    "platform": device.platform,
                    "live_bytes": live,
                    "peak_bytes": peak,
                    "limit_bytes": None,
                    "estimated": True,
                }
            )
        with self._lock:
            self._memory = rows
            self._memory_unix = time.time()
            self._memory_samples += 1
        if self._metrics is not None:
            for row in rows:
                for kind in ("live", "peak", "limit"):
                    gauge_key = (row["device"], kind)
                    if gauge_key in self._gauged:
                        continue
                    self._gauged.add(gauge_key)
                    self._metrics.gauge(
                        "bci_device_hbm_bytes",
                        "Device memory bytes by kind (live|peak|limit); "
                        "live-buffer estimate on backends without "
                        "memory_stats",
                        (
                            lambda d=row["device"], k=kind: float(
                                self._memory_value(d, k)
                            )
                        ),
                        device=row["device"],
                        kind=kind,
                    )
        return rows

    def _memory_value(self, device: str, kind: str) -> int:
        with self._lock:
            for row in self._memory:
                if row["device"] == device:
                    value = row.get(f"{kind}_bytes")
                    return int(value) if value is not None else 0
        return 0

    # ------------------------------------------------------------ queries

    def snapshot(self, recent: int = 16) -> dict:
        """The ``GET /v1/accelerator`` body: compile totals + per-function
        ledgers + the last ``recent`` compile records, the latest memory
        sample (``estimated`` marks the CPU degradation), the KV-pool
        occupancy joined from the attached batcher, the mesh descriptor,
        and the per-shape step aggregates. Pure host bookkeeping — safe on
        every scrape."""
        with self._lock:
            functions = {
                name: {
                    "compiles": fn["compiles"],
                    "triggers": dict(fn["triggers"]),
                    "signatures": list(fn["signatures"]),
                    "last_compile_ms": fn["last_compile_ms"],
                }
                for name, fn in self._functions.items()
            }
            memory_rows = [dict(row) for row in self._memory]
            body: dict = {
                "attached": self._batcher is not None,
                "compile": {
                    "total": self._compile_seq,
                    "by_trigger": dict(self._compile_by_trigger),
                    "functions": functions,
                    "recent": (
                        list(self._compiles)[-recent:] if recent > 0 else []
                    ),
                },
                "memory": {
                    "sampled_unix": self._memory_unix,
                    "samples": self._memory_samples,
                    "estimated": (
                        any(row["estimated"] for row in memory_rows)
                        if memory_rows
                        else None
                    ),
                    "devices": memory_rows,
                },
                "mesh": dict(self._mesh) if self._mesh else None,
                "steps": {
                    "by_shape": {
                        shape: dict(agg)
                        for shape, agg in self._shapes.items()
                    }
                },
            }
        body["kv_pool"] = (
            self._batcher.kv_telemetry() if self._batcher is not None else None
        )
        return body

    def fleet_summary(self) -> dict:
        """The compact ``accelerator`` section of ``GET /v1/fleet`` — the
        compile-pressure and HBM-headroom numbers a fleet router's refresh
        loop reads for placement, without the per-function ledgers."""
        with self._lock:
            limits = [
                row["limit_bytes"]
                for row in self._memory
                if row["limit_bytes"] is not None
            ]
            return {
                "compiles": self._compile_seq,
                "retraces": self._compile_by_trigger.get("retrace", 0),
                "mesh": self._mesh["shape"] if self._mesh else None,
                "hbm": {
                    "live_bytes": sum(
                        row["live_bytes"] for row in self._memory
                    ),
                    "limit_bytes": sum(limits) if limits else None,
                    "estimated": (
                        any(row["estimated"] for row in self._memory)
                        if self._memory
                        else None
                    ),
                },
            }

    # ------------------------------------------------------------ private

    def _emit(self, event: dict) -> None:
        if self._recorder is None:
            return
        try:
            # remember the loop whenever one is running here, so compiles
            # that later fire off-loop know where to deliver
            self._loop = asyncio.get_running_loop()
        except RuntimeError:
            # off-loop caller (profiler capture thread, bench): hand the
            # event to the recorder's loop — its follower queues are
            # asyncio objects a foreign thread must not poke directly
            loop = self._loop
            if loop is not None and loop.is_running():
                loop.call_soon_threadsafe(self._recorder.record, event)
                return
            # no loop was ever armed: nothing async can be following the
            # recorder either, so the direct call only touches the ring
        self._recorder.record(event)
