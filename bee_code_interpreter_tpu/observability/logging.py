"""Structured (JSON-lines) log formatting.

Opt-in via ``APP_LOG_FORMAT=json``: every record becomes exactly one line
of JSON carrying the correlation ids the tracing subsystem maintains
(``request_id``/``trace_id``/``span_id``), so a log pipeline can join pod-
and edge-side lines on ``trace_id`` without regex heroics. Exceptions are
folded into the same single line (JSON escapes the newlines) — a stack
trace must never shear a log stream that is parsed line-by-line.
"""

from __future__ import annotations

import json
import logging
import time


class JsonLogFormatter(logging.Formatter):
    """One JSON object per record. Correlation ids come from the record
    attributes the ``RequestIdLoggingFilter`` attaches; records emitted
    outside any request (startup, background sweeps) carry ``"-"``."""

    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "ts": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.gmtime(record.created)
            )
            + f".{int(record.msecs):03d}Z",
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
            "request_id": getattr(record, "request_id", "-"),
            "trace_id": getattr(record, "trace_id", "-"),
            "span_id": getattr(record, "span_id", "-"),
        }
        if record.exc_info:
            payload["exc_info"] = self.formatException(record.exc_info)
        elif record.exc_text:
            payload["exc_info"] = record.exc_text
        if record.stack_info:
            payload["stack_info"] = self.formatStack(record.stack_info)
        return json.dumps(payload, ensure_ascii=False, default=str)
