"""Fleet observability: the sandbox-pool lifecycle journal (docs/observability.md).

The warm pool is the system's core asset, yet gauges alone (`ready`,
`spawning`) cannot answer "why did the pool drain", "which pod served this
request", or "what killed pod X at 12:04". This module keeps the missing
history: every sandbox transition —

    spawning -> ready -> assigned -> executing -> released | reaped | failed

— is recorded as an event (timestamp, reason, spawn latency) in a bounded
ring shared by both pool backends (``kubernetes_code_executor.py`` and
``native_process_code_executor.py``), with a live per-pod record while the
sandbox exists. Served as ``GET /v1/fleet`` (point-in-time snapshot) and
``GET /v1/fleet/events`` on the HTTP edge, and as the
``code_interpreter.v1.FleetService`` JSON-over-gRPC methods.

Metrics fed from transitions (same registry the rest of the service uses):

- ``bci_pool_spawn_seconds``       spawn latency histogram (spawning->ready)
- ``bci_pool_utilization``         busy / live sandboxes (0-1 gauge)
- ``bci_pod_reaped_total{reason}`` abnormal removals (reaped + failed)

Everything is loop-local control-plane state: no locks, no I/O, O(1) per
transition.
"""

from __future__ import annotations

import logging
import time
from collections import deque
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)

# Canonical lifecycle states. Terminal states drop the pod from the live
# snapshot; its history stays in the event ring. ``leased`` marks a sandbox
# owned by an interactive session (docs/sessions.md) — busy from the pool's
# point of view even while idle between executes — and ``lease_expired`` is
# the terminal event for a lease the service ended (TTL, idle timeout,
# drain, shutdown), as opposed to ``released`` (clean client release) and
# ``reaped`` (the sandbox died under the lease).
STATES = (
    "spawning",
    "ready",
    "assigned",
    "leased",
    "executing",
    "released",
    "lease_expired",
    "reaped",
    "failed",
)
TERMINAL_STATES = frozenset(("released", "lease_expired", "reaped", "failed"))
BUSY_STATES = frozenset(("assigned", "leased", "executing"))


def unwrap_executor(executor):
    """The pool backend behind the resilience fronts
    (``ResilientCodeExecutor.primary`` → ``HedgingExecutor.primary`` → the
    backend) — the object holding the journal, pool counters, and breakers.
    Recursive because the fronts stack; the ONE unwrap rule shared by every
    edge (HTTP healthz, journal discovery on both transports), so they can
    never disagree about which backend they inspect."""
    seen: set[int] = set()
    while id(executor) not in seen:
        seen.add(id(executor))
        inner = getattr(executor, "primary", None)
        if inner is None:
            break
        executor = inner
    return executor


def find_journal(executor) -> "FleetJournal | None":
    """The fleet journal an executor backend records into. Returns None for
    journal-less backends (the in-process local executor)."""
    return getattr(unwrap_executor(executor), "journal", None)


@dataclass
class PodRecord:
    """Live view of one sandbox (pod group or native server process)."""

    name: str
    state: str
    workers: int = 1
    created_mono: float = field(default_factory=time.monotonic)
    ready_mono: float | None = None
    spawn_s: float | None = None
    executions: int = 0
    last_reason: str | None = None
    # Session lease (docs/sessions.md): owner session id + when the lease
    # began, so operators can tell a busy REPL from a stuck pod.
    session: str | None = None
    leased_mono: float | None = None

    def to_dict(self) -> dict:
        out = {
            "pod": self.name,
            "state": self.state,
            "workers": self.workers,
            "age_s": time.monotonic() - self.created_mono,
            "spawn_s": self.spawn_s,
            "executions": self.executions,
            "reason": self.last_reason,
        }
        if self.session is not None:
            out["session"] = self.session
            out["lease_age_s"] = (
                time.monotonic() - self.leased_mono
                if self.leased_mono is not None
                else None
            )
        return out


class FleetJournal:
    """Bounded lifecycle journal + live pool snapshot for one executor
    backend. Backends call :meth:`record` at each transition; the API edge
    reads :meth:`snapshot` / :meth:`events`."""

    def __init__(self, metrics=None, max_events: int = 512) -> None:
        self._events: deque[dict] = deque(maxlen=max(1, max_events))
        self._live: dict[str, PodRecord] = {}
        self._sinks: list = []
        # Lifetime counters (survive pod eviction from the live map).
        self.counts: dict[str, int] = {state: 0 for state in STATES}
        self.executions_total = 0
        self._spawn_seconds = None
        self._reaped_total = None
        if metrics is not None:
            self._spawn_seconds = metrics.histogram(
                "bci_pool_spawn_seconds",
                "Sandbox spawn latency, spawning to ready",
            )
            self._reaped_total = metrics.counter(
                "bci_pod_reaped_total",
                "Sandboxes removed abnormally (reaped or spawn-failed), by reason",
            )
            metrics.gauge(
                "bci_pool_utilization",
                "Busy fraction of live sandboxes (assigned+executing over live)",
                self.utilization,
            )

    # ------------------------------------------------------------ recording

    def record(
        self,
        pod: str,
        state: str,
        reason: str | None = None,
        detail: str | None = None,
        workers: int | None = None,
        **attrs,
    ) -> None:
        """Record one transition for ``pod``. Unknown states raise — the
        vocabulary above IS the contract the API and dashboards parse.

        ``reason`` is CATEGORICAL (warm_pop / cold_spawn / single_use /
        unhealthy / died_in_queue / shutdown / spawn_failed, …) because it
        becomes a Prometheus label on ``bci_pod_reaped_total`` — free text
        there would mint one time series per unique failure message.
        ``detail`` carries the free text (exception string, exit code) on
        the journal event only."""
        if state not in STATES:
            raise ValueError(f"unknown fleet state {state!r}")
        now = time.monotonic()
        rec = self._live.get(pod)
        if rec is None:
            rec = PodRecord(name=pod, state=state, workers=workers or 1)
            self._live[pod] = rec
        rec.state = state
        rec.last_reason = reason
        if workers is not None:
            rec.workers = workers
        event: dict = {
            "ts": time.time(),
            "pod": pod,
            "state": state,
            "workers": rec.workers,
        }
        if reason is not None:
            event["reason"] = reason
        if detail is not None:
            event["detail"] = detail
        event.update(attrs)

        self.counts[state] += 1
        if state == "leased":
            # Set once per sandbox (a sandbox serves at most one lease): the
            # post-execute re-record keeps the ORIGINAL lease age.
            if rec.leased_mono is None:
                rec.leased_mono = now
            if "session" in attrs:
                rec.session = attrs["session"]
        if state == "ready" and rec.ready_mono is None:
            rec.ready_mono = now
            rec.spawn_s = now - rec.created_mono
            event["spawn_s"] = rec.spawn_s
            if self._spawn_seconds is not None:
                self._spawn_seconds.observe(rec.spawn_s)
        elif state == "executing":
            rec.executions += 1
            self.executions_total += 1
        elif state in TERMINAL_STATES:
            event["executions"] = rec.executions
            event["age_s"] = now - rec.created_mono
            self._live.pop(pod, None)
            if state in ("reaped", "failed") and self._reaped_total is not None:
                self._reaped_total.inc(reason=reason or state)
        self._events.append(event)
        for sink in self._sinks:
            # A broken sink (the demand tracker) must never fail the
            # checkout/teardown that recorded this transition.
            try:
                sink(event)
            except Exception:
                logger.exception("fleet-journal sink %r failed", sink)

    def add_sink(self, sink) -> None:
        """Register a callable invoked with each recorded event (the
        capacity tracker's ``on_fleet_event``). Sinks must be cheap and
        non-blocking — they run on the checkout path."""
        self._sinks.append(sink)

    # -------------------------------------------------------------- reading

    def utilization(self) -> float:
        """Busy fraction of live (past-spawn) sandboxes; 0.0 when the pool
        is empty so a drained pool never reads as NaN."""
        live = [r for r in self._live.values() if r.state != "spawning"]
        if not live:
            return 0.0
        busy = sum(1 for r in live if r.state in BUSY_STATES)
        return busy / len(live)

    def snapshot(self) -> dict:
        """Point-in-time pool view: each live pod (state, age, executions
        served, spawn latency) plus lifetime aggregates."""
        pods = sorted(
            (r.to_dict() for r in self._live.values()),
            key=lambda d: d["age_s"],
            reverse=True,
        )
        by_state: dict[str, int] = {}
        for r in self._live.values():
            by_state[r.state] = by_state.get(r.state, 0) + 1
        return {
            "pods": pods,
            "live": len(pods),
            "by_state": by_state,
            "utilization": self.utilization(),
            "executions_total": self.executions_total,
            "lifetime": dict(self.counts),
        }

    def events(self, limit: int | None = None) -> list[dict]:
        """Most recent transitions, newest first; ``limit`` caps the list."""
        out = [dict(e) for e in reversed(self._events)]
        if limit is not None and limit >= 0:
            out = out[:limit]
        return out

    def __len__(self) -> int:
        return len(self._events)
