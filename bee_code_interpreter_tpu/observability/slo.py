"""SLO engine: config-declared objectives, sliding windows, burn-rate alerts.

Raw counters can say "37 requests failed"; they cannot say "at this rate the
month's error budget is gone by Thursday". This module computes the latter
where the data is, per the Google SRE workbook's multi-window multi-burn-rate
methodology: each objective (availability, or latency-under-threshold) is
evaluated over sliding windows, and an alert fires only when BOTH windows of
a pair burn faster than the pair's threshold —

- **page**: 5m AND 1h burning > 14.4× budget (2% of a 30-day budget per hour)
- **ticket**: 30m AND 6h burning > 6× budget

The long window keeps one bad minute from paging; the short window stops the
alert promptly once the bleeding stops.

Objectives come from config: ``APP_SLO_AVAILABILITY=99.5`` (percent of
recorded requests that must not fail server-side) and
``APP_SLO_LATENCY_MS=2000:99`` (comma-separable ``THRESHOLD_MS:PERCENT``
entries: 99% of successful requests complete within 2000 ms).

What counts: the edges record every *sandbox-bound* request the service
accepted. Server-side failures (5xx-equivalents: internal errors, blown
deadlines, open breakers) are availability-bad; client faults (422/400,
``INVALID_ARGUMENT``) are good; deliberate load management (429 shed, drain
503, client cancellation) is EXCLUDED — budget measures the service failing
work it accepted, not refusing work it never promised. Latency objectives
measure successful requests only.

Served at ``GET /v1/slo``, summarized in ``GET /healthz?verbose=1``, and
exported as ``bci_slo_error_budget_remaining_ratio{objective}`` /
``bci_slo_burn_rate{objective,window}`` gauges.

State is a ring of coarse time buckets (default 10 s) covering the longest
window (6 h): O(1) per recorded request, ~2 k buckets max, clock-injectable
so tests hand-compute every number under a manual clock.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

# Window name -> seconds. The four windows the alert pairs need; snapshot()
# reports all of them per objective.
WINDOWS: dict[str, float] = {"5m": 300.0, "30m": 1800.0, "1h": 3600.0, "6h": 21600.0}

# Multi-window multi-burn-rate pairs (SRE workbook ch. 5, 30-day budget).
ALERT_POLICIES = (
    {"severity": "page", "short": "5m", "long": "1h", "burn_threshold": 14.4},
    {"severity": "ticket", "short": "30m", "long": "6h", "burn_threshold": 6.0},
)


@dataclass(frozen=True)
class Objective:
    """One declared objective. ``target`` is the good fraction (0.995);
    ``threshold_ms`` is set for latency objectives only."""

    name: str
    kind: str  # "availability" | "latency"
    target: float
    threshold_ms: float | None = None

    @property
    def error_budget(self) -> float:
        return 1.0 - self.target


def parse_objectives(
    availability_percent: float | None, latency_spec: str | None
) -> list[Objective]:
    """Objectives from the raw config fields; raises ``ValueError`` with the
    offending entry on malformed input (config errors must fail loudly at
    startup, not silently disable alerting)."""
    objectives: list[Objective] = []
    if availability_percent is not None:
        p = float(availability_percent)
        if not 0.0 < p < 100.0:
            raise ValueError(
                f"APP_SLO_AVAILABILITY must be a percent in (0, 100), got {p!r}"
            )
        objectives.append(
            Objective(name="availability", kind="availability", target=p / 100.0)
        )
    for part in (latency_spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        threshold_raw, sep, percent_raw = part.partition(":")
        try:
            if not sep:
                raise ValueError(part)
            threshold_ms = float(threshold_raw)
            percent = float(percent_raw)
        except ValueError:
            raise ValueError(
                f"malformed APP_SLO_LATENCY_MS entry {part!r}; "
                "expected 'THRESHOLD_MS:PERCENT' like '2000:99'"
            ) from None
        if threshold_ms <= 0 or not 0.0 < percent < 100.0:
            raise ValueError(
                f"APP_SLO_LATENCY_MS entry {part!r}: threshold must be > 0 ms "
                "and percent in (0, 100)"
            )
        objectives.append(
            Objective(
                name=f"latency_{threshold_ms:g}ms",
                kind="latency",
                target=percent / 100.0,
                threshold_ms=threshold_ms,
            )
        )
    return objectives


def empty_slo_snapshot() -> dict:
    """What ``GET /v1/slo`` answers when no objectives are declared."""
    return {"objectives": [], "alerting": False, "fast_burn_alerting": False}


def record_sli(engine, ok: bool, duration_s: float, tenant: str | None) -> None:
    """The one edge-side spelling of SLI recording: pass ``tenant`` only
    when one was resolved, so engine doubles without the kwarg (test
    spies, older engines) keep working on tenancy-less servers."""
    if tenant is not None:
        engine.record(ok=ok, duration_s=duration_s, tenant=tenant)
    else:
        engine.record(ok=ok, duration_s=duration_s)


class _Bucket:
    __slots__ = ("total", "errors", "ok_total", "slow")

    def __init__(self, n_latency: int) -> None:
        self.total = 0  # recorded requests (excluded ones never get here)
        self.errors = 0  # availability-bad
        self.ok_total = 0  # latency denominators count successes only
        self.slow = [0] * n_latency  # per latency objective


class SloEngine:
    """Sliding-window objective evaluation. Edges call :meth:`record` per
    recorded request; readers call :meth:`snapshot` / :meth:`burn_rate`."""

    def __init__(
        self,
        objectives,
        metrics=None,
        clock=time.monotonic,
        bucket_s: float = 10.0,
        max_tenants: int = 32,
    ) -> None:
        self._objectives = list(objectives)
        self._latency = [o for o in self._objectives if o.kind == "latency"]
        self._latency_index = {o.name: i for i, o in enumerate(self._latency)}
        self._clock = clock
        self._bucket_s = bucket_s
        self._retention_s = max(WINDOWS.values())
        self._buckets: dict[int, _Bucket] = {}
        # Per-tenant SLO slices (docs/tenancy.md): one child engine per
        # tenant label, same objectives/clock/buckets, bounded to
        # max_tenants (overflow collapses into "other"). Metric-less:
        # per-tenant burn is served by /v1/slo?tenant= and /v1/tenants.
        self._max_tenants = max(1, max_tenants)
        self._tenants: dict[str, SloEngine] = {}
        if metrics is not None and self._objectives:
            for objective in self._objectives:
                metrics.gauge(
                    "bci_slo_error_budget_remaining_ratio",
                    "Error budget left over the 6h window "
                    "(1=untouched, 0=spent, negative=overspent)",
                    (lambda o: lambda: self.error_budget_remaining(o))(objective),
                    objective=objective.name,
                )
                for window in WINDOWS:
                    metrics.gauge(
                        "bci_slo_burn_rate",
                        "Error-budget burn rate by objective and window "
                        "(1=exactly on budget)",
                        (lambda o, w: lambda: self.burn_rate(o, w))(
                            objective, window
                        ),
                        objective=objective.name,
                        window=window,
                    )

    @property
    def objectives(self) -> tuple[Objective, ...]:
        return tuple(self._objectives)

    # ------------------------------------------------------------- recording

    def record(
        self, ok: bool, duration_s: float, tenant: str | None = None
    ) -> None:
        """One request outcome. ``ok=False`` burns availability budget;
        slow-but-successful requests burn latency budget. Callers simply do
        not call this for excluded outcomes (shed/drain/cancel). With a
        ``tenant`` label the sample ALSO lands in that tenant's SLO slice,
        so one tenant's failures burn its own budget visibly — the global
        number still aggregates everyone."""
        if not self._objectives:
            return
        if tenant is not None:
            self._tenant_engine(tenant).record(ok, duration_s)
        idx = int(self._clock() // self._bucket_s)
        bucket = self._buckets.get(idx)
        if bucket is None:
            self._prune(idx)
            bucket = self._buckets[idx] = _Bucket(len(self._latency))
        bucket.total += 1
        if ok:
            bucket.ok_total += 1
            for i, objective in enumerate(self._latency):
                if duration_s * 1000.0 > objective.threshold_ms:
                    bucket.slow[i] += 1
        else:
            bucket.errors += 1

    def _tenant_engine(self, tenant: str) -> "SloEngine":
        engine = self._tenants.get(tenant)
        if engine is None:
            if len(self._tenants) >= self._max_tenants and tenant != "other":
                return self._tenant_engine("other")
            engine = self._tenants[tenant] = SloEngine(
                self._objectives,
                clock=self._clock,
                bucket_s=self._bucket_s,
                max_tenants=1,
            )
        return engine

    def _prune(self, now_idx: int) -> None:
        horizon = now_idx - int(self._retention_s // self._bucket_s) - 1
        for idx in [i for i in self._buckets if i < horizon]:
            del self._buckets[idx]

    # --------------------------------------------------------------- reading

    def _window_counts(self, objective: Objective, window_s: float):
        """(total, bad) over the trailing window. A bucket belongs to the
        window while any part of its [idx*b, (idx+1)*b) span is inside it."""
        now = self._clock()
        total = bad = 0
        latency_i = self._latency_index.get(objective.name)
        for idx, bucket in self._buckets.items():
            if (idx + 1) * self._bucket_s <= now - window_s:
                continue
            if objective.kind == "availability":
                total += bucket.total
                bad += bucket.errors
            else:
                total += bucket.ok_total
                bad += bucket.slow[latency_i]
        return total, bad

    def bad_ratio(self, objective: Objective, window_s: float) -> float:
        total, bad = self._window_counts(objective, window_s)
        return bad / total if total else 0.0

    def burn_rate(self, objective: Objective, window: str | float) -> float:
        """bad_ratio / error_budget: 1.0 means burning exactly at the rate
        that exhausts the budget over the SLO period; 0 with no traffic."""
        window_s = WINDOWS[window] if isinstance(window, str) else window
        budget = objective.error_budget
        if budget <= 0.0:
            return 0.0
        return self.bad_ratio(objective, window_s) / budget

    def error_budget_remaining(self, objective: Objective) -> float:
        """1 - (6h bad ratio / budget): 1 with a clean window, 0 when the
        budget is exactly spent, negative when overspent."""
        budget = objective.error_budget
        if budget <= 0.0:
            return 1.0
        return 1.0 - self.bad_ratio(objective, WINDOWS["6h"]) / budget

    def snapshot(self) -> dict:
        """The ``GET /v1/slo`` document: per objective the window stats,
        budget remaining, and alert states; top-level rollups for health
        checks (``fast_burn_alerting`` is the page pair). Walks the bucket
        ring once per (objective, window) and derives everything else from
        those counts — snapshot is served per /v1/slo hit, per verbose
        healthz, and inside every debug bundle."""
        objectives = []
        fast_burn = alerting = False
        for objective in self._objectives:
            budget = objective.error_budget
            windows = {}
            for name, window_s in WINDOWS.items():
                total, bad = self._window_counts(objective, window_s)
                ratio = bad / total if total else 0.0
                windows[name] = {
                    "total": total,
                    "bad": bad,
                    "bad_ratio": ratio,
                    "burn_rate": ratio / budget if budget > 0.0 else 0.0,
                }
            alerts = []
            for policy in ALERT_POLICIES:
                short_burn = windows[policy["short"]]["burn_rate"]
                long_burn = windows[policy["long"]]["burn_rate"]
                firing = (
                    short_burn >= policy["burn_threshold"]
                    and long_burn >= policy["burn_threshold"]
                )
                alerts.append(
                    {
                        "severity": policy["severity"],
                        "windows": [policy["short"], policy["long"]],
                        "burn_threshold": policy["burn_threshold"],
                        "short_burn_rate": short_burn,
                        "long_burn_rate": long_burn,
                        "firing": firing,
                    }
                )
                if firing:
                    alerting = True
                    if policy["severity"] == "page":
                        fast_burn = True
            objectives.append(
                {
                    "name": objective.name,
                    "kind": objective.kind,
                    "target": objective.target,
                    "threshold_ms": objective.threshold_ms,
                    "error_budget": budget,
                    "error_budget_remaining_ratio": (
                        1.0 - windows["6h"]["burn_rate"]
                    ),
                    "windows": windows,
                    "alerts": alerts,
                }
            )
        out = {
            "objectives": objectives,
            "alerting": alerting,
            "fast_burn_alerting": fast_burn,
        }
        if self._tenants:
            out["tenants"] = self.tenant_summaries()
        return out

    # ------------------------------------------------------- tenant slices

    def tenant_snapshot(self, tenant: str) -> dict:
        """One tenant's full SLO slice (``GET /v1/slo?tenant=``); honestly
        empty for a tenant with no recorded samples."""
        engine = self._tenants.get(tenant)
        if engine is None:
            return empty_slo_snapshot()
        return engine.snapshot()

    def tenant_summaries(self) -> dict[str, dict]:
        """Per-tenant burn rollup for ``/v1/tenants`` and the global
        snapshot: budget remaining + whether that tenant's own alert pairs
        fire — a noisy neighbor burning ITS slice shows here while the
        victims' rows stay quiet."""
        out: dict[str, dict] = {}
        for label in sorted(self._tenants):
            snap = self._tenants[label].snapshot()
            out[label] = {
                "alerting": snap["alerting"],
                "fast_burn_alerting": snap["fast_burn_alerting"],
                "error_budget_remaining_ratio": min(
                    (
                        o["error_budget_remaining_ratio"]
                        for o in snap["objectives"]
                    ),
                    default=1.0,
                ),
            }
        return out
