"""On-demand ``jax.profiler`` capture behind ``POST /v1/profile``.

The trace-inspection API (``/v1/traces``) finds the slow *stage*; this
module drills into the slow *op* without redeploying anything:

- **Sandbox executions**: the in-pod shim already starts a profiler trace
  when ``BCI_PROFILE_DIR`` is set (``runtime/shim/sitecustomize.py``), but
  until now only an operator editing request env could use it. The edge
  injects :data:`SANDBOX_PROFILE_DIR` into the request env and the trace
  artifacts ride back through the ordinary changed-file snapshot — no new
  download channel.
- **The serving engine**: :class:`ServingProfiler` wraps anything with a
  ``step()`` (an ``Engine`` or ``ContinuousBatcher``) and captures N steps
  under ``jax.profiler`` into a local directory the operator can pull into
  TensorBoard/XProf.

``jax`` is imported lazily: a control plane serving only the executor path
never pays a jax import for having the endpoint mounted.
"""

from __future__ import annotations

import os
import shutil
import tempfile
import threading
import time
from pathlib import Path

# Where a profiled sandbox execution writes its trace; lives under the
# workspace so the artifacts come back via the changed-file map.
PROFILE_DIR_ENV = "BCI_PROFILE_DIR"
SANDBOX_PROFILE_DIR = "/workspace/.bci-profile"


class ProfilerUnavailable(RuntimeError):
    """jax (or its profiler backend) is not importable/usable here."""


def inject_profile_env(env: dict[str, str] | None) -> dict[str, str]:
    """Request env with the shim's profile trigger set (a caller's
    NON-EMPTY value wins, so a client may point the trace elsewhere in the
    workspace). Empty counts as unset: the shim ignores an empty dir, and
    a "" profile_dir would make every changed file look like an artifact
    (prefix "/" matches all workspace paths)."""
    out = dict(env or {})
    if not out.get(PROFILE_DIR_ENV):
        out[PROFILE_DIR_ENV] = SANDBOX_PROFILE_DIR
    return out


def profile_artifacts(files: dict[str, str], profile_dir: str) -> list[str]:
    """The changed-file paths that are profiler trace artifacts."""
    prefix = profile_dir.rstrip("/") + "/"
    return sorted(p for p in files if p.startswith(prefix))


class ServingProfiler:
    """Captures batcher/engine steps under ``jax.profiler``.

    ``stepper`` is anything with a ``step()`` method. Overlapping captures
    are rejected internally (atomic check-and-set under a lock) —
    ``jax.profiler`` is process-global and two concurrent traces would
    corrupt each other, and the HTTP handler runs captures off-loop in a
    thread pool where two requests CAN race.
    """

    def __init__(self, stepper, trace_root: str | Path | None = None) -> None:
        self._stepper = stepper
        self._trace_root = str(trace_root) if trace_root else None
        self._capturing = False
        self._lock = threading.Lock()

    @property
    def capturing(self) -> bool:
        return self._capturing

    @property
    def available(self) -> bool:
        """True when the stepper can actually step. A stepper may expose
        its own ``available`` (the ServingMonitor reports False until an
        engine attaches — the edge answers 501, not a capture error);
        steppers without the attribute are assumed ready."""
        return bool(getattr(self._stepper, "available", True))

    def capture(self, steps: int) -> dict:
        """Run ``steps`` stepper steps under a profiler trace; returns
        ``{trace_dir, files, steps, duration_ms}`` with ``files`` relative
        to ``trace_dir``. Raises :class:`ProfilerUnavailable` if a capture
        is already running."""
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        with self._lock:
            if self._capturing:
                raise ProfilerUnavailable("a capture is already in progress")
            self._capturing = True
        # EVERY exit path below must reset the flag — a stuck True would
        # 503 all future serving captures until process restart.
        try:
            try:
                import jax
            except ImportError as e:  # pragma: no cover - jax is baked in
                raise ProfilerUnavailable(f"jax not importable: {e}") from e
            trace_dir = tempfile.mkdtemp(
                prefix="bci-profile-", dir=self._trace_root
            )
            t0 = time.monotonic()
            try:
                jax.profiler.start_trace(trace_dir)
            except Exception as e:
                # Nothing was captured: don't leak an empty trace dir per
                # failed attempt on hosts without a profiler backend.
                shutil.rmtree(trace_dir, ignore_errors=True)
                raise ProfilerUnavailable(
                    f"jax.profiler unavailable: {e}"
                ) from e
            try:
                for _ in range(steps):
                    self._stepper.step()
            finally:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
        finally:
            self._capturing = False
        files = sorted(
            str(Path(root, name).relative_to(trace_dir))
            for root, _dirs, names in os.walk(trace_dir)
            for name in names
        )
        return {
            "trace_dir": trace_dir,
            "files": files,
            "steps": steps,
            "duration_ms": (time.monotonic() - t0) * 1000.0,
        }


class DeviceProfiler:
    """``POST /v1/profile target=device``: one on-demand ``jax.profiler``
    trace directory of raw device activity (docs/observability.md
    "Accelerator observability").

    Unlike ``target=serving`` this does not REQUIRE an engine: with one
    attached (``stepper.available``) the capture windows real batcher
    steps; without one it runs a small probe computation so the timeline
    is never empty — the capture is about the DEVICE runtime (XLA ops,
    transfers, compilation), not the serving loop. Raises
    :class:`ProfilerUnavailable` with the concrete reason (the edge's 501
    body) when the runtime cannot trace at all.
    """

    def __init__(self, stepper=None, trace_root: str | Path | None = None) -> None:
        self._stepper = stepper
        self._trace_root = str(trace_root) if trace_root else None
        self._capturing = False
        self._lock = threading.Lock()

    @property
    def capturing(self) -> bool:
        return self._capturing

    @property
    def available(self) -> bool:
        """True when jax.profiler is importable here. Whether start_trace
        actually works on this backend is only knowable by trying — the
        capture path turns that failure into ProfilerUnavailable."""
        try:
            import jax.profiler  # noqa: F401
        except Exception:
            return False
        return True

    def capture(self, steps: int = 8) -> dict:
        """Capture a device trace: ``steps`` engine steps when an engine is
        attached, a probe computation otherwise. Returns the
        ``ServingProfiler.capture`` shape plus ``source`` =
        ``serving|probe``."""
        if steps < 1:
            raise ValueError(f"steps must be >= 1, got {steps}")
        with self._lock:
            if self._capturing:
                raise ProfilerUnavailable("a capture is already in progress")
            self._capturing = True
        try:
            try:
                import jax
                import jax.numpy as jnp
            except ImportError as e:  # pragma: no cover - jax is baked in
                raise ProfilerUnavailable(f"jax not importable: {e}") from e
            trace_dir = tempfile.mkdtemp(
                prefix="bci-device-profile-", dir=self._trace_root
            )
            t0 = time.monotonic()
            try:
                jax.profiler.start_trace(trace_dir)
            except Exception as e:
                shutil.rmtree(trace_dir, ignore_errors=True)
                raise ProfilerUnavailable(
                    f"jax.profiler cannot trace on this runtime: {e}"
                ) from e
            stepped = bool(
                self._stepper is not None
                and getattr(self._stepper, "available", True)
            )
            try:
                if stepped:
                    for _ in range(steps):
                        self._stepper.step()
                else:
                    x = jnp.ones((256, 256), dtype=jnp.float32)
                    for _ in range(steps):
                        x = x @ x / 256.0
                    x.block_until_ready()
            finally:
                try:
                    jax.profiler.stop_trace()
                except Exception:
                    pass
        finally:
            self._capturing = False
        files = sorted(
            str(Path(root, name).relative_to(trace_dir))
            for root, _dirs, names in os.walk(trace_dir)
            for name in names
        )
        return {
            "trace_dir": trace_dir,
            "files": files,
            "steps": steps,
            "source": "serving" if stepped else "probe",
            "duration_ms": (time.monotonic() - t0) * 1000.0,
        }
