"""Fleet-scoped observability queries at the router edge (docs/fleet.md,
docs/observability.md "Fleet observability").

Every observability surface built for one replica — traces, wide events,
SLO snapshots, tenants, the debug bundle — terminates at that replica; N
replicas behind a router are N disconnected answers. The
:class:`FederationPlane` turns them into ONE answer at the edge the client
actually talks to, by scatter-gathering the same GET across the live
replicas and merging with the router's own local view.

Contract, deliberately partial-tolerant:

- **Never a 500 because one replica is down.** A dead, breaker-open,
  timed-out, or garbage-answering replica is *accounted*, not fatal: every
  federated response carries ``replicas_reporting`` (names that answered)
  and ``replicas_failed`` (name → reason) so a partial answer is visibly
  partial.
- **Bounded fan-out.** One concurrent GET per live replica, each with its
  own ``APP_ROUTER_FEDERATION_TIMEOUT_S`` deadline, issued through the
  router's existing per-replica circuit breakers (``call_replica``) — a
  replica that stops answering federated queries trips the same breaker
  the data plane uses, and an open breaker skips the call entirely.
- **Dead replicas cost nothing.** Replicas the refresh loop already marked
  dead are accounted as ``"dead"`` without a network call.

The plane is duck-typed against :class:`fleet.router.FleetRouter` (it only
reads ``replicas``/``dead_after_s`` and calls ``call_replica``), so this
module stays free of any ``fleet`` import — ``fleet.router`` imports *it*.
"""

from __future__ import annotations

import asyncio
import time
from urllib.parse import quote

from bee_code_interpreter_tpu.observability.bundle import build_debug_bundle
from bee_code_interpreter_tpu.resilience import BreakerOpenError


class FederationPlane:
    """Scatter-gather fan-out over a router's live replicas, merged with
    the router's own stores. All query methods are total: they return a
    (possibly partial) document, never raise for replica trouble."""

    def __init__(self, router, *, timeout_s: float = 2.0, metrics=None) -> None:
        from bee_code_interpreter_tpu.utils.metrics import Registry

        self._router = router
        self._timeout_s = timeout_s
        self._clock = getattr(router, "_clock", time.monotonic)
        metrics = metrics or Registry()
        self._requests_total = metrics.counter(
            "bci_federation_requests_total",
            "Federated fleet queries served at this router edge, by "
            "endpoint",
        )
        self._replica_errors_total = metrics.counter(
            "bci_federation_replica_errors_total",
            "Per-replica failures during federated fan-out, by reason "
            "(dead/breaker_open/timeout/unreachable/http_*/bad_json)",
        )
        self._fanout_seconds = metrics.histogram(
            "bci_federation_fanout_seconds",
            "Wall-clock of one federated scatter-gather, by endpoint",
        )
        self._last_target_replicas = 0
        metrics.gauge(
            "bci_fleet_target_replicas",
            "Replica count the federated autoscale query last recommended "
            "at this router edge (0 before the first query)",
            lambda: float(self._last_target_replicas),
        )

    # ------------------------------------------------------------ fan-out

    async def _fan_out(
        self,
        endpoint: str,
        path: str,
        *,
        params=None,
        accept: tuple[int, ...] = (200,),
        timeout_s: float | None = None,
    ) -> tuple[dict, dict]:
        """One bounded scatter-gather: ``(answers, failed)`` where answers
        maps replica name → ``(status, parsed_body)`` for statuses in
        ``accept`` and failed maps name → reason for everything else."""
        router = self._router
        self._requests_total.inc(endpoint=endpoint)
        now = self._clock()
        live, failed = [], {}
        for name in sorted(router.replicas):
            replica = router.replicas[name]
            if replica.state(now, router.dead_after_s) == "dead":
                failed[name] = "dead"
            else:
                live.append(replica)

        async def one(replica):
            try:
                response = await router.call_replica(
                    replica,
                    "GET",
                    path,
                    params=params,
                    timeout=timeout_s or self._timeout_s,
                )
            except asyncio.CancelledError:
                raise
            except BreakerOpenError:
                return replica.name, None, "breaker_open"
            except asyncio.TimeoutError:
                return replica.name, None, "timeout"
            except Exception:
                return replica.name, None, "unreachable"
            if response.status_code not in accept:
                return replica.name, None, f"http_{response.status_code}"
            try:
                body = response.json()
            except ValueError:
                return replica.name, None, "bad_json"
            if not isinstance(body, dict):
                return replica.name, None, "bad_json"
            return replica.name, (response.status_code, body), None

        start = self._clock()
        answers: dict[str, tuple[int, dict]] = {}
        for name, answer, reason in await asyncio.gather(
            *(one(r) for r in live)
        ):
            if reason is not None:
                failed[name] = reason
                self._replica_errors_total.inc(reason=reason)
            else:
                answers[name] = answer
        self._fanout_seconds.observe(self._clock() - start, endpoint=endpoint)
        return answers, failed

    @staticmethod
    def _accounted(body: dict, answers: dict, failed: dict) -> dict:
        """Stamp the partial-result contract onto a federated response."""
        body["replicas_reporting"] = sorted(answers)
        body["replicas_failed"] = {k: failed[k] for k in sorted(failed)}
        return body

    # ------------------------------------------------------------ queries

    async def slo(self, tenant: str | None = None) -> dict:
        """Federated ``GET /v1/slo``: the router's USER-PERCEIVED engine
        (what clients saw after retries/failover) at top level — so
        ``slo-report.py``/``health_check.py`` pointed at a router edge read
        the same keys they read on a replica — plus each live replica's
        own budget snapshot under ``fleet`` and two fleet-wide rollups."""
        params = {"tenant": tenant} if tenant is not None else None
        answers, failed = await self._fan_out("slo", "/v1/slo", params=params)
        router = self._router
        body = (
            router.slo.tenant_snapshot(tenant)
            if tenant is not None
            else router.slo.snapshot()
        )
        fleet = {name: doc for name, (_status, doc) in answers.items()}
        body["fleet"] = {k: fleet[k] for k in sorted(fleet)}
        # Any-replica rollups: a single replica paging is a fleet fact even
        # while the user-perceived edge numbers still look clean.
        body["fleet_alerting"] = any(
            doc.get("alerting") for doc in fleet.values()
        )
        body["fleet_fast_burn"] = any(
            doc.get("fast_burn_alerting") for doc in fleet.values()
        )
        return self._accounted(body, answers, failed)

    async def traces(
        self,
        limit: int | None = None,
        min_duration_ms: float | None = None,
    ) -> dict:
        """Federated ``GET /v1/traces``: router + replica trace summaries
        merged newest-first, each stamped with its ``source`` (``router``
        or the replica name)."""
        params = {}
        if limit is not None:
            params["limit"] = str(limit)
        if min_duration_ms is not None:
            params["min_duration_ms"] = str(min_duration_ms)
        answers, failed = await self._fan_out(
            "traces", "/v1/traces", params=params or None
        )
        merged = []
        for t in self._router.trace_store.traces():
            if (
                min_duration_ms is not None
                and t.duration_s * 1000.0 < min_duration_ms
            ):
                continue
            merged.append({**t.summary(), "source": "router"})
        for name in sorted(answers):
            _status, doc = answers[name]
            for summary in doc.get("traces") or []:
                if isinstance(summary, dict):
                    merged.append({**summary, "source": name})
        merged.sort(key=lambda d: d.get("start_unix") or 0.0, reverse=True)
        if limit is not None:
            merged = merged[:limit]
        return self._accounted({"traces": merged}, answers, failed)

    async def trace(self, trace_id: str) -> dict:
        """Federated ``GET /v1/traces/{id}``: ONE distributed trace
        stitched by trace_id — the router's spans plus every replica's
        continuation — with a merged ``spans`` list (each span stamped
        with its ``source``) and the per-source documents intact. A 404
        from a replica means "not mine", not a failure; ``sources`` empty
        means the trace is known nowhere that answered."""
        answers, failed = await self._fan_out(
            "trace",
            f"/v1/traces/{quote(trace_id, safe='')}",
            accept=(200, 404),
        )
        docs: dict[str, dict] = {}
        own = self._router.trace_store.get(trace_id)
        if own is not None:
            docs["router"] = own.to_dict()
        for name in sorted(answers):
            status, doc = answers[name]
            if status == 200:
                docs[name] = doc
        sources = [s for s in ("router", *sorted(answers)) if s in docs]
        spans = []
        for source in sources:
            for sp in docs[source].get("spans") or []:
                if isinstance(sp, dict):
                    spans.append({**sp, "source": source})
        body = {
            "trace_id": trace_id,
            "sources": sources,
            "router": docs.get("router"),
            "replicas": {n: d for n, d in docs.items() if n != "router"},
            "spans": spans,
        }
        return self._accounted(body, answers, failed)

    async def events(
        self,
        *,
        limit: int | None = None,
        kind: str | None = None,
        outcome: str | None = None,
        session: str | None = None,
        tenant: str | None = None,
        min_duration_ms: float | None = None,
        since: float | None = None,
    ) -> dict:
        """Federated ``GET /v1/events``: the router's own routing/migration
        journal merged with every live replica's wide events, same filter
        surface, each event stamped with its ``source``. Timestamps order
        the merge; they are per-host clocks, close enough for a tail."""
        params = {}
        for name, value in (
            ("limit", limit),
            ("kind", kind),
            ("outcome", outcome),
            ("session", session),
            ("tenant", tenant),
            ("min_duration_ms", min_duration_ms),
            ("since", since),
        ):
            if value is not None:
                params[name] = str(value)
        answers, failed = await self._fan_out(
            "events", "/v1/events", params=params or None
        )
        merged = [
            {**event, "source": "router"}
            for event in self._router.recorder.events(
                limit=limit,
                kind=kind,
                outcome=outcome,
                session=session,
                tenant=tenant,
                min_duration_ms=min_duration_ms,
                since=since,
            )
        ]
        for name in sorted(answers):
            _status, doc = answers[name]
            for event in doc.get("events") or []:
                if isinstance(event, dict):
                    merged.append({**event, "source": name})
        merged.sort(key=lambda e: e.get("ts") or 0.0, reverse=True)
        if limit is not None:
            merged = merged[:limit]
        return self._accounted({"events": merged}, answers, failed)

    async def tenants(self) -> dict:
        """Federated ``GET /v1/tenants``: each live replica's isolation/
        billing snapshot side by side with the router's fleet-wide
        quota-lease ledger — the two halves of the tenancy plane in one
        answer. A replica answering 501 (no tenant registry wired) reports
        ``null``, which is its honest answer, not a failure."""
        answers, failed = await self._fan_out(
            "tenants", "/v1/tenants", accept=(200, 501)
        )
        replicas = {
            name: (doc if status == 200 else None)
            for name, (status, doc) in answers.items()
        }
        body = {
            "replicas": {k: replicas[k] for k in sorted(replicas)},
            "quota": self._router.ledger.snapshot(),
        }
        return self._accounted(body, answers, failed)

    async def autoscale(self) -> dict:
        """Federated ``GET /v1/autoscale``: each live replica's demand/
        forecast document side by side, summed into one fleet-wide demand
        signal, and — the loop the forecaster exists for — a fleet
        **replica-count recommendation** (docs/capacity.md). Rates and
        concurrency add across replicas; the horizon is the slowest
        replica's (a pre-spawn must beat the worst spawn anywhere); the
        per-replica capacity unit is the largest pool ceiling any replica
        reports. A replica answering 501 (no capacity tracker wired)
        reports ``null`` — its honest answer, not a failure. The router's
        own user-perceived fast-burn page vetoes any shrink, exactly as on
        the single-replica edge."""
        from bee_code_interpreter_tpu.observability.forecast import (
            recommend_replicas,
        )

        answers, failed = await self._fan_out(
            "autoscale", "/v1/autoscale", accept=(200, 501)
        )
        router = self._router
        replicas = {
            name: (doc if status == 200 else None)
            for name, (status, doc) in answers.items()
        }
        wired = [doc for doc in replicas.values() if doc is not None]
        demands = [d.get("demand") or {} for d in wired]
        forecasts = [d.get("forecast") or {} for d in wired]
        by_tenant: dict[str, dict[str, int]] = {}
        for demand in demands:
            for tenant, counts in (demand.get("by_tenant") or {}).items():
                slot = by_tenant.setdefault(
                    tenant, {"arrivals": 0, "sheds": 0}
                )
                slot["arrivals"] += int(counts.get("arrivals") or 0)
                slot["sheds"] += int(counts.get("sheds") or 0)
        fleet_demand = {
            "rps_10s": sum(d.get("rps_10s") or 0.0 for d in demands),
            "peak_rps_60s": sum(d.get("peak_rps_60s") or 0.0 for d in demands),
            "sheds_60s": sum(int(d.get("sheds_60s") or 0) for d in demands),
            "sheds_total": sum(int(d.get("sheds_total") or 0) for d in demands),
            "arrivals_total": sum(
                int(d.get("arrivals_total") or 0) for d in demands
            ),
            "concurrency_high_water_60s": sum(
                int(d.get("concurrency_high_water_60s") or 0) for d in demands
            ),
            "warm_pop_ratio_min": min(
                (
                    d.get("warm_pop_ratio_60s")
                    for d in demands
                    if d.get("warm_pop_ratio_60s") is not None
                ),
                default=1.0,
            ),
            "by_tenant": {k: by_tenant[k] for k in sorted(by_tenant)},
        }
        fleet_forecast = {
            "forecast_rps": sum(
                f.get("forecast_rps") or 0.0 for f in forecasts
            ),
            "horizon_s": max(
                (f.get("horizon_s") or 0.0 for f in forecasts), default=0.0
            ),
        }
        per_replica = max(
            (int(d.get("max") or 0) for d in wired), default=0
        ) or 8
        now = self._clock()
        states = {"healthy": 0, "draining": 0, "dead": 0}
        for replica in router.replicas.values():
            state = replica.state(now, router.dead_after_s)
            states[state] = states.get(state, 0) + 1
        burn = bool(
            router.slo.snapshot().get("fast_burn_alerting", False)
        )
        recommendation = recommend_replicas(
            forecast_rps=fleet_forecast["forecast_rps"],
            horizon_s=fleet_forecast["horizon_s"],
            concurrency_high_water=fleet_demand["concurrency_high_water_60s"],
            per_replica_capacity=per_replica,
            current_replicas=states["healthy"],
            slo_fast_burn=burn,
        )
        self._last_target_replicas = recommendation["target_replicas"]
        body = {
            "demand": fleet_demand,
            "forecast": fleet_forecast,
            "recommendation": recommendation,
            "replica_states": states,
            "replicas": {k: replicas[k] for k in sorted(replicas)},
        }
        return self._accounted(body, answers, failed)

    async def debug_bundle(self) -> dict:
        """``GET /v1/fleet/debug/bundle``: the one-call incident snapshot
        for the whole fleet — the router's own bundle (traces, SLO, events,
        metrics) plus its decision snapshot, and every live replica's full
        debug bundle. Partial-tolerant like every federated query: a dead
        replica costs an accounting entry, not the bundle."""
        answers, failed = await self._fan_out(
            "bundle",
            "/v1/debug/bundle",
            # Bundles are the heaviest federated answer; give slow replicas
            # headroom beyond the per-query default.
            timeout_s=max(self._timeout_s, 5.0),
        )
        router = self._router
        router_bundle = build_debug_bundle(
            tracer=router.tracer,
            slo=router.slo,
            metrics=router.metrics,
            recorder=router.recorder,
        )
        router_bundle["snapshot"] = router.snapshot()
        body = {
            "generated_unix": time.time(),
            "router": router_bundle,
            "replicas": {
                name: doc for name, (_status, doc) in sorted(answers.items())
            },
        }
        return self._accounted(body, answers, failed)
