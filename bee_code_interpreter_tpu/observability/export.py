"""Telemetry export: OTLP/JSON-over-HTTP push of traces and metric snapshots.

PRs 2–3 made the observability stack rich but replica-local: traces live in a
bounded in-memory ring and vanish on restart, metrics are pull-only. This
module is the fleet-scale half — a background :class:`TelemetryExporter` that
batches finished traces (fed by a ``Tracer`` sink), flight-recorder wide
events as the **logs signal** (fed by a ``FlightRecorder`` sink), and
periodic snapshots of the whole metrics ``Registry`` into OTLP/JSON payloads
and pushes them to the collector named by ``APP_OTLP_ENDPOINT``
(``POST {endpoint}/v1/traces``, ``/v1/logs`` and ``/v1/metrics``, the
standard OTLP/HTTP paths).

The wire format is hand-rolled (no OTel SDK in the image) but spec-conformant
in the shapes a collector actually parses: ``resourceSpans`` → ``scopeSpans``
→ spans with base16 trace/span ids, uint64 nano timestamps as decimal
strings, and ``resourceMetrics`` with cumulative sums/gauges/histograms.

Operational contract (docs/observability.md "Telemetry export"):

- **Drop, never block.** The request path only ever appends to a bounded
  deque; a full queue or a dead collector costs the request nothing. Every
  trace that does not reach the collector is accounted in
  ``bci_telemetry_dropped_total{signal,reason}`` — exported + dropped +
  queued always equals enqueued.
- **Retry with backoff, then drop the batch.** Sends reuse the resilience
  retry schedule (:class:`~bee_code_interpreter_tpu.resilience.retry.RetryPolicy`);
  after the attempts are exhausted the batch is dropped (``send_failed``)
  and the remaining queue waits for the next flush, so one outage never
  snowballs into a retry storm.
"""

from __future__ import annotations

import asyncio
import itertools
import json
import logging
import time
from collections import deque

from bee_code_interpreter_tpu.resilience.retry import RetryPolicy
from bee_code_interpreter_tpu.utils.metrics import (
    Counter,
    Gauge,
    Histogram,
    Registry,
)

logger = logging.getLogger(__name__)

TRACES_PATH = "/v1/traces"
METRICS_PATH = "/v1/metrics"
LOGS_PATH = "/v1/logs"
SCOPE_NAME = "bee_code_interpreter_tpu.observability"

# OTLP severity numbers (opentelemetry.proto.logs.v1.SeverityNumber) for
# the wide-event outcomes worth distinguishing downstream.
_SEVERITY_INFO, _SEVERITY_WARN, _SEVERITY_ERROR = 9, 13, 17
_WARN_OUTCOMES = frozenset({"stall", "shed", "drained", "breaker_open"})
_ERROR_OUTCOMES = frozenset({"error", "deadline"})

_SPAN_KIND_INTERNAL = 1  # opentelemetry.proto.trace.v1.Span.SpanKind
_STATUS_OK, _STATUS_ERROR = 1, 2  # Status.StatusCode
_CUMULATIVE = 2  # AggregationTemporality


def _attr(key: str, value) -> dict:
    return {"key": key, "value": {"stringValue": str(value)}}


def _nanos(unix_s: float) -> str:
    # proto3 JSON maps uint64 to a decimal string; collectors reject numbers.
    return str(int(unix_s * 1e9))


def span_to_otlp(span) -> dict:
    """One :class:`~..tracing.Span` as an OTLP/JSON span object. Ids are
    base16 (the OTLP/JSON special case — NOT base64 like other bytes)."""
    end_unix = span.start_unix + (span.duration_s or 0.0)
    out = {
        "traceId": span.trace_id,
        "spanId": span.span_id,
        "name": span.name,
        "kind": _SPAN_KIND_INTERNAL,
        "startTimeUnixNano": _nanos(span.start_unix),
        "endTimeUnixNano": _nanos(end_unix),
        "attributes": [_attr(k, v) for k, v in span.attributes.items()],
        "status": {
            "code": _STATUS_ERROR if span.status == "error" else _STATUS_OK
        },
    }
    if span.parent_id is not None:
        out["parentSpanId"] = span.parent_id
    return out


def spans_payload(traces, service_name: str) -> dict:
    """A batch of finished traces as one OTLP/JSON ExportTraceServiceRequest."""
    spans = []
    for trace in traces:
        for s in trace.spans:
            spans.append(span_to_otlp(s))
    return {
        "resourceSpans": [
            {
                "resource": {
                    "attributes": [_attr("service.name", service_name)]
                },
                "scopeSpans": [
                    {"scope": {"name": SCOPE_NAME}, "spans": spans}
                ],
            }
        ]
    }


def log_record_to_otlp(event: dict) -> dict:
    """One flight-recorder wide event as an OTLP/JSON LogRecord: the whole
    event rides in ``body`` as canonical JSON (wide events are the point —
    flattening to attributes would shear the nested fields), with the
    query-worthy scalars (kind/outcome/session) doubled as attributes and
    the trace id attached for log↔trace correlation."""
    outcome = event.get("outcome")
    if outcome in _ERROR_OUTCOMES:
        severity, severity_text = _SEVERITY_ERROR, "ERROR"
    elif outcome in _WARN_OUTCOMES:
        severity, severity_text = _SEVERITY_WARN, "WARN"
    else:
        severity, severity_text = _SEVERITY_INFO, "INFO"
    attributes = [_attr("event.kind", event.get("kind", "event"))]
    for key in ("outcome", "session", "name"):
        if event.get(key):
            attributes.append(_attr(f"event.{key}", event[key]))
    record = {
        "timeUnixNano": _nanos(float(event.get("ts", time.time()))),
        "severityNumber": severity,
        "severityText": severity_text,
        "body": {"stringValue": json.dumps(event, default=str)},
        "attributes": attributes,
    }
    if event.get("trace_id"):
        record["traceId"] = event["trace_id"]
    return record


def logs_payload(events, service_name: str) -> dict:
    """A batch of wide events as one OTLP/JSON ExportLogsServiceRequest."""
    return {
        "resourceLogs": [
            {
                "resource": {
                    "attributes": [_attr("service.name", service_name)]
                },
                "scopeLogs": [
                    {
                        "scope": {"name": SCOPE_NAME},
                        "logRecords": [
                            log_record_to_otlp(e) for e in events
                        ],
                    }
                ],
            }
        ]
    }


def _counter_otlp(metric: Counter, now: str, start: str) -> dict:
    return {
        "sum": {
            "dataPoints": [
                {
                    "attributes": [_attr(k, v) for k, v in key],
                    # startTimeUnixNano lets cumulative consumers detect
                    # counter resets across process restarts (OTLP spec)
                    "startTimeUnixNano": start,
                    "timeUnixNano": now,
                    "asDouble": value,
                }
                for key, value in sorted(metric._values.items())
            ],
            "aggregationTemporality": _CUMULATIVE,
            "isMonotonic": True,
        }
    }


def _gauge_otlp(metric: Gauge, now: str) -> dict:
    points = []
    for key, fn in sorted(metric._fns.items()):
        try:
            value = float(fn())
        except Exception:
            continue  # one broken callback must not sink the whole snapshot
        points.append(
            {
                "attributes": [_attr(k, v) for k, v in key],
                "timeUnixNano": now,
                "asDouble": value,
            }
        )
    return {"gauge": {"dataPoints": points}}


def _histogram_otlp(metric: Histogram, now: str, start: str) -> dict:
    points = []
    for key in sorted(metric._totals):
        total = metric._totals[key]
        # OTLP wants per-bucket counts with one overflow bucket beyond the
        # last explicit bound — exactly the histogram's native accessor.
        per_bucket = metric.per_bucket_counts(key)
        points.append(
            {
                "attributes": [_attr(k, v) for k, v in key],
                "startTimeUnixNano": start,
                "timeUnixNano": now,
                "count": str(total),
                "sum": metric._sums[key],
                "bucketCounts": [str(c) for c in per_bucket],
                "explicitBounds": list(metric._buckets),
            }
        )
    return {
        "histogram": {
            "dataPoints": points,
            "aggregationTemporality": _CUMULATIVE,
        }
    }


def metrics_payload(
    registry: Registry, service_name: str, start_unix: float | None = None
) -> dict:
    """The registry's current state as one OTLP/JSON
    ExportMetricsServiceRequest. Cumulative temporality, so every sum and
    histogram point is stamped with ``start_unix`` (when the accumulation
    began — the exporter passes its construction time) so consumers can
    detect counter resets across restarts."""
    now = _nanos(time.time())
    start = _nanos(start_unix) if start_unix is not None else now
    metrics = []
    for name, metric in registry.metrics.items():
        entry: dict = {"name": name, "description": metric.help}
        if isinstance(metric, Counter):
            entry.update(_counter_otlp(metric, now, start))
        elif isinstance(metric, Gauge):
            entry.update(_gauge_otlp(metric, now))
        elif isinstance(metric, Histogram):
            entry.update(_histogram_otlp(metric, now, start))
        else:  # pragma: no cover - no fourth metric type exists
            continue
        metrics.append(entry)
    return {
        "resourceMetrics": [
            {
                "resource": {
                    "attributes": [_attr("service.name", service_name)]
                },
                "scopeMetrics": [
                    {"scope": {"name": SCOPE_NAME}, "metrics": metrics}
                ],
            }
        ]
    }


class TelemetryExporter:
    """Background push of traces + metric snapshots to an OTLP collector.

    Wire it as a ``Tracer`` sink (:meth:`enqueue_trace`) and :meth:`start`
    it once a loop is running; :meth:`stop` flushes what it can and closes
    the HTTP client. ``transport`` (an ``async (path, body_bytes) -> None``)
    replaces the httpx POST for tests and the chaos harness.
    """

    def __init__(
        self,
        endpoint: str,
        metrics: Registry,
        *,
        service_name: str = "bee-code-interpreter-tpu",
        flush_interval_s: float = 5.0,
        queue_max: int = 512,
        batch_max: int = 64,
        retry: RetryPolicy | None = None,
        timeout_s: float = 10.0,
        transport=None,
    ) -> None:
        self._endpoint = endpoint.rstrip("/")
        self._registry = metrics
        self._service_name = service_name
        self._flush_interval_s = flush_interval_s
        self._queue_max = queue_max
        self._batch_max = batch_max
        self._retry = retry or RetryPolicy(
            attempts=3, wait_min_s=0.5, wait_max_s=5.0
        )
        self._timeout_s = timeout_s
        self._transport = transport
        self._queue: deque = deque()
        # Wide events bound for the logs signal: the same drop-not-block
        # queue discipline and exact accounting as traces, separately
        # bounded so a log storm can't evict traces (or vice versa).
        self._logs_queue: deque = deque()
        self._start_unix = time.time()  # cumulative-point start stamp
        self._wake = asyncio.Event()
        self._task: asyncio.Task | None = None
        self._client = None
        self._stopping = False
        self._exported_total = metrics.counter(
            "bci_telemetry_exported_total",
            "Telemetry successfully pushed to the OTLP collector, by signal",
        )
        self._dropped_total = metrics.counter(
            "bci_telemetry_dropped_total",
            "Telemetry dropped instead of blocking the request path, "
            "by signal and reason",
        )
        metrics.gauge(
            "bci_telemetry_queue_depth",
            "Finished traces waiting for the next export flush",
            lambda: len(self._queue),
        )

    # ---------------------------------------------------------- request path

    def enqueue_trace(self, trace) -> None:
        """Tracer sink: O(1), no I/O, never blocks. A full queue drops the
        NEW trace (the queued ones are already promised to the collector)
        and accounts it — backpressure must never reach the request."""
        if len(self._queue) >= self._queue_max:
            self._dropped_total.inc(signal="traces", reason="queue_full")
            return
        self._queue.append(trace)
        if len(self._queue) >= self._batch_max:
            self._wake.set()

    def enqueue_log(self, event: dict) -> None:
        """Flight-recorder sink: wide events bound for ``/v1/logs``. Same
        contract as :meth:`enqueue_trace` — O(1), no I/O, a full queue
        drops the new event and accounts it."""
        if len(self._logs_queue) >= self._queue_max:
            self._dropped_total.inc(signal="logs", reason="queue_full")
            return
        self._logs_queue.append(event)
        if len(self._logs_queue) >= self._batch_max:
            self._wake.set()

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def logs_queue_depth(self) -> int:
        return len(self._logs_queue)

    # ------------------------------------------------------- background loop

    def start(self) -> None:
        """Start the flush loop (requires a running event loop)."""
        if self._task is None or self._task.done():
            self._stopping = False
            self._task = asyncio.create_task(self._run())

    async def stop(self, timeout_s: float | None = 5.0) -> None:
        """Final best-effort flush bounded by ``timeout_s`` of wall clock,
        then close the client. The bound matters at SIGTERM: a blackholed
        collector (connects that hang until the client timeout) must not
        stall teardown past the k8s termination grace and leak the warm
        pool — whatever could not be shipped in time is dropped and
        accounted (``reason="shutdown"``)."""
        self._stopping = True
        self._wake.set()
        pending = self._task
        self._task = None
        if pending is None:
            pending = asyncio.ensure_future(self.flush_once())
        try:
            if timeout_s is None:
                await pending
            else:
                # wait_for cancels the flush on timeout; flush_once pops
                # batches only after a send resolves, so a cancelled send
                # leaves its traces queued for the accounting below.
                await asyncio.wait_for(pending, timeout_s)
        except asyncio.TimeoutError:
            pass  # wait_for already cancelled (and awaited) the flush
        if self._queue:
            self._dropped_total.inc(
                len(self._queue), signal="traces", reason="shutdown"
            )
            self._queue.clear()
        if self._logs_queue:
            self._dropped_total.inc(
                len(self._logs_queue), signal="logs", reason="shutdown"
            )
            self._logs_queue.clear()
        if self._client is not None:
            await self._client.aclose()
            self._client = None

    async def _run(self) -> None:
        while not self._stopping:
            try:
                await asyncio.wait_for(
                    self._wake.wait(), timeout=self._flush_interval_s
                )
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            try:
                await self.flush_once()
            except Exception:  # defensive: the loop must survive anything
                logger.exception("telemetry flush failed")
        await self.flush_once()

    async def _drain_queue(self, queue, path, payload_fn, signal) -> tuple[int, int]:
        """Drain one signal's queue in batches; a failed batch is dropped
        (accounted) and ends this signal's drain for the flush — the rest
        waits for the next interval. Returns (exported, dropped)."""
        exported = dropped = 0
        while queue:
            # Peek, send, THEN pop: a cancellation mid-send (the bounded
            # stop()) leaves the batch queued where shutdown accounting
            # still sees it — no item is ever silently lost.
            batch = list(itertools.islice(queue, self._batch_max))
            sent = await self._push(path, payload_fn(batch, self._service_name))
            for _ in batch:
                queue.popleft()
            if sent:
                self._exported_total.inc(len(batch), signal=signal)
                exported += len(batch)
            else:
                self._dropped_total.inc(
                    len(batch), signal=signal, reason="send_failed"
                )
                dropped += len(batch)
                break
        return exported, dropped

    async def flush_once(self) -> dict:
        """Drain the trace queue in batches, then the wide-event logs
        queue, then push one metrics snapshot."""
        exported, dropped = await self._drain_queue(
            self._queue, TRACES_PATH, spans_payload, "traces"
        )
        logs_exported, logs_dropped = await self._drain_queue(
            self._logs_queue, LOGS_PATH, logs_payload, "logs"
        )
        metrics_ok = await self._push(
            METRICS_PATH,
            metrics_payload(
                self._registry, self._service_name, start_unix=self._start_unix
            ),
        )
        if metrics_ok:
            self._exported_total.inc(signal="metrics")
        else:
            self._dropped_total.inc(signal="metrics", reason="send_failed")
        return {
            "traces_exported": exported,
            "traces_dropped": dropped,
            "logs_exported": logs_exported,
            "logs_dropped": logs_dropped,
            "metrics_exported": metrics_ok,
        }

    async def _push(self, path: str, payload: dict) -> bool:
        body = json.dumps(payload).encode("utf-8")
        attempt = 0
        while True:
            attempt += 1
            try:
                await self._send(path, body)
                return True
            except Exception as e:
                if attempt >= self._retry.attempts:
                    logger.warning(
                        "telemetry push to %s%s failed after %d attempt(s): %s",
                        self._endpoint, path, attempt, e,
                    )
                    return False
                await asyncio.sleep(self._retry.backoff_s(attempt))

    async def _send(self, path: str, body: bytes) -> None:
        if self._transport is not None:
            await self._transport(path, body)
            return
        import httpx

        if self._client is None:
            self._client = httpx.AsyncClient(timeout=self._timeout_s)
        response = await self._client.post(
            self._endpoint + path,
            content=body,
            headers={"Content-Type": "application/json"},
        )
        response.raise_for_status()

    # -------------------------------------------------------------- operator

    def snapshot(self) -> dict:
        """Exporter state for the debug bundle / verbose health."""
        return {
            "endpoint": self._endpoint,
            "queue_depth": len(self._queue),
            "logs_queue_depth": len(self._logs_queue),
            "queue_max": self._queue_max,
            "running": self._task is not None and not self._task.done(),
        }
