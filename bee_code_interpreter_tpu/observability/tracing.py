"""Request-scoped distributed tracing on contextvars (Dapper-style).

One ``Trace`` per request, rooted at the HTTP/gRPC edge next to
``new_request_id()``; child spans mark the stages a slow request could have
spent its time in (admission wait, pod-group spawn, workspace upload, the
execute itself, download). The context crosses the network as a W3C
``traceparent`` header plus ``X-Request-Id``, so the executor server
continues the same trace inside the pod and its log lines correlate with
the edge request that caused them.

Design constraints that shaped this module:

- **contextvars, not thread-locals**: the service is one asyncio loop with
  interleaved requests; a span started in one request's task must be
  invisible to every other in-flight request, including across ``await``
  boundaries and ``asyncio.gather`` fan-outs (children copy the context).
- **No-op off the request path**: ``span()`` with no active trace yields
  ``None`` and touches nothing, so library code (executors, drivers) can be
  instrumented unconditionally — direct/test callers pay two ContextVar
  reads per stage, nothing more.
- **Traces are retained, not shipped**: finished traces land in a bounded
  in-memory :class:`TraceStore` (with a reserved slice for the slowest
  requests, which are exactly the ones worth inspecting after the fact)
  and are served as JSON from ``GET /v1/traces``. No collector required.
- **Spans feed the metrics registry**: every finished child span is also
  observed into the ``bci_stage_seconds{stage=...}`` histogram, so the
  Prometheus view and the per-request trace view agree by construction.
"""

from __future__ import annotations

import heapq
import logging
import secrets
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field

TRACEPARENT_HEADER = "traceparent"
REQUEST_ID_HEADER = "X-Request-Id"

_current_trace: ContextVar["Trace | None"] = ContextVar("bci_trace", default=None)
_current_span: ContextVar["Span | None"] = ContextVar("bci_span", default=None)


def _new_trace_id() -> str:
    return uuid.uuid4().hex  # 32 lowercase hex chars, W3C trace-id shaped


def _new_span_id() -> str:
    return secrets.token_hex(8)  # 16 lowercase hex chars


def format_traceparent(trace_id: str, span_id: str) -> str:
    """W3C trace-context header: version 00, sampled flag set."""
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """(trace_id, parent_span_id) from a ``traceparent`` header, or None for
    anything malformed — a bad header from an arbitrary client must degrade
    to "start a fresh trace", never to an error."""
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if version == "ff" or len(version) != 2:
        return None
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return trace_id, span_id


@dataclass
class Span:
    trace_id: str
    span_id: str
    parent_id: str | None
    name: str
    start_unix: float
    start_mono: float
    duration_s: float | None = None
    status: str = "ok"
    attributes: dict[str, str] = field(default_factory=dict)

    @property
    def duration_ms(self) -> float | None:
        return None if self.duration_s is None else self.duration_s * 1000.0

    def to_dict(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_unix": self.start_unix,
            "duration_ms": self.duration_ms,
            "status": self.status,
            "attributes": dict(self.attributes),
        }


class Trace:
    """One request's spans. Created by :meth:`Tracer.trace`; child spans
    attach through the module-level :func:`span` via the ambient context."""

    def __init__(
        self,
        tracer: "Tracer | None",
        name: str,
        trace_id: str | None = None,
        parent_span_id: str | None = None,
        request_id: str | None = None,
    ) -> None:
        self._tracer = tracer
        self.trace_id = trace_id or _new_trace_id()
        self.request_id = request_id
        self.spans: list[Span] = []
        self.root = self.start_span(name, parent_id=parent_span_id)

    def start_span(
        self, name: str, parent_id: str | None, attributes: dict | None = None
    ) -> Span:
        s = Span(
            trace_id=self.trace_id,
            span_id=_new_span_id(),
            parent_id=parent_id,
            name=name,
            start_unix=time.time(),
            start_mono=time.monotonic(),
            attributes={k: str(v) for k, v in (attributes or {}).items()},
        )
        self.spans.append(s)
        return s

    def end_span(self, s: Span, status: str = "ok", error: str | None = None) -> None:
        if s.duration_s is not None:
            return  # already ended (error path raced the normal path)
        s.duration_s = time.monotonic() - s.start_mono
        s.status = status
        if error is not None:
            s.attributes["error"] = error
        if self._tracer is not None:
            self._tracer._on_span_end(self, s)

    @property
    def name(self) -> str:
        return self.root.name

    @property
    def status(self) -> str:
        return self.root.status

    @property
    def duration_s(self) -> float:
        if self.root.duration_s is not None:
            return self.root.duration_s
        return time.monotonic() - self.root.start_mono

    def stage_ms(self) -> dict[str, float]:
        """stage name → total milliseconds across the trace's FINISHED child
        spans. Repeated stages (per-worker uploads, retry attempts) sum —
        for concurrent fan-outs that is aggregate stage time, which can
        exceed the wall-clock the stage occupied."""
        out: dict[str, float] = {}
        for s in self.spans:
            if s is self.root or s.duration_s is None:
                continue
            out[s.name] = out.get(s.name, 0.0) + s.duration_s * 1000.0
        return out

    def summary(self) -> dict:
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "request_id": self.request_id,
            "start_unix": self.root.start_unix,
            "duration_ms": self.duration_s * 1000.0,
            "status": self.status,
            "n_spans": len(self.spans),
        }

    def to_dict(self) -> dict:
        return {
            **self.summary(),
            "stage_ms": self.stage_ms(),
            "spans": [s.to_dict() for s in self.spans],
        }


class TraceStore:
    """Bounded retention for finished traces: a FIFO ring of the most recent
    ones plus a reserved slice that always keeps the slowest-N seen — the
    requests an operator actually goes looking for are the outliers, and a
    plain ring would have evicted them minutes ago under load."""

    def __init__(self, max_traces: int = 256, slowest_keep: int = 32) -> None:
        slowest_keep = max(0, min(slowest_keep, max_traces - 1))
        self._recent: deque[Trace] = deque(maxlen=max(1, max_traces - slowest_keep))
        self._slowest_keep = slowest_keep
        # min-heap of (duration_s, seq, trace): the fastest of the kept-slow
        # set sits at the top and is the one displaced by a slower arrival
        self._slowest: list[tuple[float, int, Trace]] = []
        self._seq = 0
        self._lock = threading.Lock()

    def add(self, trace: Trace) -> None:
        with self._lock:
            self._recent.append(trace)
            if self._slowest_keep:
                self._seq += 1
                entry = (trace.duration_s, self._seq, trace)
                if len(self._slowest) < self._slowest_keep:
                    heapq.heappush(self._slowest, entry)
                elif entry[0] > self._slowest[0][0]:
                    heapq.heapreplace(self._slowest, entry)

    def get(self, trace_id: str) -> Trace | None:
        with self._lock:
            for t in self._recent:
                if t.trace_id == trace_id:
                    return t
            for _, _, t in self._slowest:
                if t.trace_id == trace_id:
                    return t
        return None

    def traces(self) -> list[Trace]:
        """All retained traces (recent ∪ slowest, deduplicated), newest
        first."""
        with self._lock:
            seen: dict[str, Trace] = {}
            for t in list(self._recent) + [t for _, _, t in self._slowest]:
                seen.setdefault(t.trace_id, t)
        return sorted(
            seen.values(), key=lambda t: t.root.start_unix, reverse=True
        )

    def __len__(self) -> int:
        return len(self.traces())


class Tracer:
    """Trace factory bound to a :class:`TraceStore` and (optionally) the
    metrics registry. One per process edge; the executors never see it —
    they attach through the ambient context via :func:`span`."""

    def __init__(self, store: TraceStore | None = None, metrics=None) -> None:
        # `store or TraceStore()` would discard a passed-in EMPTY store
        # (TraceStore defines __len__, and a fresh store is len 0 — falsy):
        # the composition root's configured ring sizes silently never
        # applied, and a second consumer sharing ctx.trace_store (the
        # serving monitor) saw a different store than the edge served.
        self.store = store if store is not None else TraceStore()
        # Finished-trace sinks (the telemetry exporter's enqueue, say): each
        # gets the whole Trace right after it lands in the store. Sinks MUST
        # be cheap and non-blocking — they run on the request path.
        self._sinks: list = []
        # Trace ids currently in flight. Read by the continuous profiler's
        # sampler THREAD (GIL-atomic set ops; a momentarily stale view is
        # fine — it tags profile windows, it doesn't gate anything).
        self._active: set[str] = set()
        self._stage_seconds = (
            metrics.histogram(
                "bci_stage_seconds",
                "Per-request stage latency, from trace spans",
            )
            if metrics is not None
            else None
        )

    def add_sink(self, sink) -> None:
        """Register a callable invoked with each finished :class:`Trace`."""
        self._sinks.append(sink)

    def active_trace_ids(self) -> tuple[str, ...]:
        """Ids of traces currently in flight (the continuous profiler tags
        its windows with these)."""
        return tuple(self._active)

    def _on_span_end(self, trace: Trace, s: Span) -> None:
        if self._stage_seconds is not None and s is not trace.root:
            self._stage_seconds.observe(s.duration_s, stage=s.name)

    @contextmanager
    def trace(
        self,
        name: str,
        trace_id: str | None = None,
        parent_span_id: str | None = None,
        request_id: str | None = None,
    ):
        """Root a new trace (or continue an inbound one when
        ``trace_id``/``parent_span_id`` came off a ``traceparent`` header),
        make it the ambient trace for the duration, and land it in the
        store on exit — error or not; failed requests are the ones most
        worth inspecting."""
        t = Trace(
            self,
            name,
            trace_id=trace_id,
            parent_span_id=parent_span_id,
            request_id=request_id,
        )
        trace_token = _current_trace.set(t)
        span_token = _current_span.set(t.root)
        self._active.add(t.trace_id)
        try:
            yield t
        except BaseException as e:
            t.end_span(t.root, status="error", error=repr(e))
            raise
        else:
            t.end_span(t.root)
        finally:
            self._active.discard(t.trace_id)
            _current_span.reset(span_token)
            _current_trace.reset(trace_token)
            self.store.add(t)
            for sink in self._sinks:
                # A broken sink must never fail the request it observed.
                try:
                    sink(t)
                except Exception:
                    logging.getLogger(__name__).exception(
                        "trace sink %r failed", sink
                    )


@contextmanager
def span(name: str, **attributes):
    """Child span under the ambient trace; a no-op (yields ``None``) when no
    trace is active, so instrumented library code costs nothing off the
    request path."""
    trace = _current_trace.get()
    if trace is None:
        yield None
        return
    parent = _current_span.get()
    s = trace.start_span(
        name, parent.span_id if parent is not None else None, attributes
    )
    token = _current_span.set(s)
    try:
        yield s
    except BaseException as e:
        trace.end_span(s, status="error", error=repr(e))
        raise
    else:
        trace.end_span(s)
    finally:
        _current_span.reset(token)


@contextmanager
def activate_trace(trace: Trace, span: Span | None = None):
    """Make an externally-managed trace the ambient one for the duration.

    The request path gets its ambient trace from :meth:`Tracer.trace`; code
    that manages traces by hand — the serving monitor's per-request
    lifecycle traces live across many batcher steps, far outside any one
    call stack — uses this to scope a metric observation (histogram
    exemplars read the ambient ids) or a log line to a specific trace
    without adopting the context-manager lifecycle."""
    trace_token = _current_trace.set(trace)
    span_token = _current_span.set(span or trace.root)
    try:
        yield trace
    finally:
        _current_span.reset(span_token)
        _current_trace.reset(trace_token)


def current_trace() -> Trace | None:
    return _current_trace.get()


def current_span() -> Span | None:
    return _current_span.get()


def current_ids() -> tuple[str, str]:
    """(trace_id, span_id) of the ambient span, or ("-", "-") — the logging
    filter's read, shaped to never raise."""
    s = _current_span.get()
    if s is None:
        return "-", "-"
    return s.trace_id, s.span_id


def outbound_headers() -> dict[str, str]:
    """Headers propagating the ambient context to a sandbox: ``traceparent``
    (when a trace is active) and ``X-Request-Id`` (whenever one is set —
    request-id correlation must survive even with tracing off)."""
    headers: dict[str, str] = {}
    trace = _current_trace.get()
    request_id = None
    if trace is not None:
        s = _current_span.get() or trace.root
        headers[TRACEPARENT_HEADER] = format_traceparent(trace.trace_id, s.span_id)
        request_id = trace.request_id
    if request_id is None:
        # lazy import: utils.request_id imports this module for the logging
        # filter, so the reverse edge must not exist at import time
        from bee_code_interpreter_tpu.utils.request_id import (
            request_id_context_var,
        )

        rid = request_id_context_var.get()
        request_id = rid if rid != "-" else None
    if request_id:
        headers[REQUEST_ID_HEADER] = request_id
    return headers
