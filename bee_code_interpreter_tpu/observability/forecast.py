"""Demand forecasting over the capacity tracker (docs/autoscaling.md).

A reactive pool refill always pays one cold-spawn latency per traffic step;
acting *proactively* needs a short-horizon forecast of the arrival rate.
The model here is deliberately small and fully inspectable (the
Borg/Autopilot-style moving-window estimators, not an ML service):

- **EWMA level + trend** (Holt's linear smoothing) over the tracker's
  completed per-second arrival series — the smoothed rate and its slope,
  projected one horizon ahead;
- **recent-peak envelope** — the largest single second observed recently
  (current partial second included), so a burst raises the forecast the
  moment it starts instead of one smoothing constant later;
- **horizon = observed p95 sandbox spawn latency** (from the fleet
  journal's spawn samples, clamped to a sane band): the forecast looks
  exactly as far ahead as the pool needs to START a spawn for it to be warm
  in time.

``forecast()`` recomputes from the ring on every call — deterministic under
a ManualClock, nothing to keep consistent, and the ring is at most
``APP_DEMAND_WINDOW_S`` entries. Served as the ``forecast`` section of
``GET /v1/autoscale`` and the ``bci_forecast_rps`` gauge.
"""

from __future__ import annotations

import math

from bee_code_interpreter_tpu.observability.capacity import DemandTracker


def _finite(value: float, fallback: float) -> float:
    try:
        value = float(value)
    except (TypeError, ValueError):
        return fallback
    return value if math.isfinite(value) else fallback


class Forecaster:
    def __init__(
        self,
        demand: DemandTracker,
        *,
        alpha: float = 0.4,
        beta: float = 0.2,
        peak_window_s: float = 60.0,
        min_horizon_s: float = 1.0,
        max_horizon_s: float = 60.0,
        metrics=None,
    ) -> None:
        self._demand = demand
        self._alpha = min(1.0, max(0.0, _finite(alpha, 0.4)))
        self._beta = min(1.0, max(0.0, _finite(beta, 0.2)))
        self._peak_window_s = max(0.0, _finite(peak_window_s, 60.0))
        # An inverted or non-finite band would make horizon_s() — and with
        # it every proactive spawn decision — NaN or permanently pinned;
        # normalize once here so horizon_s() is a pure clamp.
        min_h = max(0.0, _finite(min_horizon_s, 1.0))
        max_h = _finite(max_horizon_s, 60.0)
        self._min_horizon_s = min_h
        self._max_horizon_s = max(min_h, max_h)
        if metrics is not None:
            metrics.gauge(
                "bci_forecast_rps",
                "Forecast arrival rate one spawn-horizon ahead "
                "(EWMA level+trend with a recent-peak envelope)",
                lambda: self.forecast()["forecast_rps"],
            )

    def horizon_s(self) -> float:
        """How far ahead the forecast looks: the observed p95 spawn latency
        (what a pre-spawn must beat), clamped to [min, max] — before the
        first spawn is observed, the floor."""
        p95 = self._demand.spawn_latency_quantile(0.95)
        if p95 is None:
            return self._min_horizon_s
        return min(self._max_horizon_s, max(self._min_horizon_s, p95))

    def forecast(self) -> dict:
        """The full forecast document (the ``forecast`` section of
        ``GET /v1/autoscale``). ``forecast_rps`` is the number the
        autoscaler sizes against: the Holt projection at the horizon,
        floored by the recent-peak envelope, never negative."""
        series = self._demand.completed_series()
        level = 0.0
        trend = 0.0
        if series:
            level = float(series[0])
            for y in series[1:]:
                prev = level
                level = self._alpha * y + (1.0 - self._alpha) * (level + trend)
                trend = self._beta * (level - prev) + (1.0 - self._beta) * trend
        horizon = self.horizon_s()
        projected = max(0.0, level + trend * horizon)
        peak = self._demand.peak_rps(self._peak_window_s)
        return {
            "level_rps": level,
            "trend_rps_per_s": trend,
            "projected_rps": projected,
            "peak_rps": peak,
            "forecast_rps": max(projected, peak),
            "horizon_s": horizon,
            "samples": len(series),
        }


def recommend_replicas(
    *,
    forecast_rps: float,
    horizon_s: float,
    concurrency_high_water: float = 0.0,
    per_replica_capacity: int = 8,
    current_replicas: int = 1,
    min_replicas: int = 1,
    max_replicas: int = 64,
    slo_fast_burn: bool = False,
) -> dict:
    """Turn the forecast into a concrete replica count — the
    ``recommendation`` section of ``GET /v1/autoscale`` on both edges
    (docs/capacity.md).

    Same sizing rule as :class:`~..resilience.autoscaler.PoolAutoscaler`
    applies to sandboxes, lifted one level: the fleet must cover
    ``max(forecast_rps * horizon_s, concurrency_high_water)`` in-flight
    requests, and each replica covers ``per_replica_capacity`` of them
    (its admission ``max_in_flight`` / pool ceiling). An active fast-burn
    page overrides arithmetic — capacity math that says "shrink" while
    users are failing is wrong by definition, so burn holds or grows the
    fleet by one. Every input is NaN/inf-guarded: this document feeds an
    actuator."""
    forecast_rps = max(0.0, _finite(forecast_rps, 0.0))
    horizon_s = max(0.0, _finite(horizon_s, 0.0))
    concurrency_high_water = max(0.0, _finite(concurrency_high_water, 0.0))
    per_replica_capacity = max(1, int(_finite(per_replica_capacity, 1)))
    min_replicas = max(0, int(_finite(min_replicas, 1)))
    max_replicas = max(min_replicas, int(_finite(max_replicas, 64)))
    current_replicas = max(0, int(_finite(current_replicas, 0)))

    needed = max(forecast_rps * horizon_s, concurrency_high_water)
    target = math.ceil(needed / per_replica_capacity) if needed > 0 else 0
    reason = "forecast"
    if target <= 0:
        target = min_replicas
        reason = "idle"
    if slo_fast_burn and target <= current_replicas:
        # Never recommend scale-in (or even steady-state) while the page
        # is firing: whatever the demand math says, the fleet is failing
        # users at its CURRENT size.
        target = current_replicas + 1
        reason = "slo_burn"
    clamped = min(max_replicas, max(min_replicas, target))
    if clamped != target and reason != "slo_burn":
        reason = "clamped"
    return {
        "target_replicas": clamped,
        "reason": reason,
        "needed_slots": needed,
        "per_replica_capacity": per_replica_capacity,
        "current_replicas": current_replicas,
        "min_replicas": min_replicas,
        "max_replicas": max_replicas,
        "slo_fast_burn": bool(slo_fast_burn),
    }
