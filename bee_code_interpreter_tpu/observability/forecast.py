"""Demand forecasting over the capacity tracker (docs/autoscaling.md).

A reactive pool refill always pays one cold-spawn latency per traffic step;
acting *proactively* needs a short-horizon forecast of the arrival rate.
The model here is deliberately small and fully inspectable (the
Borg/Autopilot-style moving-window estimators, not an ML service):

- **EWMA level + trend** (Holt's linear smoothing) over the tracker's
  completed per-second arrival series — the smoothed rate and its slope,
  projected one horizon ahead;
- **recent-peak envelope** — the largest single second observed recently
  (current partial second included), so a burst raises the forecast the
  moment it starts instead of one smoothing constant later;
- **horizon = observed p95 sandbox spawn latency** (from the fleet
  journal's spawn samples, clamped to a sane band): the forecast looks
  exactly as far ahead as the pool needs to START a spawn for it to be warm
  in time.

``forecast()`` recomputes from the ring on every call — deterministic under
a ManualClock, nothing to keep consistent, and the ring is at most
``APP_DEMAND_WINDOW_S`` entries. Served as the ``forecast`` section of
``GET /v1/autoscale`` and the ``bci_forecast_rps`` gauge.
"""

from __future__ import annotations

from bee_code_interpreter_tpu.observability.capacity import DemandTracker


class Forecaster:
    def __init__(
        self,
        demand: DemandTracker,
        *,
        alpha: float = 0.4,
        beta: float = 0.2,
        peak_window_s: float = 60.0,
        min_horizon_s: float = 1.0,
        max_horizon_s: float = 60.0,
        metrics=None,
    ) -> None:
        self._demand = demand
        self._alpha = min(1.0, max(0.0, alpha))
        self._beta = min(1.0, max(0.0, beta))
        self._peak_window_s = peak_window_s
        self._min_horizon_s = min_horizon_s
        self._max_horizon_s = max_horizon_s
        if metrics is not None:
            metrics.gauge(
                "bci_forecast_rps",
                "Forecast arrival rate one spawn-horizon ahead "
                "(EWMA level+trend with a recent-peak envelope)",
                lambda: self.forecast()["forecast_rps"],
            )

    def horizon_s(self) -> float:
        """How far ahead the forecast looks: the observed p95 spawn latency
        (what a pre-spawn must beat), clamped to [min, max] — before the
        first spawn is observed, the floor."""
        p95 = self._demand.spawn_latency_quantile(0.95)
        if p95 is None:
            return self._min_horizon_s
        return min(self._max_horizon_s, max(self._min_horizon_s, p95))

    def forecast(self) -> dict:
        """The full forecast document (the ``forecast`` section of
        ``GET /v1/autoscale``). ``forecast_rps`` is the number the
        autoscaler sizes against: the Holt projection at the horizon,
        floored by the recent-peak envelope, never negative."""
        series = self._demand.completed_series()
        level = 0.0
        trend = 0.0
        if series:
            level = float(series[0])
            for y in series[1:]:
                prev = level
                level = self._alpha * y + (1.0 - self._alpha) * (level + trend)
                trend = self._beta * (level - prev) + (1.0 - self._beta) * trend
        horizon = self.horizon_s()
        projected = max(0.0, level + trend * horizon)
        peak = self._demand.peak_rps(self._peak_window_s)
        return {
            "level_rps": level,
            "trend_rps_per_s": trend,
            "projected_rps": projected,
            "peak_rps": peak,
            "forecast_rps": max(projected, peak),
            "horizon_s": horizon,
            "samples": len(series),
        }
