"""One-call incident snapshot: everything an operator would otherwise curl.

``GET /v1/debug/bundle`` (and the gRPC ``ObservabilityService/GetDebugBundle``
spelling) returns a single JSON document — recent + slowest traces, the fleet
lifecycle journal, SLO state, breaker/pool/supervisor/drain health, telemetry
exporter state, the redacted config, and a full metrics dump — so an incident
gets ONE attached artifact instead of five separately-timed curls that never
quite line up.

Both edges build it through the composition root's
``ApplicationContext.build_debug_bundle`` so they can never disagree about
which components are included; a standalone ``create_http_server`` (tests)
falls back to building from whatever it was handed.
"""

from __future__ import annotations

import time

from bee_code_interpreter_tpu.observability.fleet import unwrap_executor


def executor_health(executor) -> dict:
    """Deep-health view of the executor backend: pool occupancy and breaker
    states (the ``GET /healthz?verbose=1`` shape). Empty for backends with
    no pool (the in-process local executor)."""
    inner = unwrap_executor(executor)
    info: dict = {}
    ready = getattr(inner, "pool_ready_count", None)
    if ready is not None:
        info["pool"] = {
            "ready": ready,
            "spawning": getattr(inner, "pool_spawning_count", 0),
            # The live refill target (the autoscaler's override in act
            # mode, the static config otherwise — docs/autoscaling.md).
            "target": getattr(inner, "pool_target", None),
        }
    breakers = {}
    for attr in ("spawn_breaker", "http_breaker"):
        breaker = getattr(inner, attr, None)
        if breaker is not None:
            breakers[breaker.name] = breaker.state.name.lower()
    if breakers:
        info["breakers"] = breakers
    return info


def build_debug_bundle(
    *,
    tracer=None,
    fleet=None,
    slo=None,
    metrics=None,
    config=None,
    executor=None,
    supervisor=None,
    drain=None,
    exporter=None,
    recorder=None,
    loopmon=None,
    contprof=None,
    serving=None,
    device=None,  # observability.DeviceMonitor (accelerator section)
    autoscale=None,  # callable -> dict (resilience.autoscale_snapshot)
    tenancy=None,  # tenancy.TenantRegistry (per-tenant view in the bundle)
    recent_traces: int = 50,
    slowest_traces: int = 10,
    fleet_events: int = 100,
    recent_events: int = 50,
    serving_steps: int = 16,
) -> dict:
    """Assemble the bundle from whatever components exist; every section is
    present (null/empty when its component isn't wired) so consumers parse
    one stable schema."""
    bundle: dict = {"generated_unix": time.time()}

    traces = tracer.store.traces() if tracer is not None else []
    slowest = sorted(traces, key=lambda t: t.duration_s, reverse=True)
    bundle["traces"] = {
        "retained": len(traces),
        # summaries for breadth (newest first), full spans for the outliers
        # an incident is usually about
        "recent": [t.summary() for t in traces[:recent_traces]],
        "slowest": [t.to_dict() for t in slowest[:slowest_traces]],
    }

    bundle["fleet"] = (
        {
            "snapshot": fleet.snapshot(),
            "events": fleet.events(limit=fleet_events),
        }
        if fleet is not None
        else None
    )

    from bee_code_interpreter_tpu.observability.slo import empty_slo_snapshot

    bundle["slo"] = slo.snapshot() if slo is not None else empty_slo_snapshot()

    service: dict = {
        "draining": bool(drain is not None and drain.draining),
    }
    if drain is not None:
        service["drain_inflight"] = drain.in_flight
    if executor is not None:
        service.update(executor_health(executor))
    if supervisor is not None:
        service["supervisor"] = supervisor.snapshot()
    bundle["service"] = service

    bundle["telemetry"] = exporter.snapshot() if exporter is not None else None

    # The flight-recorder / loop-health / profiler view (ISSUE 8): the last
    # N wide events, the live task dump with the monitor's lag state, and
    # the latest profile window — one call still captures a whole incident.
    bundle["events"] = (
        {
            **recorder.snapshot(),
            "recent": recorder.events(limit=recent_events),
        }
        if recorder is not None
        else None
    )
    from bee_code_interpreter_tpu.observability.loopmon import task_inventory

    bundle["loop"] = {
        "monitor": loopmon.snapshot() if loopmon is not None else None,
        "tasks": task_inventory(),
    }
    bundle["profile"] = contprof.snapshot() if contprof is not None else None

    # Serving-engine deep observability (docs/observability.md "Serving
    # observability"): batcher/queue aggregates, KV-cache telemetry, and
    # the last few step records next to everything else an incident needs.
    bundle["serving"] = (
        serving.snapshot(steps=serving_steps) if serving is not None else None
    )

    # Accelerator observability (docs/observability.md "Accelerator
    # observability"): compile/retrace totals + per-function signature
    # sets, the latest device-memory sample (estimated on CPU), KV-pool
    # occupancy, and per-mesh-shape step timing.
    bundle["accelerator"] = device.snapshot() if device is not None else None

    # Capacity observability (docs/autoscaling.md): demand, forecast, and
    # the autoscaler's target + decision log — the "was the pool sized for
    # this" context every capacity incident needs.
    bundle["autoscale"] = autoscale() if autoscale is not None else None

    # Multi-tenant view (docs/tenancy.md): who has been spending what —
    # the declared table, usage rollups, and per-tenant SLO burn, so a
    # noisy-neighbor incident reads from the same one call.
    if tenancy is not None:
        from bee_code_interpreter_tpu.tenancy import build_tenants_snapshot

        bundle["tenants"] = build_tenants_snapshot(tenancy, slo=slo)
    else:
        bundle["tenants"] = None

    # The extracted API surface model + the contract lint's live verdict
    # (docs/analysis.md "Contract lint"): the route table an operator or
    # the FleetRouter reads instead of hardcoding it. Non-blocking: the
    # scan runs once per process on the warm thread both servers kick at
    # build time; a pull that races it answers {"status": "warming"}
    # instead of stalling the event loop, and None means the source tree
    # isn't readable where this process runs (a stripped image).
    try:
        from bee_code_interpreter_tpu.analysis.contractlint import (
            surface_section_nowait,
        )

        bundle["surface"] = surface_section_nowait()
    except Exception:
        bundle["surface"] = None

    bundle["config"] = config.redacted_dump() if config is not None else None
    bundle["metrics"] = metrics.expose() if metrics is not None else None
    return bundle
