"""Always-on continuous profiler: low-overhead wall-clock stack sampling.

``POST /v1/profile`` (observability/profiling.py) answers "profile THIS
request, now, on purpose" — useless for the incident that already
happened. The :class:`ContinuousProfiler` answers "what has this process
been doing for the last minute": a daemon thread samples every thread's
current stack via ``sys._current_frames`` at a deliberately off-beat
~19 Hz (a prime-ish rate so the sampler can't phase-lock with periodic
work and systematically miss it), aggregates the samples into
collapsed-stack form (``frame;frame;frame count`` — the folded format
flamegraph tooling eats directly), and keeps a short history of completed
windows. Each window also remembers the trace ids that were in flight
while its samples were taken, so a hot window links back to the requests
that were running through it.

Overhead is bounded by construction: sampling cost is per-*thread*, not
per-request (the request path is never touched); stack depth, distinct
stacks per window, and remembered trace ids are all capped. The profiler
holds no references to frames beyond the sampling instant.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from pathlib import Path

DEFAULT_HZ = 19.0
_REPO_ROOT = str(Path(__file__).resolve().parent.parent.parent)
_TRUNCATED = "<truncated>"


def _frame_label(frame) -> str:
    filename = frame.f_code.co_filename
    if filename.startswith(_REPO_ROOT):
        filename = filename[len(_REPO_ROOT):].lstrip("/")
    else:
        # Off-repo frames (stdlib, site-packages) collapse to their module
        # file name: full interpreter paths would explode stack cardinality.
        filename = filename.rsplit("/", 1)[-1]
    return f"{filename}:{frame.f_code.co_name}"


def collapse_stack(frame, max_depth: int = 48) -> str:
    """One thread's current stack as a collapsed-stack line key:
    root-first, ``;``-joined, depth-capped (innermost frames win — the
    leaf is where the time is actually being spent)."""
    labels: list[str] = []
    f = frame
    while f is not None and len(labels) < max_depth:
        labels.append(_frame_label(f))
        f = f.f_back
    return ";".join(reversed(labels))


class ProfileWindow:
    """One aggregation window: collapsed stacks → sample counts, plus the
    trace ids seen in flight during sampling (capped)."""

    def __init__(
        self, start_unix: float, max_stacks: int, max_trace_ids: int
    ) -> None:
        self.start_unix = start_unix
        self.end_unix: float | None = None
        self.samples = 0
        self.stacks: dict[str, int] = {}
        self.trace_ids: set[str] = set()
        self._max_stacks = max_stacks
        self._max_trace_ids = max_trace_ids

    def add(self, stack: str) -> None:
        if stack in self.stacks or len(self.stacks) < self._max_stacks:
            self.stacks[stack] = self.stacks.get(stack, 0) + 1
        else:
            self.stacks[_TRUNCATED] = self.stacks.get(_TRUNCATED, 0) + 1

    def note_traces(self, trace_ids) -> None:
        for trace_id in trace_ids:
            if len(self.trace_ids) >= self._max_trace_ids:
                break
            self.trace_ids.add(trace_id)

    def collapsed(self, top: int | None = None) -> str:
        """The folded flamegraph exposition: one ``stack count`` line per
        distinct stack, hottest first."""
        ranked = sorted(self.stacks.items(), key=lambda kv: -kv[1])
        if top is not None:
            ranked = ranked[:top]
        return "\n".join(f"{stack} {count}" for stack, count in ranked)

    def to_dict(self, top: int = 50) -> dict:
        ranked = sorted(self.stacks.items(), key=lambda kv: -kv[1])
        return {
            "start_unix": self.start_unix,
            "end_unix": self.end_unix,
            "samples": self.samples,
            "distinct_stacks": len(self.stacks),
            "trace_ids": sorted(self.trace_ids),
            "hot_stacks": [
                {"stack": stack, "count": count}
                for stack, count in ranked[:top]
            ],
        }


class ContinuousProfiler:
    """Background sampling profiler over ``sys._current_frames``.

    ``active_trace_ids`` is a zero-arg callable returning the trace ids
    currently in flight (the ``Tracer`` provides one); it is read from the
    sampler thread, so it must be cheap and thread-safe — a GIL-atomic
    snapshot of a set qualifies.
    """

    def __init__(
        self,
        *,
        hz: float = DEFAULT_HZ,
        window_s: float = 60.0,
        max_windows: int = 5,
        max_stack_depth: int = 48,
        max_stacks_per_window: int = 512,
        max_trace_ids_per_window: int = 64,
        active_trace_ids=None,
        metrics=None,
        clock=time.time,
    ) -> None:
        self.hz = max(0.1, hz)
        self.window_s = max(1.0, window_s)
        self._max_stack_depth = max_stack_depth
        self._max_stacks = max_stacks_per_window
        self._max_trace_ids = max_trace_ids_per_window
        self._active_trace_ids = active_trace_ids
        self._clock = clock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()
        self._current = ProfileWindow(
            self._clock(), self._max_stacks, self._max_trace_ids
        )
        self._completed: deque[ProfileWindow] = deque(
            maxlen=max(1, max_windows)
        )
        self._samples_total = (
            metrics.counter(
                "bci_contprof_samples_total",
                "Stack samples taken by the continuous profiler",
            )
            if metrics is not None
            else None
        )

    # ----------------------------------------------------------- sampling

    def sample_once(self) -> None:
        """Take one sample of every thread's stack (public so tests can
        drive sampling deterministically without the thread)."""
        own = threading.get_ident()
        frames = sys._current_frames()
        now = self._clock()
        with self._lock:
            window = self._roll(now)
            window.samples += 1
            for thread_id, frame in frames.items():
                if thread_id == own:
                    continue  # the profiler must not profile itself
                window.add(collapse_stack(frame, self._max_stack_depth))
            if self._active_trace_ids is not None:
                try:
                    window.note_traces(tuple(self._active_trace_ids()))
                except Exception:
                    pass  # the trace hook must never kill the sampler
        # sys._current_frames returns live frames; drop the references
        # before sleeping so the sampler never extends their lifetime.
        del frames
        if self._samples_total is not None:
            self._samples_total.inc()

    def _roll(self, now: float) -> ProfileWindow:
        if now - self._current.start_unix >= self.window_s:
            self._current.end_unix = now
            if self._current.samples:
                self._completed.append(self._current)
            self._current = ProfileWindow(
                now, self._max_stacks, self._max_trace_ids
            )
        return self._current

    def _run(self) -> None:
        interval = 1.0 / self.hz
        while not self._stop.wait(interval):
            try:
                self.sample_once()
            except Exception:
                # A pathological frame walk must not end profiling forever;
                # skip the sample and keep the cadence.
                continue

    # ---------------------------------------------------------- lifecycle

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="bci-contprof", daemon=True
        )
        self._thread.start()

    def stop(self, timeout_s: float = 2.0) -> None:
        self._stop.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=timeout_s)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ----------------------------------------------------------- operator

    def windows(self) -> list[ProfileWindow]:
        """Completed windows plus the in-progress one, oldest first."""
        with self._lock:
            return list(self._completed) + [self._current]

    def _latest_locked(self) -> ProfileWindow:
        if self._current.samples or not self._completed:
            return self._current
        return self._completed[-1]

    def latest_window(self) -> ProfileWindow:
        """The freshest window with samples (the in-progress one, or the
        last completed one right after a roll)."""
        with self._lock:
            return self._latest_locked()

    def collapsed(self) -> str:
        """The latest window in folded flamegraph form (the
        ``GET /v1/debug/pprof`` default body). Rendered under the lock:
        the latest window is usually the LIVE one the sampler thread is
        mutating, and iterating its stacks unlocked is a crash waiting for
        an incident (dict changed size mid-sort)."""
        with self._lock:
            return self._latest_locked().collapsed()

    def snapshot(self, top: int = 50) -> dict:
        with self._lock:
            window_dict = self._latest_locked().to_dict(top)
            completed = list(self._completed)
        return {
            "running": self.running,
            "hz": self.hz,
            "window_s": self.window_s,
            "window": window_dict,
            "completed_windows": [
                {
                    "start_unix": w.start_unix,
                    "end_unix": w.end_unix,
                    "samples": w.samples,
                    "distinct_stacks": len(w.stacks),
                    "trace_ids": len(w.trace_ids),
                }
                for w in completed
            ],
        }
