"""Composable traffic shapes → deterministic arrival schedules.

A shape is a rate curve ``rate_at(t) -> rps`` over a finite duration; the
arrival schedule is its integral: the k-th request fires when the
cumulative expected-arrival count crosses k. That makes schedules exactly
reproducible (same shape, same jitter seed → byte-identical schedule),
which is what lets a capacity probe be re-run and compared — the classic
open-loop construction from the load-testing literature, where arrivals
model USERS (who do not politely wait for the previous user's response)
rather than a single serialized client.
"""

from __future__ import annotations

import math
import random
from dataclasses import dataclass


@dataclass(frozen=True)
class Steady:
    """Constant ``rps`` for ``duration_s`` — the capacity-probe unit."""

    rps: float
    duration_s: float

    def rate_at(self, t: float) -> float:
        return self.rps if 0.0 <= t < self.duration_s else 0.0


@dataclass(frozen=True)
class Ramp:
    """Linear ``start_rps`` → ``end_rps`` sweep: where the p99-vs-load
    curve's knee shows up as a bend, not a cliff."""

    start_rps: float
    end_rps: float
    duration_s: float

    def rate_at(self, t: float) -> float:
        if not 0.0 <= t < self.duration_s:
            return 0.0
        frac = t / self.duration_s if self.duration_s > 0 else 0.0
        return self.start_rps + (self.end_rps - self.start_rps) * frac


@dataclass(frozen=True)
class Diurnal:
    """One (or more) raised-cosine day cycles compressed into
    ``duration_s`` — trough at t=0, crest mid-period. The forecaster's
    EWMA trend term exists for exactly this curve."""

    base_rps: float
    peak_rps: float
    duration_s: float
    period_s: float | None = None

    def rate_at(self, t: float) -> float:
        if not 0.0 <= t < self.duration_s:
            return 0.0
        period = self.period_s or self.duration_s
        if period <= 0:
            return self.base_rps
        swing = (1.0 - math.cos(2.0 * math.pi * t / period)) / 2.0
        return self.base_rps + (self.peak_rps - self.base_rps) * swing


@dataclass(frozen=True)
class FlashCrowd:
    """``base_rps`` with a ``multiplier``× step during
    [``crowd_start_s``, ``crowd_start_s + crowd_s``) — the league-client
    stampede the warm pool exists to absorb. Default 10×."""

    base_rps: float
    duration_s: float
    crowd_start_s: float
    crowd_s: float
    multiplier: float = 10.0

    def rate_at(self, t: float) -> float:
        if not 0.0 <= t < self.duration_s:
            return 0.0
        if self.crowd_start_s <= t < self.crowd_start_s + self.crowd_s:
            return self.base_rps * self.multiplier
        return self.base_rps


@dataclass(frozen=True)
class Phases:
    """Shapes in sequence (steady warm-up, then a ramp, then a crowd…);
    each phase's clock starts at zero when the previous one ends."""

    phases: tuple

    @property
    def duration_s(self) -> float:
        return sum(p.duration_s for p in self.phases)

    def rate_at(self, t: float) -> float:
        if t < 0.0:
            return 0.0
        for phase in self.phases:
            if t < phase.duration_s:
                return phase.rate_at(t)
            t -= phase.duration_s
        return 0.0


def arrival_times(
    shape,
    *,
    jitter_s: float = 0.0,
    seed: int = 0,
    dt: float = 0.001,
) -> list[float]:
    """Integrate the shape's rate curve into a sorted arrival schedule
    (seconds from load start). Deterministic: fixed-step trapezoid-free
    integration (the step is small against any sane rate), plus optional
    uniform ``±jitter_s`` from a SEEDED rng so two runs with the same seed
    stress identical instants."""
    duration = float(shape.duration_s)
    if duration <= 0.0 or dt <= 0.0:
        return []
    times: list[float] = []
    accumulated = 0.0
    target = 1.0
    steps = int(math.ceil(duration / dt))
    # The epsilon absorbs the drift of summing ~duration/dt tiny floats:
    # without it, an exact-integral shape (5 rps × 4 s = 20) drops its
    # final arrival at 19.999999…
    eps = 1e-6
    for step in range(steps):
        t = step * dt
        # Midpoint rule: exact for the piecewise-linear shapes (a left sum
        # under-integrates every ramp by (end−start)·dt/2 and loses the
        # final arrival).
        accumulated += max(0.0, shape.rate_at(t + 0.5 * dt)) * dt
        while accumulated >= target - eps:
            times.append(min(t, duration))
            target += 1.0
    if jitter_s > 0.0:
        rng = random.Random(seed)
        times = [
            min(duration, max(0.0, t + rng.uniform(-jitter_s, jitter_s)))
            for t in times
        ]
        times.sort()
    return times
