"""The open-loop generator: fire at the scheduled instant, never gate on
responses.

The one invariant that distinguishes this from every closed-loop test in
the repo: the send loop's only await is *sleeping until the next scheduled
arrival*. Each request runs as its own task; a slow or collapsing service
changes what comes BACK, never what goes OUT — so queue growth, shed
storms, and latency knees show at the offered rate that caused them. The
schedule lag (intended send instant vs actual) is itself a first-class
sample: a generator that cannot keep its own schedule invalidates the
probe, and says so instead of silently under-offering.
"""

from __future__ import annotations

import asyncio
import math
from dataclasses import dataclass, field

from bee_code_interpreter_tpu.loadgen.mix import PlannedRequest, TrafficMix
from bee_code_interpreter_tpu.loadgen.shapes import arrival_times

TENANT_HEADER = "X-Tenant-Id"


def quantile(values: list[float], q: float) -> float:
    """Nearest-rank quantile, 0.0 on empty — the same convention the
    DemandTracker uses for spawn latencies."""
    if not values:
        return 0.0
    if not math.isfinite(q):
        q = 1.0
    q = min(1.0, max(0.0, q))
    ordered = sorted(values)
    idx = min(len(ordered) - 1, int(q * len(ordered)))
    return ordered[idx]


@dataclass
class Sample:
    """One fired request's outcome."""

    kind: str
    cost_class: str
    tenant: str | None
    scheduled_s: float
    lag_s: float
    latency_s: float
    status: int | None  # None: transport error or undrained at cutoff
    error: str | None = None


@dataclass
class LoadResult:
    """One shape's worth of open-loop samples, with the aggregates the
    capacity reporter judges."""

    label: str
    offered: int
    duration_s: float
    samples: list[Sample] = field(default_factory=list)

    @property
    def sent(self) -> int:
        return len(self.samples)

    @property
    def completed(self) -> int:
        return sum(
            1
            for s in self.samples
            if s.status is not None and 200 <= s.status < 300
        )

    @property
    def sheds(self) -> int:
        return sum(1 for s in self.samples if s.status == 429)

    @property
    def errors(self) -> int:
        """5xx plus transport failures plus undrained requests — anything
        a USER would experience as the service failing."""
        return sum(
            1
            for s in self.samples
            if s.status is None or s.status >= 500
        )

    @property
    def offered_rps(self) -> float:
        return self.offered / self.duration_s if self.duration_s > 0 else 0.0

    @property
    def achieved_rps(self) -> float:
        return (
            self.completed / self.duration_s if self.duration_s > 0 else 0.0
        )

    def latency_quantile_ms(self, q: float) -> float:
        oks = [
            s.latency_s
            for s in self.samples
            if s.status is not None and 200 <= s.status < 300
        ]
        return quantile(oks, q) * 1000.0

    def lag_quantile_s(self, q: float) -> float:
        return quantile([max(0.0, s.lag_s) for s in self.samples], q)

    def shed_ledger(self) -> dict[str, int]:
        """Client-observed 429s by tenant label (``-`` for keyless) — the
        half of the shed accounting the SERVICE cannot fake; chaos-18
        reconciles it against the demand tracker's ledger."""
        out: dict[str, int] = {}
        for s in self.samples:
            if s.status == 429:
                label = s.tenant or "-"
                out[label] = out.get(label, 0) + 1
        return {k: out[k] for k in sorted(out)}

    def to_dict(self) -> dict:
        statuses: dict[str, int] = {}
        for s in self.samples:
            key = str(s.status) if s.status is not None else "transport_error"
            statuses[key] = statuses.get(key, 0) + 1
        return {
            "label": self.label,
            "offered": self.offered,
            "sent": self.sent,
            "duration_s": self.duration_s,
            "offered_rps": self.offered_rps,
            "achieved_rps": self.achieved_rps,
            "completed": self.completed,
            "sheds": self.sheds,
            "errors": self.errors,
            "statuses": {k: statuses[k] for k in sorted(statuses)},
            "latency_ms": {
                "p50": self.latency_quantile_ms(0.50),
                "p95": self.latency_quantile_ms(0.95),
                "p99": self.latency_quantile_ms(0.99),
            },
            "schedule_lag_p95_s": self.lag_quantile_s(0.95),
            "shed_ledger": self.shed_ledger(),
        }


def _outcome_label(status: int | None) -> str:
    if status is None:
        return "transport_error"
    if status == 429:
        return "shed"
    if status >= 500:
        return "error"
    if status >= 400:
        return "client_error"
    return "ok"


class OpenLoopGenerator:
    """Drives one base URL (a replica or a router edge) with planned
    open-loop traffic. ``client`` is any httpx-compatible async client —
    the chaos suite passes its in-process ASGI-free transport, bench
    passes a real socket client."""

    def __init__(
        self,
        client,
        base_url: str,
        *,
        mix: TrafficMix | None = None,
        session_ids: list[str] | None = None,
        metrics=None,
        request_timeout_s: float = 30.0,
    ) -> None:
        self._client = client
        self._base_url = base_url.rstrip("/")
        self._mix = mix or TrafficMix()
        self._session_ids = list(session_ids or [])
        self._timeout_s = request_timeout_s
        self._last_offered_rps = 0.0
        self._sent_total = None
        self._lag_seconds = None
        if metrics is not None:
            self._sent_total = metrics.counter(
                "bci_loadgen_sent_total",
                "Open-loop requests fired, by kind and client-observed "
                "outcome",
            )
            self._lag_seconds = metrics.histogram(
                "bci_loadgen_lag_seconds",
                "Scheduled-vs-actual send lag per open-loop request — "
                "nonzero tails mean the GENERATOR, not the service, was "
                "the bottleneck",
            )
            metrics.gauge(
                "bci_loadgen_offered_rps",
                "Offered (intended) arrival rate of the most recent "
                "open-loop run",
                lambda: self._last_offered_rps,
            )

    async def _fire(
        self, request: PlannedRequest, target_mono: float
    ) -> Sample:
        loop = asyncio.get_running_loop()
        start = loop.time()
        lag = start - target_mono
        if self._lag_seconds is not None:
            self._lag_seconds.observe(max(0.0, lag), kind=request.kind)
        headers = {}
        if request.tenant is not None:
            headers[TENANT_HEADER] = request.tenant
        url = f"{self._base_url}/v1/execute"
        params = None
        if request.kind == "stream":
            params = {"stream": "1"}
        elif request.kind == "session" and self._session_ids:
            sid = self._session_ids[request.index % len(self._session_ids)]
            url = f"{self._base_url}/v1/sessions/{sid}/execute"
        status: int | None = None
        error: str | None = None
        try:
            response = await self._client.post(
                url,
                json={"source_code": request.source},
                params=params,
                headers=headers or None,
                timeout=self._timeout_s,
            )
            status = response.status_code
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 — the outcome IS the data
            error = type(exc).__name__
        latency = loop.time() - start
        if self._sent_total is not None:
            self._sent_total.inc(
                kind=request.kind, outcome=_outcome_label(status)
            )
        return Sample(
            kind=request.kind,
            cost_class=request.cost_class,
            tenant=request.tenant,
            scheduled_s=request.at_s,
            lag_s=lag,
            latency_s=latency,
            status=status,
            error=error,
        )

    async def run(
        self,
        shape,
        *,
        label: str = "load",
        jitter_s: float = 0.0,
        seed: int = 0,
        drain_timeout_s: float = 30.0,
    ) -> LoadResult:
        """Fire the shape's full schedule open-loop and collect samples.
        The send loop NEVER awaits a response; after the last scheduled
        send, in-flight requests get ``drain_timeout_s`` to land, then are
        cancelled and counted as errors (an overloaded service does not
        get to launder its queue into an infinite drain)."""
        times = arrival_times(shape, jitter_s=jitter_s, seed=seed)
        plan = self._mix.plan(times)
        result = LoadResult(
            label=label, offered=len(plan), duration_s=shape.duration_s
        )
        self._last_offered_rps = result.offered_rps
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        tasks: list[tuple[PlannedRequest, asyncio.Task]] = []
        for request in plan:
            target = t0 + request.at_s
            delay = target - loop.time()
            if delay > 0:
                await asyncio.sleep(delay)
            tasks.append(
                (request, asyncio.create_task(self._fire(request, target)))
            )
        if tasks:
            await asyncio.wait(
                [task for _, task in tasks], timeout=drain_timeout_s
            )
        for request, task in tasks:
            if task.done() and not task.cancelled():
                result.samples.append(task.result())
            else:
                task.cancel()
                try:
                    await task
                except (asyncio.CancelledError, Exception):  # noqa: BLE001
                    pass
                result.samples.append(
                    Sample(
                        kind=request.kind,
                        cost_class=request.cost_class,
                        tenant=request.tenant,
                        scheduled_s=request.at_s,
                        lag_s=0.0,
                        latency_s=drain_timeout_s,
                        status=None,
                        error="undrained",
                    )
                )
                if self._sent_total is not None:
                    self._sent_total.inc(
                        kind=request.kind, outcome="undrained"
                    )
        return result
