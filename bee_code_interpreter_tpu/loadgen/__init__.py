"""Deterministic open-loop load generation + capacity measurement
(docs/capacity.md).

Every proof elsewhere in-tree is *closed-loop*: a test awaits each response
before sending the next request, so the offered rate silently degrades to
whatever the service can absorb and queueing collapse is invisible by
construction. This package is the open-loop counterpart — arrivals are
scheduled by wall-clock **intention** (a shape's integrated rate curve),
fired whether or not earlier responses came back — plus the capacity
reporter that reads the PR 10–17 observability plane while the load runs
and binary-searches the max-sustained-rps-at-SLO knee published as
``CAPACITY_r01.json`` by ``bench.py capacity``.

Layering mirrors ``observability/``: pure primitives here (shapes, mix,
generator, reporter — stdlib + the repo's own metrics registry only), the
fleet wiring lives in ``bench.py`` and the chaos suite.
"""

from bee_code_interpreter_tpu.loadgen.generator import (
    LoadResult,
    OpenLoopGenerator,
    quantile,
)
from bee_code_interpreter_tpu.loadgen.mix import (
    COST_CLASS_PAYLOADS,
    PlannedRequest,
    TrafficMix,
    heavy_tail_weights,
)
from bee_code_interpreter_tpu.loadgen.reporter import (
    CapacityReporter,
    evaluate_sustained,
    find_knee,
)
from bee_code_interpreter_tpu.loadgen.shapes import (
    Diurnal,
    FlashCrowd,
    Phases,
    Ramp,
    Steady,
    arrival_times,
)

__all__ = [
    "COST_CLASS_PAYLOADS",
    "CapacityReporter",
    "Diurnal",
    "FlashCrowd",
    "LoadResult",
    "OpenLoopGenerator",
    "Phases",
    "PlannedRequest",
    "Ramp",
    "Steady",
    "TrafficMix",
    "arrival_times",
    "evaluate_sustained",
    "find_knee",
    "heavy_tail_weights",
    "quantile",
]
