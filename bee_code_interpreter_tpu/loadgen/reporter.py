"""The capacity reporter: judge a load run against the SLO, find the knee.

The generator says what the CLIENT saw; this module reads what the
SERVICE said about itself while it happened — the federated ``/v1/slo``
burn state and the ``/v1/autoscale`` demand/forecast/recommendation
document — and combines both into one verdict per probe:

    sustained  ⇔  p99 ≤ threshold
               ∧  user-visible error ratio within the SLO's error budget
               ∧  shed ratio within budget
               ∧  achieved ≥ 90% of offered
               ∧  no fast-burn page fired
               ∧  the generator held its own schedule

``find_knee`` then bisects offered rps on that predicate: the largest
rate every probe sustained is the max-sustained-rps-at-SLO that
``CAPACITY_r01.json`` publishes, and the probes themselves are the
p99-vs-load curve.
"""

from __future__ import annotations

import statistics

from bee_code_interpreter_tpu.loadgen.generator import LoadResult
from bee_code_interpreter_tpu.loadgen.shapes import Steady


class CapacityReporter:
    """Scrapes one base URL's observability plane. Works identically
    against a replica edge and a router edge: both serve ``/v1/slo`` and
    ``/v1/autoscale`` (the router's are the federated documents). With an
    in-process router handle, also reads per-stage trace p50s."""

    def __init__(self, client, base_url: str, *, router=None) -> None:
        self._client = client
        self._base_url = base_url.rstrip("/")
        self._router = router

    async def _get(self, path: str) -> dict | None:
        try:
            response = await self._client.get(
                f"{self._base_url}{path}", timeout=10.0
            )
        except Exception:  # noqa: BLE001 — a scrape must never kill a probe
            return None
        if response.status_code != 200:
            return None
        try:
            body = response.json()
        except ValueError:
            return None
        return body if isinstance(body, dict) else None

    async def scrape(self) -> dict:
        """One observation of the plane: SLO + autoscale, each None when
        the edge cannot answer (scrapes are best-effort by contract)."""
        slo = await self._get("/v1/slo")
        autoscale = await self._get("/v1/autoscale")
        return {
            "slo": slo,
            "autoscale": autoscale,
            "fast_burn": bool((slo or {}).get("fast_burn_alerting"))
            or bool((slo or {}).get("fleet_fast_burn")),
            "warm_pop_ratio": _warm_pop_ratio(autoscale),
            "recommendation": (autoscale or {}).get("recommendation"),
        }

    def stage_p50_ms(self) -> dict[str, float]:
        """Per-stage router-tax breakdown from the in-process trace store
        (same computation as the bench router phase); empty without a
        router handle."""
        if self._router is None:
            return {}
        by_stage: dict[str, list[float]] = {}
        for trace in self._router.trace_store.traces():
            for stage, ms in trace.stage_ms().items():
                by_stage.setdefault(stage, []).append(ms)
        return {
            stage: round(statistics.median(samples), 3)
            for stage, samples in sorted(by_stage.items())
        }


def _warm_pop_ratio(autoscale: dict | None) -> float | None:
    if not autoscale:
        return None
    demand = autoscale.get("demand") or {}
    for key in ("warm_pop_ratio_60s", "warm_pop_ratio_min"):
        if demand.get(key) is not None:
            return demand[key]
    return None


def evaluate_sustained(
    result: LoadResult,
    scrape: dict | None = None,
    *,
    p99_ms: float,
    error_budget: float = 0.005,
    shed_budget: float = 0.01,
    max_lag_s: float = 0.25,
) -> dict:
    """The at-SLO verdict for one probe, with every failed criterion
    named — a knee you cannot explain is a number, not a measurement."""
    reasons: list[str] = []
    sent = max(1, result.sent)
    p99 = result.latency_quantile_ms(0.99)
    if p99 > p99_ms:
        reasons.append(f"p99 {p99:.0f}ms > {p99_ms:.0f}ms")
    error_ratio = result.errors / sent
    if error_ratio > error_budget:
        reasons.append(f"error ratio {error_ratio:.3f} > {error_budget}")
    shed_ratio = result.sheds / sent
    if shed_ratio > shed_budget:
        reasons.append(f"shed ratio {shed_ratio:.3f} > {shed_budget}")
    if result.achieved_rps < 0.9 * result.offered_rps:
        reasons.append(
            f"achieved {result.achieved_rps:.2f} rps < 90% of offered "
            f"{result.offered_rps:.2f}"
        )
    if scrape is not None and scrape.get("fast_burn"):
        reasons.append("fast-burn page fired")
    lag = result.lag_quantile_s(0.95)
    if lag > max_lag_s:
        # The generator fell behind its own schedule: the probe measured
        # the load box, not the service — an invalid probe counts as
        # unsustained so the knee search stays conservative.
        reasons.append(f"generator lag p95 {lag:.2f}s > {max_lag_s}s")
    return {"sustained": not reasons, "reasons": reasons}


async def find_knee(
    generator,
    *,
    lo_rps: float,
    hi_rps: float,
    duration_s: float,
    p99_ms: float,
    reporter: CapacityReporter | None = None,
    iterations: int = 5,
    error_budget: float = 0.005,
    shed_budget: float = 0.01,
    drain_timeout_s: float = 15.0,
    settle_s: float = 0.0,
    on_probe=None,
) -> tuple[float, list[dict]]:
    """Bisect offered steady rps on the sustained predicate. Returns
    ``(knee_rps, probes)``: the largest rate that sustained (0.0 when even
    ``lo_rps`` did not) plus every probe point — offered/achieved rps,
    latency quantiles, sheds, the plane scrape — oldest first, which IS
    the p99-vs-load curve."""
    probes: list[dict] = []
    knee = 0.0

    async def probe(rps: float) -> bool:
        result = await generator.run(
            Steady(rps=rps, duration_s=duration_s),
            label=f"steady-{rps:g}rps",
            drain_timeout_s=drain_timeout_s,
        )
        scrape = await reporter.scrape() if reporter is not None else None
        verdict = evaluate_sustained(
            result,
            scrape,
            p99_ms=p99_ms,
            error_budget=error_budget,
            shed_budget=shed_budget,
        )
        point = {
            "offered_rps": result.offered_rps,
            **verdict,
            "result": result.to_dict(),
            "warm_pop_ratio": (scrape or {}).get("warm_pop_ratio"),
            "recommendation": (scrape or {}).get("recommendation"),
        }
        probes.append(point)
        if on_probe is not None:
            on_probe(point)
        if settle_s > 0:
            # Let queues fully drain between probes so each rate is judged
            # from a clean start, not the previous probe's backlog.
            import asyncio

            await asyncio.sleep(settle_s)
        return verdict["sustained"]

    if not await probe(lo_rps):
        return 0.0, probes
    knee = lo_rps
    if await probe(hi_rps):
        return hi_rps, probes
    lo, hi = lo_rps, hi_rps
    for _ in range(max(0, iterations - 2)):
        mid = (lo + hi) / 2.0
        if hi - lo < 0.5:
            break
        if await probe(mid):
            knee = mid
            lo = mid
        else:
            hi = mid
    return knee, probes
