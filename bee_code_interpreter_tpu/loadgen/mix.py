"""Request mixes: what each scheduled arrival actually sends.

The fleet's cost-aware placement (docs/fleet.md) keys off
``analysis.classify_cost``; the payloads here are chosen so the
CLASSIFIER sees each cost class while the sandbox does near-zero work —
the ``accelerator`` payload carries a statically-visible ``import jax``
inside an ``if False:`` arm, so the router steers it like TPU work
without any sandbox ever paying the import. Tenant assignment follows a
seeded weighted draw; ``heavy_tail_weights`` produces the Zipf-like skew
(one hot tenant, a long cold tail) that makes per-tenant isolation tests
mean something.
"""

from __future__ import annotations

import random
from dataclasses import dataclass

# One near-free payload per analysis.policy.COST_CLASSES verdict (minus
# install_heavy, which would hit the dependency gate, not the pool).
COST_CLASS_PAYLOADS: dict[str, str] = {
    "cheap": "print(21 * 2)",
    "loopy": (
        "total = 0\n"
        "for i in range(3):\n"
        "    for j in range(3):\n"
        "        total += i * j\n"
        "print(total)"
    ),
    "io_heavy": (
        "with open('loadgen.txt', 'w') as f:\n"
        "    f.write('x')\n"
        "print('io')"
    ),
    "accelerator": "if False:\n    import jax\nprint('accel')",
}


def heavy_tail_weights(
    names: list[str] | tuple[str, ...], exponent: float = 1.5
) -> list[tuple[str, float]]:
    """Zipf-like weights over ``names``: the first gets weight 1, the
    k-th gets 1/k**exponent — the canonical heavy-tail tenant popularity
    curve."""
    return [
        (name, 1.0 / (k + 1) ** exponent) for k, name in enumerate(names)
    ]


@dataclass(frozen=True)
class PlannedRequest:
    """One scheduled arrival, fully decided before the load starts."""

    index: int
    at_s: float
    kind: str  # execute | session | stream
    cost_class: str
    tenant: str | None
    source: str


class TrafficMix:
    """Seeded weighted assignment of (kind, cost class, tenant) to each
    arrival slot. Same seed → same plan, so a probe is repeatable."""

    def __init__(
        self,
        *,
        kinds: tuple = (("execute", 7.0), ("session", 2.0), ("stream", 1.0)),
        cost_classes: tuple = (
            ("cheap", 8.0),
            ("loopy", 2.0),
            ("io_heavy", 1.0),
            ("accelerator", 1.0),
        ),
        tenants: list[tuple[str, float]] | None = None,
        seed: int = 0,
    ) -> None:
        self._kinds = [k for k, _ in kinds]
        self._kind_weights = [w for _, w in kinds]
        self._classes = [c for c, _ in cost_classes]
        self._class_weights = [w for _, w in cost_classes]
        self._tenants = [t for t, _ in tenants] if tenants else None
        self._tenant_weights = [w for _, w in tenants] if tenants else None
        self._seed = seed

    def plan(self, times: list[float]) -> list[PlannedRequest]:
        """Assign every arrival in one pass with one seeded rng — calling
        again with the same times reproduces the identical plan."""
        rng = random.Random(self._seed)
        out: list[PlannedRequest] = []
        for index, at_s in enumerate(times):
            kind = rng.choices(self._kinds, self._kind_weights)[0]
            cost_class = rng.choices(self._classes, self._class_weights)[0]
            tenant = None
            if self._tenants:
                tenant = rng.choices(self._tenants, self._tenant_weights)[0]
            out.append(
                PlannedRequest(
                    index=index,
                    at_s=at_s,
                    kind=kind,
                    cost_class=cost_class,
                    tenant=tenant,
                    source=COST_CLASS_PAYLOADS[cost_class],
                )
            )
        return out
