"""Service entry point: HTTP + gRPC servers sharing one asyncio loop.

Reference: __main__.py:22-36 (uvicorn + grpc.aio under aiorun). Here: aiohttp
AppRunner + grpc.aio, plain asyncio.run with signal-driven shutdown.
"""

from __future__ import annotations

import asyncio
import logging
import signal

from aiohttp import web

from bee_code_interpreter_tpu.application_context import ApplicationContext

logger = logging.getLogger(__name__)


async def main() -> None:
    ctx = ApplicationContext()

    host, _, port = ctx.config.http_listen_addr.rpartition(":")
    runner = web.AppRunner(ctx.http_server)
    await runner.setup()
    site = web.TCPSite(runner, host or "0.0.0.0", int(port))
    await site.start()
    logger.info("HTTP server listening on %s", ctx.config.http_listen_addr)

    await ctx.grpc_server.start(ctx.config.grpc_listen_addr)
    logger.info("gRPC server listening on %s", ctx.config.grpc_listen_addr)

    sweeper = ctx.start_storage_sweeper()

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()

    if sweeper is not None:
        sweeper.cancel()
    await ctx.grpc_server.stop()
    await runner.cleanup()
    # Tear down any warm sandboxes (only if the executor was ever built —
    # touching the cached_property here would needlessly construct it).
    executor = ctx.__dict__.get("code_executor")
    if executor is not None and hasattr(executor, "shutdown"):
        executor.shutdown()


def run() -> None:
    asyncio.run(main())


if __name__ == "__main__":
    run()
