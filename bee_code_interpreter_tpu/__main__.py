"""Service entry point: HTTP + gRPC servers sharing one asyncio loop.

Reference: __main__.py:22-36 (uvicorn + grpc.aio under aiorun). Here: aiohttp
AppRunner + grpc.aio, plain asyncio.run with signal-driven GRACEFUL shutdown
(docs/resilience.md "Graceful drain"):

1. SIGTERM/SIGINT flips the service into draining mode — new sandbox-bound
   work is rejected retryably (HTTP 503 + ``Retry-After``, gRPC UNAVAILABLE,
   health ``NOT_SERVING``) while in-flight executions keep running.
2. Teardown waits up to ``APP_DRAIN_GRACE_S`` for the in-flight work to
   finish (a second signal skips the wait).
3. Servers stop, the supervisor and warm pool are torn down, and the
   executor's HTTP client is closed deterministically (awaited in-loop).
"""

from __future__ import annotations

import asyncio
import logging
import signal

from aiohttp import web

from bee_code_interpreter_tpu.application_context import ApplicationContext

logger = logging.getLogger(__name__)


async def main() -> None:
    ctx = ApplicationContext()

    host, _, port = ctx.config.http_listen_addr.rpartition(":")
    # Short cleanup bound: by the time runner.cleanup() runs, the drain
    # already waited APP_DRAIN_GRACE_S for in-flight work — aiohttp's 60s
    # default would let one wedged handler outlive a k8s termination grace
    # and skip the pool teardown entirely.
    runner = web.AppRunner(ctx.http_server, shutdown_timeout=3.0)
    await runner.setup()
    site = web.TCPSite(runner, host or "0.0.0.0", int(port))
    await site.start()
    logger.info("HTTP server listening on %s", ctx.config.http_listen_addr)

    await ctx.grpc_server.start(ctx.config.grpc_listen_addr)
    logger.info("gRPC server listening on %s", ctx.config.grpc_listen_addr)

    ctx.start_storage_sweeper()
    # Once-only sweep of crash-orphaned .tmp-* writer temps (lazily kicked
    # by the first write otherwise): run at boot so the count is logged
    # deterministically.
    await ctx.storage.recover_orphans()
    # Background OTLP push of traces + metric snapshots (APP_OTLP_ENDPOINT);
    # no-op when export isn't configured.
    ctx.start_telemetry_exporter()
    # Flight-recorder disk flusher, event-loop lag probe, and the
    # continuous profiler (docs/observability.md).
    ctx.start_observability()

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()

    # Graceful drain: stop admitting, let in-flight executions finish. A
    # second signal during the grace period forces immediate teardown.
    ctx.begin_drain()
    force = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.remove_signal_handler(sig)
        loop.add_signal_handler(sig, force.set)
    grace_s = ctx.config.drain_grace_s
    logger.info(
        "Draining: waiting up to %.0fs for %d in-flight request(s)",
        grace_s,
        ctx.drain.in_flight,
    )
    wait = asyncio.ensure_future(ctx.drain.wait_idle(grace_s))
    forced = asyncio.ensure_future(force.wait())
    done, _ = await asyncio.wait(
        {wait, forced}, return_when=asyncio.FIRST_COMPLETED
    )
    forced.cancel()
    if wait in done and wait.result():
        logger.info("Drain complete: no requests in flight")
    else:
        wait.cancel()
        logger.warning(
            "Drain %s with %d request(s) still in flight; tearing down",
            "interrupted" if force.is_set() else "grace expired",
            ctx.drain.in_flight,
        )

    # Short stop grace for the same reason: the drain wait above is the
    # real in-flight budget; teardown must stay inside the supervisor's
    # (k8s terminationGracePeriodSeconds) remaining allowance.
    await ctx.grpc_server.stop(grace=2.0)
    await runner.cleanup()
    # Supervisor, storage sweeper, and warm sandboxes torn down awaited —
    # the old path scheduled the executor's HTTP-client close as a task the
    # dying loop could cancel before it ran.
    await ctx.aclose()


def run() -> None:
    asyncio.run(main())


if __name__ == "__main__":
    run()
