"""Composition root: wires config → storage → executors → servers.

Same role and shape as the reference's ApplicationContext
(application_context.py:36-125): lazy ``cached_property`` singletons, logging
dictConfig + request-id filter installed at construction, pod-pool warmup
kicked off on first access to the Kubernetes executor.
"""

from __future__ import annotations

import asyncio
import logging.config
from functools import cached_property

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.observability import (
    ContinuousProfiler,
    DemandTracker,
    DeviceMonitor,
    DeviceProfiler,
    FleetJournal,
    FlightRecorder,
    Forecaster,
    LoopMonitor,
    ServingMonitor,
    ServingProfiler,
    SloEngine,
    TelemetryExporter,
    Tracer,
    TraceStore,
    parse_objectives,
)
from bee_code_interpreter_tpu.services.custom_tool_executor import CustomToolExecutor
from bee_code_interpreter_tpu.services.storage import Storage
from bee_code_interpreter_tpu.utils.metrics import Registry
from bee_code_interpreter_tpu.utils.request_id import install_request_id_filter


class _LazyAdmission:
    """Late-binding admission handle for the quota-lease client: the real
    AdmissionController is a cached_property that itself consumes the lease
    cache, so the client (constructed first) must not materialize it."""

    def __init__(self, ctx: "ApplicationContext") -> None:
        self._ctx = ctx

    def quota_tenants(self) -> list[str]:
        return self._ctx.admission.quota_tenants()


class ApplicationContext:
    def __init__(self, config: Config | None = None) -> None:
        self.config = config or Config.from_env()
        # resolved config applies APP_LOG_FORMAT=json (structured one-line
        # records); the request-id filter also stamps trace/span ids now.
        logging.config.dictConfig(self.config.resolved_logging_config())
        install_request_id_filter()
        self.metrics = Registry()
        # Tenant-label cardinality bound (docs/tenancy.md "Cardinality"):
        # applied before any tenant-labeled metric registers.
        self.metrics.bound_label(
            "tenant", self.config.metrics_max_tenant_labels
        )
        # Tenant table + usage meter shared by both edges (docs/tenancy.md).
        # Always constructed: with no APP_TENANTS declared, every request
        # shares one unlimited `default` tenant and behavior is unchanged —
        # but the bci_tenant_* surface exists from first scrape.
        from bee_code_interpreter_tpu.tenancy import TenantRegistry

        self.tenancy = TenantRegistry.from_config(
            self.config, metrics=self.metrics
        )
        # Fleet-wide quota leases (docs/tenancy.md "Fleet-wide tenancy"):
        # with APP_QUOTA_LEASE_URLS set, this replica's rate quotas become
        # leased slices of each tenant's FLEET-wide quota, refreshed from
        # the router tier in the background. The cache is constructed
        # eagerly (the admission gate reads it synchronously); the client
        # loop starts in start_observability / on demand. Unset: leasing
        # off, local quotas enforced in full — the pre-fleet behavior.
        self.quota_leases = None
        self.quota_lease_client = None
        if self.config.quota_lease_urls:
            from bee_code_interpreter_tpu.tenancy import (
                QuotaLeaseCache,
                QuotaLeaseClient,
            )

            self.quota_leases = QuotaLeaseCache()
            self.quota_lease_client = QuotaLeaseClient(
                self.quota_leases,
                # late-bound: self.admission is a cached_property that
                # itself consumes self.quota_leases
                _LazyAdmission(self),
                replica=self.config.replica_name
                or self.config.http_listen_addr,
                router_urls=[
                    u.strip()
                    for u in self.config.quota_lease_urls.split(",")
                    if u.strip()
                ],
                interval_s=self.config.quota_lease_interval_s,
                metrics=self.metrics,
            )
        # One tracer + retention store shared by both transports: a trace is
        # a service-level object, whichever edge rooted it.
        self.trace_store = TraceStore(
            max_traces=self.config.trace_max_traces,
            slowest_keep=self.config.trace_slowest_keep,
        )
        self.tracer = Tracer(store=self.trace_store, metrics=self.metrics)
        # One fleet journal for the whole service: the pool backend records
        # sandbox transitions into it; both transports serve it.
        self.fleet = FleetJournal(
            metrics=self.metrics, max_events=self.config.fleet_max_events
        )
        # Pool supervisor (resilience/supervisor.py): created with the pool
        # executor it reconciles, None for the pool-less local backend.
        self.supervisor = None
        # Capacity observability (docs/autoscaling.md): per-second demand
        # telemetry fed by the shared admission gate and the fleet journal,
        # and the forecaster over it. Constructed unconditionally (their
        # gauges must exist either way); the PoolAutoscaler consuming them
        # is created with the pool executor in _wrap_pool_executor (None
        # for the pool-less local backend).
        self.demand = DemandTracker(
            window_s=self.config.demand_window_s,
            spawn_samples=self.config.demand_spawn_samples,
            metrics=self.metrics,
        )
        self.fleet.add_sink(self.demand.on_fleet_event)
        self.forecaster = Forecaster(
            self.demand,
            alpha=self.config.demand_ewma_alpha,
            beta=self.config.demand_trend_beta,
            metrics=self.metrics,
        )
        self.autoscaler = None
        # SLO engine: objectives come from config (APP_SLO_AVAILABILITY /
        # APP_SLO_LATENCY_MS); with none declared it is inert and /v1/slo
        # answers honestly empty. Both edges record into the ONE engine.
        self.slo = SloEngine(
            parse_objectives(
                self.config.slo_availability, self.config.slo_latency_ms
            ),
            metrics=self.metrics,
            bucket_s=self.config.slo_window_bucket_s,
            # Per-tenant SLO slices share the tenant-label bound the
            # registry and usage meter use (docs/tenancy.md "Cardinality").
            max_tenants=self.config.metrics_max_tenant_labels,
        )
        # Flight recorder (docs/observability.md "Flight recorder"): ONE
        # canonical wide event per execution / session op / stream / loop
        # stall, fed by a tracer sink so both edges emit identically.
        self.flight = FlightRecorder(
            max_events=self.config.events_max,
            dir=self.config.events_dir,
            segment_bytes=self.config.events_segment_bytes,
            max_segments=self.config.events_segments,
            metrics=self.metrics,
        )
        self.tracer.add_sink(self.flight.record_trace)
        # Event-loop health: lag probe + stall detector (task-stack dumps
        # land in the flight recorder); started by __main__ with the loop.
        self.loopmon = LoopMonitor(
            interval_s=self.config.loop_lag_interval_s,
            stall_threshold_s=self.config.loop_lag_stall_s,
            recorder=self.flight,
            metrics=self.metrics,
        )
        # Continuous profiler: constructed unconditionally (its metric must
        # exist either way); the sampler thread starts only when enabled.
        self.contprof = ContinuousProfiler(
            hz=self.config.contprof_hz,
            window_s=self.config.contprof_window_s,
            max_windows=self.config.contprof_windows,
            active_trace_ids=self.tracer.active_trace_ids,
            metrics=self.metrics,
        )
        # Serving-engine deep observability (docs/observability.md "Serving
        # observability"): per-request lifecycle traces into the shared
        # trace store, kind="serving" wide events into the flight recorder,
        # a bounded step-record ring behind GET /v1/serving. Constructed
        # unconditionally (its metrics must exist either way); an engine
        # binds later via attach_serving_engine, which also arms the
        # serving profiler (POST /v1/profile target=serving answers 501
        # until then).
        self.serving = ServingMonitor(
            metrics=self.metrics,
            store=self.trace_store,
            recorder=self.flight,
            max_steps=self.config.serving_step_records,
            max_requests=self.config.serving_request_records,
        )
        self.serving_profiler = ServingProfiler(self.serving)
        # Accelerator observability (docs/observability.md "Accelerator
        # observability"): compile/retrace wide events + counters, the
        # device-memory sampler (live-buffer estimate on CPU), per-mesh-
        # shape step timing. Constructed unconditionally — metrics must
        # exist either way, and the constructor's eager memory sample
        # registers the HBM gauges; attach_serving_engine binds the
        # batcher's tracked jits, start_observability starts the sampler.
        self.device = DeviceMonitor(
            metrics=self.metrics,
            recorder=self.flight,
            sample_interval_s=self.config.device_sample_interval_s,
            max_compiles=self.config.device_compile_records,
        )
        # POST /v1/profile target=device: raw jax.profiler capture —
        # serving steps when an engine is attached, a probe computation
        # otherwise (501 when the runtime cannot trace at all).
        self.device_profiler = DeviceProfiler(self.serving)
        # Telemetry export: with APP_OTLP_ENDPOINT set, finished traces and
        # metric snapshots are pushed OTLP/JSON to the collector by a
        # background exporter (started by __main__ once the loop runs).
        self.exporter = None
        if self.config.otlp_endpoint:
            from bee_code_interpreter_tpu.resilience import RetryPolicy

            self.exporter = TelemetryExporter(
                self.config.otlp_endpoint,
                self.metrics,
                flush_interval_s=self.config.otlp_flush_interval_s,
                queue_max=self.config.otlp_queue_max,
                batch_max=self.config.otlp_batch_max,
                retry=RetryPolicy(
                    attempts=self.config.otlp_retry_attempts,
                    wait_min_s=self.config.otlp_retry_wait_min_s,
                    wait_max_s=self.config.otlp_retry_wait_max_s,
                ),
                timeout_s=self.config.otlp_timeout_s,
            )
            self.tracer.add_sink(self.exporter.enqueue_trace)
            # Wide events ride the logs signal through the same exporter
            # (drop-not-block queue, exact drop accounting).
            self.flight.add_sink(self.exporter.enqueue_log)

    @cached_property
    def storage(self) -> Storage:
        # Backend selected by APP_STORAGE_BACKEND (docs/fleet.md): local
        # replica-private directory by default, shared mounted volume or an
        # S3-shaped store when snapshots must resolve fleet-wide. The
        # backend's init sweep reaps crash-orphaned .tmp-* writer temps,
        # counted and logged once.
        return Storage.from_config(self.config)

    def start_storage_sweeper(self) -> asyncio.Task | None:
        """Periodic TTL sweep of stored objects when storage_max_age_s is set
        (must be called from a running loop; __main__ does)."""
        if self.config.storage_max_age_s is None:
            return None

        async def sweeper() -> None:
            log = logging.getLogger(__name__)
            while True:
                try:
                    removed = await self.storage.sweep(self.config.storage_max_age_s)
                    if removed:
                        log.info("Storage sweep removed %d expired objects", removed)
                except Exception:
                    log.exception("Storage sweep failed")
                await asyncio.sleep(self.config.storage_sweep_interval_s)

        self._storage_sweeper_task = asyncio.create_task(sweeper())
        return self._storage_sweeper_task

    def start_telemetry_exporter(self):
        """Start the background OTLP export loop when one is configured
        (must be called from a running loop; __main__ does)."""
        if self.exporter is not None:
            self.exporter.start()
        return self.exporter

    def start_observability(self) -> None:
        """Start the flight recorder's disk flusher (when a segment dir is
        configured), the event-loop lag probe, and the continuous profiler
        (must be called from a running loop; __main__ does)."""
        self.flight.start()
        self.loopmon.start()
        # the serving monitor's wide events must reach the recorder's loop
        # even when its hooks fire from a worker thread (profiler captures)
        # and the engine was attached before the loop existed
        self.serving.arm_loop()
        if self.config.device_monitor_enabled:
            # periodic device-memory sampler + compile-event loop binding
            self.device.start()
        else:
            self.device.arm_loop()
        if self.config.contprof_enabled:
            self.contprof.start()
        if self.quota_lease_client is not None:
            self.quota_lease_client.start()

    def attach_serving_engine(self, engine) -> None:
        """Bind a ``models.engine.Engine`` (or bare ``ContinuousBatcher``)
        to the serving monitor: per-request lifecycle traces/wide events
        start flowing, ``GET /v1/serving`` reports it, and ``POST
        /v1/profile`` target=serving captures real batcher steps instead of
        answering 501. Construct the engine with ``metrics=ctx.metrics`` so
        its aggregate gauges land in the same registry. The device monitor
        attaches too: the batcher's tracked jits start reporting compiles
        and its steps land in the per-mesh-shape aggregates."""
        self.serving.attach(engine)
        self.device.attach(engine)

    def autoscale_snapshot(self) -> dict:
        """The ``GET /v1/autoscale`` document both edges serve — demand
        telemetry, the forecast, and the autoscaler's target + decision log
        (null section for the pool-less local backend)."""
        from bee_code_interpreter_tpu.resilience import autoscale_snapshot

        return autoscale_snapshot(
            demand=self.demand,
            forecaster=self.forecaster,
            autoscaler=self.autoscaler,
        )

    def build_debug_bundle(self) -> dict:
        """The one-call incident snapshot both edges serve — built here so
        HTTP and gRPC can never disagree about what a bundle contains."""
        from bee_code_interpreter_tpu.observability import build_debug_bundle

        return build_debug_bundle(
            tracer=self.tracer,
            fleet=self.fleet,
            slo=self.slo,
            metrics=self.metrics,
            config=self.config,
            executor=self.__dict__.get("code_executor"),
            supervisor=self.supervisor,
            drain=self.drain,
            exporter=self.exporter,
            recorder=self.flight,
            loopmon=self.loopmon,
            contprof=self.contprof,
            serving=self.serving,
            device=self.device,
            autoscale=self.autoscale_snapshot,
            tenancy=self.tenancy,
        )

    @cached_property
    def drain(self):
        """Graceful-drain state shared by both transports and ``__main__``:
        one flag, one in-flight count, one grace wait for the whole service."""
        from bee_code_interpreter_tpu.resilience import DrainController

        return DrainController(
            metrics=self.metrics,
            retry_after_s=self.config.admission_retry_after_s,
        )

    def begin_drain(self) -> None:
        """Flip the service into draining mode (SIGTERM does this via
        ``__main__``): edges reject new work retryably, gRPC health goes
        NOT_SERVING, the supervisor stops replenishing the pool. In-flight
        executions keep running; await ``drain.wait_idle(grace)`` for them."""
        self.drain.begin()

    async def aclose(self) -> None:
        """Deterministic teardown for the drain path: stop the supervisor
        and storage sweeper, then close the executor backend (awaited —
        never a fire-and-forget task a dying loop can cancel)."""
        sweeper = getattr(self, "_storage_sweeper_task", None)
        if sweeper is not None:
            sweeper.cancel()
        if self.quota_lease_client is not None:
            await self.quota_lease_client.stop()
        sessions = self.__dict__.get("sessions")
        if sessions is not None:
            # Leases end BEFORE the executor closes: each teardown journals
            # its reason and returns the sandbox through the backend while
            # the backend is still alive to do it.
            await sessions.stop()
            await sessions.close_all("shutdown")
        if self.exporter is not None:
            # Final best-effort flush (retry-bounded) before teardown.
            await self.exporter.stop()
        self.contprof.stop()
        self.device.stop()
        await self.loopmon.stop()
        # After the exporter: its final flush may still have drained wide
        # events; the recorder's stop writes its own pending disk segment.
        await self.flight.stop()
        if self.supervisor is not None:
            await self.supervisor.stop()
        executor = self.__dict__.get("code_executor")
        if executor is not None:
            from bee_code_interpreter_tpu.observability import unwrap_executor

            backend = unwrap_executor(executor)
            aclose = getattr(backend, "aclose", None)
            if aclose is not None:
                await aclose()
            elif hasattr(backend, "shutdown"):
                backend.shutdown()
        storage = self.__dict__.get("storage")
        if storage is not None:
            # After the executor: snapshots may still move during teardown
            # (lease checkpoints). No-op for directory backends; closes the
            # s3 backend's HTTP client.
            await storage.aclose()

    def _wrap_pool_executor(self, executor):
        """Shared pool-backend wiring: the replay/hedge front, the
        SLO-aware predictive autoscaler (docs/autoscaling.md), and the pool
        supervisor (owned per executor; its loop starts only when one runs —
        mirroring the warmup deferral below)."""
        from bee_code_interpreter_tpu.resilience import (
            HedgingExecutor,
            PoolAutoscaler,
            PoolSupervisor,
        )

        cfg = self.config
        self.autoscaler = PoolAutoscaler(
            executor,
            self.forecaster,
            self.demand,
            mode=cfg.autoscale_mode,
            min_size=cfg.autoscale_min,
            max_size=cfg.autoscale_max,
            idle_s=cfg.autoscale_idle_s,
            cooldown_s=cfg.autoscale_cooldown_s,
            base_target=cfg.executor_pod_queue_target_length,
            slo=self.slo,
            recorder=self.flight,
            metrics=self.metrics,
        )
        self.supervisor = PoolSupervisor(
            executor,
            interval_s=cfg.supervisor_interval_s,
            execute_hard_cap_s=cfg.resolved_execution_hard_cap_s(),
            metrics=self.metrics,
            drain=self.drain,
            autoscaler=self.autoscaler,
        )
        if cfg.supervisor_interval_s > 0:
            try:
                asyncio.get_running_loop()
            except RuntimeError:
                pass
            else:
                self.supervisor.start()
        return HedgingExecutor(
            executor,
            replay_max=cfg.execution_replay_max,
            hedge_delay_s=cfg.hedge_delay_s,
            metrics=self.metrics,
        )

    @cached_property
    def sessions(self):
        """Session-lease manager shared by both transports
        (docs/sessions.md): one lease table, one expiry sweep, one cap for
        the whole service. Its background sweep starts with the first
        access inside a running loop (tests drive ``sweep_once`` by hand)."""
        from bee_code_interpreter_tpu.sessions import SessionManager

        cfg = self.config
        manager = SessionManager(
            self.code_executor,
            self.storage,
            max_sessions=cfg.session_max,
            ttl_s=cfg.session_ttl_s,
            idle_s=cfg.session_idle_s,
            sweep_interval_s=cfg.session_sweep_interval_s,
            drain_grace_s=cfg.session_drain_grace_s,
            retry_after_s=cfg.admission_retry_after_s,
            metrics=self.metrics,
            drain=self.drain,
            recorder=self.flight,
        )
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            pass
        else:
            manager.start()
        return manager

    @cached_property
    def analyzer(self):
        """Edge static-analysis gate shared by both transports (None when
        APP_ANALYSIS_ENABLED=false): one policy, one metrics surface, one
        dep-prediction behavior — the two edges can never disagree about
        what gets refused."""
        from bee_code_interpreter_tpu.analysis import WorkloadAnalyzer

        return WorkloadAnalyzer.from_config(self.config, metrics=self.metrics)

    @cached_property
    def admission(self):
        """Edge admission gate shared by the HTTP and gRPC servers: one
        in-flight/queue budget for the whole service, not per transport."""
        from bee_code_interpreter_tpu.resilience import AdmissionController

        return AdmissionController(
            max_in_flight=self.config.admission_max_in_flight,
            max_queue=self.config.admission_max_queue,
            retry_after_s=self.config.admission_retry_after_s,
            metrics=self.metrics,
            # The one chokepoint both transports share is also the demand
            # sensor: arrivals/sheds/queue-waits feed the capacity tracker.
            demand=self.demand,
            # Opt-in: the analyzer's cost_class hint bounds heavy work
            # (docs/analysis.md "Cost classes").
            cost_aware=self.config.admission_cost_aware,
            # Per-tenant WFQ + quotas (docs/tenancy.md): with no tenant
            # table declared this is one unlimited default lane.
            tenancy=self.tenancy,
            # Fleet-wide quota leases: rate refills consult the leased
            # slice (or its fail-safe 1/N fallback) when leasing is on.
            quota_leases=self.quota_leases,
        )

    def _build_local_executor(self):
        from bee_code_interpreter_tpu.services.local_code_executor import (
            LocalCodeExecutor,
        )

        return LocalCodeExecutor(
            storage=self.storage,
            workspace_root=self.config.local_workspace_root,
            disable_dep_install=self.config.disable_dep_install,
            execution_timeout_s=self.config.execution_timeout_s,
            shim_dir=self.config.resolved_shim_dir(),
        )

    @cached_property
    def code_executor(self):
        if self.config.executor_backend == "local":
            # With a native binary configured, sandboxes are real executor-server
            # processes (the single-TPU-VM deployment mode — full wire contract,
            # no cluster); otherwise the pure-Python in-process core.
            if self.config.local_executor_binary:
                from bee_code_interpreter_tpu.services.native_process_code_executor import (
                    NativeProcessCodeExecutor,
                )

                executor = NativeProcessCodeExecutor(
                    storage=self.storage,
                    config=self.config,
                    metrics=self.metrics,
                    journal=self.fleet,
                )
                self._register_pool_gauges(executor)
                try:
                    asyncio.get_running_loop()
                except RuntimeError:
                    pass
                else:
                    # anchored on the executor's task set (loop refs are weak)
                    executor._spawn_background(executor.fill_sandbox_queue())
                return self._wrap_pool_executor(executor)
            return self._build_local_executor()
        from bee_code_interpreter_tpu.resilience import ResilientCodeExecutor
        from bee_code_interpreter_tpu.services.kubectl import Kubectl
        from bee_code_interpreter_tpu.services.kubernetes_code_executor import (
            KubernetesCodeExecutor,
        )

        executor = KubernetesCodeExecutor(
            kubectl=Kubectl(kubectl_path=self.config.kubectl_path),
            storage=self.storage,
            config=self.config,
            metrics=self.metrics,
            journal=self.fleet,
        )
        self._register_pool_gauges(executor)
        self._register_breaker_gauges(executor)
        # Pool warmup starts as soon as the executor exists (reference
        # application_context.py:83). Outside a running loop (e.g. tests
        # constructing the context), warmup is deferred — the pool refills on
        # first use anyway.
        try:
            asyncio.get_running_loop()
        except RuntimeError:
            pass
        else:
            # anchored on the executor's task set (loop refs are weak)
            executor._spawn_background(executor.fill_executor_pod_queue())
        # Graceful degradation: with APP_FALLBACK_TO_LOCAL=true, requests are
        # served by the local in-process executor while the Kubernetes
        # backend's breaker is open (docs/resilience.md).
        fallback = self._build_local_executor() if self.config.fallback_to_local else None
        return ResilientCodeExecutor(
            primary=self._wrap_pool_executor(executor),
            fallback=fallback,
            metrics=self.metrics,
        )

    def _register_pool_gauges(self, executor) -> None:
        self.metrics.gauge(
            "bci_executor_pool_ready",
            "Warm executor sandboxes ready in the pool",
            lambda: executor.pool_ready_count,
        )
        self.metrics.gauge(
            "bci_executor_pool_spawning",
            "Executor sandboxes currently being spawned",
            lambda: executor.pool_spawning_count,
        )

    def _register_breaker_gauges(self, executor) -> None:
        for breaker in (executor.spawn_breaker, executor.http_breaker):
            self.metrics.gauge(
                "bci_breaker_state",
                "Circuit breaker state (0=closed, 1=open, 2=half-open)",
                (lambda b: lambda: int(b.state))(breaker),
                breaker=breaker.name,
            )

    @cached_property
    def custom_tool_executor(self) -> CustomToolExecutor:
        return CustomToolExecutor(code_executor=self.code_executor)

    @cached_property
    def http_server(self):
        from bee_code_interpreter_tpu.api.http_server import create_http_server

        return create_http_server(
            code_executor=self.code_executor,
            custom_tool_executor=self.custom_tool_executor,
            metrics=self.metrics,
            admission=self.admission,
            request_deadline_s=self.config.request_deadline_s,
            tracer=self.tracer,
            fleet=self.fleet,
            drain=self.drain,
            supervisor=self.supervisor,
            slo=self.slo,
            debug_bundle=self.build_debug_bundle,
            analyzer=self.analyzer,
            sessions=self.sessions,
            recorder=self.flight,
            loopmon=self.loopmon,
            contprof=self.contprof,
            serving=self.serving,
            profiler=self.serving_profiler,
            device=self.device,
            device_profiler=self.device_profiler,
            autoscale=self.autoscale_snapshot,
            tenancy=self.tenancy,
        )

    @cached_property
    def grpc_server(self):
        from bee_code_interpreter_tpu.api.grpc_server import GrpcServer

        return GrpcServer(
            code_executor=self.code_executor,
            custom_tool_executor=self.custom_tool_executor,
            tls_cert=self.config.grpc_tls_cert,
            tls_cert_key=self.config.grpc_tls_cert_key,
            tls_ca_cert=self.config.grpc_tls_ca_cert,
            admission=self.admission,
            request_deadline_s=self.config.request_deadline_s,
            metrics=self.metrics,
            tracer=self.tracer,
            fleet=self.fleet,
            drain=self.drain,
            slo=self.slo,
            debug_bundle=self.build_debug_bundle,
            analyzer=self.analyzer,
            sessions=self.sessions,
            recorder=self.flight,
            loopmon=self.loopmon,
            contprof=self.contprof,
            serving=self.serving,
            device=self.device,
            autoscale=self.autoscale_snapshot,
            tenancy=self.tenancy,
        )
