"""Service configuration.

Single env-overridable config object with the ``APP_`` prefix, matching the
reference's production-config surface (reference: src/code_interpreter/config.py:18-80,
README.md:159). pydantic-settings is not available in this environment, so env
loading is implemented directly on top of pydantic: scalar fields parse from the
raw string, dict/list-valued fields (container resources, pod-spec extras,
logging config, TPU node selectors) parse from JSON env strings — the documented
way deployments inject gVisor ``runtimeClassName``, resource limits, and TPU
node-pool selectors.

TPU additions beyond the reference's fields: executor backend selection
(``kubernetes`` | ``local``), slice topology (accelerator type, chips per host,
hosts per slice) used by the pod-group scheduler, and the execution timeout that
the reference hardcoded in the executor (executor/server.rs:151).
"""

from __future__ import annotations

import json
import os
from typing import Any, Literal

from pydantic import BaseModel, Field


def _default_logging_config() -> dict[str, Any]:
    return {
        "version": 1,
        "disable_existing_loggers": False,
        "formatters": {
            "default": {
                "format": "%(asctime)s [%(levelname)s] [%(request_id)s] %(name)s: %(message)s",
            }
        },
        "handlers": {
            "default": {
                "class": "logging.StreamHandler",
                "formatter": "default",
                "stream": "ext://sys.stderr",
            }
        },
        "root": {"level": "WARNING", "handlers": ["default"]},
        "loggers": {
            "bee_code_interpreter_tpu": {"level": "INFO"},
            "aiohttp.access": {"level": "INFO"},
        },
    }


class Config(BaseModel):
    """All service configuration; every field overridable via ``APP_<UPPER_NAME>``."""

    # --- network listeners (reference config.py:50-53) ---
    http_listen_addr: str = "0.0.0.0:50081"
    grpc_listen_addr: str = "0.0.0.0:50051"

    # --- optional gRPC mTLS (reference config.py:56-62) ---
    grpc_tls_cert: bytes | None = None
    grpc_tls_cert_key: bytes | None = None
    grpc_tls_ca_cert: bytes | None = None

    # --- executor backend ---
    executor_backend: Literal["kubernetes", "local"] = "kubernetes"
    executor_image: str = "bee-code-interpreter-tpu-executor:local"
    executor_container_resources: dict[str, Any] = Field(default_factory=dict)
    executor_pod_spec_extra: dict[str, Any] = Field(default_factory=dict)
    executor_pod_queue_target_length: int = 5
    executor_pod_name_prefix: str = "code-executor-"
    executor_port: int = 8000
    # kubectl binary the service shells out to (APP_KUBECTL_PATH). Lets a
    # deployment pin a versioned binary, and the e2e suite point the REAL
    # kubernetes executor at a fake cluster CLI.
    kubectl_path: str = "kubectl"
    # Per-execution wall-clock timeout, plumbed through to the sandbox executor
    # (the reference hardcoded 60s in the executor and never set the request
    # field: executor/server.rs:151, kubernetes_code_executor.py:117-123).
    execution_timeout_s: float = 60.0
    # Service→pod HTTP client timeout (reference kubernetes_code_executor.py:95-97).
    executor_http_timeout_s: float = 60.0
    # Cold pod spawn readiness bound (reference kubernetes_code_executor.py:239-241).
    pod_ready_timeout_s: float = 60.0

    # --- resilience (new; see docs/resilience.md) ---
    # Total wall-clock budget per request, created as a Deadline at the API
    # edge and propagated through spawn/upload/execute/download so the sum of
    # all downstream work is bounded — not each step independently.
    request_deadline_s: float = Field(default=120.0, gt=0)
    # When the Kubernetes backend's circuit breaker is open, serve requests
    # with the local in-process executor instead of failing (degraded
    # isolation, preserved availability). APP_FALLBACK_TO_LOCAL=true.
    fallback_to_local: bool = False
    # Circuit breakers around pod-group spawn and the executor HTTP data
    # plane: trip OPEN once failure_rate_threshold is hit across the last
    # `window` calls (given at least min_calls outcomes); probe again after
    # cooldown_s with up to half_open_max_calls concurrent half-open calls.
    breaker_window: int = Field(default=10, ge=1)
    breaker_failure_rate_threshold: float = Field(default=0.5, gt=0, le=1)
    breaker_min_calls: int = Field(default=4, ge=1)
    breaker_cooldown_s: float = Field(default=30.0, gt=0)
    breaker_half_open_max_calls: int = Field(default=1, ge=1)
    # Admission control at the edge: max_in_flight requests execute, up to
    # admission_max_queue wait (deadline-bounded); the rest shed as HTTP 429
    # / gRPC RESOURCE_EXHAUSTED with a Retry-After of admission_retry_after_s.
    admission_max_in_flight: int = Field(default=64, ge=1)
    admission_max_queue: int = Field(default=128, ge=0)
    admission_retry_after_s: float = Field(default=1.0, gt=0)
    # Opt-in cost-aware admission (docs/analysis.md "Cost classes"): when
    # on, executions the edge analyzer classified io_heavy/install_heavy
    # additionally pass a bounded heavy lane (half of max_in_flight), so a
    # burst of expensive work is shed (429/RESOURCE_EXHAUSTED) before it
    # can starve cheap interactive turns out of the warm pool. Off by
    # default: cost classes are then hints only (span/wide event/response).
    admission_cost_aware: bool = False
    # Transient-failure retry schedule for executor spawn and data-plane
    # calls (the seed hardcoded tenacity's 3×/4-10s at import time).
    executor_retry_attempts: int = Field(default=3, ge=1)
    executor_retry_wait_min_s: float = Field(default=4.0, gt=0)
    executor_retry_wait_max_s: float = Field(default=10.0, gt=0)
    # --- proactive resilience: supervisor / replay / hedge / drain ---
    # Pool supervisor reconcile cadence: each sweep health-probes queued warm
    # sandboxes (reaping dead ones), kills stuck executions, and replenishes
    # the pool to target. 0 disables the background loop (sweeps can still be
    # driven manually, e.g. by tests).
    supervisor_interval_s: float = Field(default=10.0, ge=0)
    # Warm-sandbox /healthz probe timeout (checkout pre-probe + supervisor
    # sweeps); on the request path it is additionally clamped to the
    # remaining checkout deadline.
    health_probe_timeout_s: float = Field(default=2.0, gt=0)
    # Stuck-execution watchdog: an execute in flight longer than this hard
    # wall-clock cap gets its sandbox killed and fails as transient (replay /
    # retry may still recover it). Unset derives execution_timeout_s +
    # executor_http_timeout_s — strictly above any legitimate execution.
    execution_hard_cap_s: float | None = Field(default=None, gt=0)
    # Max transparent replays of an execution whose sandbox died mid-flight
    # (safe: single-use sandboxes + content-addressed workspace snapshots;
    # at-least-once semantics — see docs/resilience.md). 0 disables.
    execution_replay_max: int = Field(default=1, ge=0)
    # Opt-in hedged execution: when the primary attempt hasn't finished after
    # this many seconds, launch the request on a second warm sandbox; first
    # result wins, the loser is cancelled and reaped. Unset/0 disables.
    hedge_delay_s: float | None = Field(default=None, ge=0)
    # Graceful drain: after SIGTERM (or ctx.begin_drain()) the edges reject
    # new work retryably while in-flight executions get up to this many
    # seconds to finish before teardown.
    drain_grace_s: float = Field(default=30.0, ge=0)

    # --- observability (new; see docs/observability.md) ---
    # APP_LOG_FORMAT=json swaps the default text formatter for one-line JSON
    # records carrying request_id/trace_id/span_id (structured-log schema in
    # docs/observability.md). Only the default formatter is swapped; a custom
    # APP_LOGGING_CONFIG is taken verbatim.
    log_format: Literal["text", "json"] = "text"
    # Finished traces retained in memory for GET /v1/traces: a ring of the
    # most recent trace_max_traces, of which trace_slowest_keep slots are
    # reserved for the slowest requests seen (the outliers worth inspecting
    # are exactly the ones a plain ring evicts first under load).
    trace_max_traces: int = Field(default=256, ge=1)
    trace_slowest_keep: int = Field(default=32, ge=0)
    # Sandbox lifecycle events retained in the fleet journal for
    # GET /v1/fleet/events (each pod contributes ~4-6 events per life).
    fleet_max_events: int = Field(default=512, ge=1)
    # --- flight recorder (docs/observability.md "Flight recorder") ---
    # Wide events retained in memory for GET /v1/events: one canonical
    # record per execution / session lifecycle op / stream / loop stall.
    events_max: int = Field(default=512, ge=1)
    # Directory for size-rotated ndjson segment files of every wide event;
    # unset keeps the recorder memory-only. Writes happen off-loop behind a
    # bounded queue — a slow disk drops events (accounted), never blocks.
    events_dir: str | None = None
    # Rotate the active segment once it exceeds this many bytes; keep at
    # most events_segments files (oldest deleted).
    events_segment_bytes: int = Field(default=1 << 20, ge=1)
    events_segments: int = Field(default=4, ge=1)
    # --- event-loop health (docs/observability.md "Event-loop health") ---
    # Lag-probe cadence for bci_event_loop_lag_seconds; 0 disables the
    # background probe entirely.
    loop_lag_interval_s: float = Field(default=0.25, ge=0)
    # Lag at/over this threshold is a *stall*: the monitor captures an
    # asyncio task-stack dump into a wide event and GET /v1/debug/tasks.
    loop_lag_stall_s: float = Field(default=0.5, gt=0)
    # --- continuous profiler (docs/observability.md "Continuous profiler") ---
    # Always-on sampling profiler over sys._current_frames, served at
    # GET /v1/debug/pprof. The sampler costs per-process (not per-request);
    # disable only to A/B its overhead.
    contprof_enabled: bool = True
    # Sampling rate; ~19 Hz is deliberately off-beat so the sampler cannot
    # phase-lock with periodic work.
    contprof_hz: float = Field(default=19.0, gt=0)
    # Aggregation window length and how many completed windows to retain.
    contprof_window_s: float = Field(default=60.0, gt=0)
    contprof_windows: int = Field(default=5, ge=1)
    # --- serving observability (docs/observability.md "Serving
    # observability") ---
    # Batcher step records retained in the serving monitor's ring for
    # GET /v1/serving (one record per ContinuousBatcher.step when an
    # engine is attached).
    serving_step_records: int = Field(default=512, ge=1)
    # Finished per-request lifecycle records retained for
    # GET /v1/serving/requests (live requests are always reported).
    serving_request_records: int = Field(default=256, ge=1)
    # --- accelerator observability (docs/observability.md "Accelerator
    # observability") ---
    # Gates the background device-memory sampler only: compile/retrace
    # tracking and per-mesh-shape step telemetry are hook-driven and stay
    # on whenever a serving engine is attached (their cost is one None
    # check when nothing is).
    device_monitor_enabled: bool = True
    # Device-memory sample cadence (memory_stats on TPU; the live-buffer
    # estimate walks every live array on CPU, so not too hot).
    device_sample_interval_s: float = Field(default=10.0, gt=0)
    # Recent compile records retained for GET /v1/accelerator (lifetime
    # totals and per-function signature sets are kept regardless).
    device_compile_records: int = Field(default=256, ge=1)
    # --- telemetry export (docs/observability.md "Telemetry export") ---
    # OTLP/HTTP collector base URL (e.g. http://otel-collector:4318): finished
    # traces and metric snapshots are pushed as OTLP/JSON to
    # {endpoint}/v1/traces and /v1/metrics by a background exporter. Unset
    # disables export entirely (the in-memory stores keep working).
    otlp_endpoint: str | None = None
    # Export flush cadence; a full batch flushes early.
    otlp_flush_interval_s: float = Field(default=5.0, gt=0)
    # Finished traces buffered for export; beyond this, new traces are
    # DROPPED (accounted in bci_telemetry_dropped_total) — never blocks the
    # request path.
    otlp_queue_max: int = Field(default=512, ge=1)
    # Traces per export POST.
    otlp_batch_max: int = Field(default=64, ge=1)
    # Send retry schedule (reuses the resilience backoff); an exhausted batch
    # is dropped, not retried forever.
    otlp_retry_attempts: int = Field(default=3, ge=1)
    otlp_retry_wait_min_s: float = Field(default=0.5, gt=0)
    otlp_retry_wait_max_s: float = Field(default=5.0, gt=0)
    # Collector HTTP client timeout per POST.
    otlp_timeout_s: float = Field(default=10.0, gt=0)
    # --- SLOs (docs/observability.md "SLOs and burn-rate alerts") ---
    # Availability objective as a percent of recorded sandbox-bound requests
    # that must not fail server-side, e.g. 99.5. Unset declares none.
    slo_availability: float | None = Field(default=None, gt=0, lt=100)
    # Latency objectives as comma-separable THRESHOLD_MS:PERCENT entries,
    # e.g. "2000:99" (99% of successful requests within 2s). Unset: none.
    slo_latency_ms: str | None = None
    # SLO sliding-window bucket coarseness; windows span 5m..6h.
    slo_window_bucket_s: float = Field(default=10.0, gt=0)

    # --- capacity observability + predictive pool autoscaling
    # (new; see docs/autoscaling.md) ---
    # What the PoolAutoscaler does with its recommendations: `off` = no
    # evaluation at all; `advise` = decisions are logged/counted/emitted
    # (GET /v1/autoscale, bci_autoscale_decisions_total, kind="autoscale"
    # wide events) but the pool keeps its static target — run this in
    # production until the decision log earns trust; `act` = the pool
    # backend's refill target follows the recommendation.
    autoscale_mode: Literal["off", "advise", "act"] = "advise"
    # Warm-pool size bounds the recommendation is clamped to.
    autoscale_min: int = Field(default=1, ge=0)
    autoscale_max: int = Field(default=16, ge=1)
    # Shrink only after this long with NO arrivals at all (sustained idle);
    # scale-ups are never delayed by it.
    autoscale_idle_s: float = Field(default=60.0, gt=0)
    # Minimum spacing between a scale-down (or an SLO-burn-driven notch up)
    # and the previous decision — the anti-flap hysteresis.
    autoscale_cooldown_s: float = Field(default=15.0, ge=0)
    # Demand telemetry: per-second ring length behind GET /v1/autoscale and
    # the forecaster (bounded memory: one small bucket per second).
    demand_window_s: float = Field(default=120.0, gt=0)
    # Observed sandbox spawn latencies sampled for the forecast horizon.
    demand_spawn_samples: int = Field(default=64, ge=1)
    # Holt's linear smoothing constants over the per-second arrival series:
    # alpha weights the newest second's rate, beta the trend update.
    demand_ewma_alpha: float = Field(default=0.4, gt=0, le=1)
    demand_trend_beta: float = Field(default=0.2, ge=0, le=1)

    # --- tenancy (new; see docs/tenancy.md) ---
    # The tenant table: comma-separated "name[:key=value]..." entries, e.g.
    # APP_TENANTS="alpha:weight=4:max_in_flight=8:rps=20,beta:weight=1:rps=5".
    # Keys: weight (WFQ share), max_in_flight, rps, burst, sessions
    # (per-tenant lease cap), key (API key for Authorization: Bearer). A
    # "default" entry customizes the catch-all lane every unknown or
    # anonymous request shares; unset leaves one unlimited default tenant —
    # identical behavior to the pre-tenancy service.
    tenants: str | None = None
    # Bounded tenant-label cardinality: at most this many distinct tenant
    # labels on /metrics, in the SLO slices, and in the usage meter before
    # further ids collapse into "other" (overflow counted in
    # bci_metrics_label_overflow_total) — a tenant-id flood can widen one
    # bucket, never OOM the exposition.
    metrics_max_tenant_labels: int = Field(default=32, ge=1)

    # --- sessions: leased sandboxes + streaming (new; see docs/sessions.md) ---
    # Hard cap on concurrent session leases. Each lease pins one warm
    # sandbox the stateless pool cannot serve with, so this bounds how much
    # of the fleet interactive clients can hold; past the cap POST
    # /v1/sessions answers 429.
    session_max: int = Field(default=16, ge=0)
    # Total lease lifetime: a session older than this is expired by the
    # background sweep regardless of activity (a request may ask for less,
    # never more).
    session_ttl_s: float = Field(default=900.0, gt=0)
    # Idle bound between executions inside a lease: a REPL nobody is typing
    # into gives its sandbox back.
    session_idle_s: float = Field(default=120.0, gt=0)
    # Expiry sweep cadence; also how quickly a drain reclaims idle leases.
    session_sweep_interval_s: float = Field(default=1.0, gt=0)
    # Grace between drain start and the sweep force-expiring live leases
    # (reason="drain"): gives a fleet router time to hand leases off
    # (checkpoint → re-lease elsewhere → restore, docs/fleet.md) instead of
    # the replica killing them. 0 keeps the original behavior — first sweep
    # after drain reclaims everything. Set it at least one router refresh
    # interval on replicas fronted by a router.
    session_drain_grace_s: float = Field(default=0.0, ge=0)

    # --- fleet router (new; see docs/fleet.md) ---
    # The router edge (`python -m bee_code_interpreter_tpu.fleet`) listens
    # here and proxies /v1/execute, streaming, and session routes across the
    # replicas below.
    router_listen_addr: str = "0.0.0.0:50080"
    # Comma-separated replica base URLs, optionally named:
    # "r0=http://a:50081,r1=http://b:50081" (bare URLs are auto-named).
    router_replicas: str | None = None
    # Background refresh cadence: each tick pulls /v1/fleet (utilization,
    # drain state, leases) + /v1/slo (burn) from every replica.
    router_refresh_interval_s: float = Field(default=2.0, gt=0)
    # Virtual nodes per replica on the consistent-hash ring; more vnodes =
    # smoother ownership split at a small ring-size cost.
    router_vnodes: int = Field(default=64, ge=1)
    # Spill threshold: the ring owner is passed over while its utilization
    # is at/above this (or its SLO page alert fires) and a healthier
    # replica exists — affinity is a preference, overload is a veto.
    router_utilization_spill: float = Field(default=0.9, gt=0, le=1)
    # Cross-replica attempts per request (sheds/5xx/unreachable walk the
    # ring to the next replica; the count includes the first attempt).
    router_retry_attempts: int = Field(default=3, ge=1)
    # Router -> replica HTTP client timeout (covers the proxied execute).
    router_http_timeout_s: float = Field(default=120.0, gt=0)
    # A replica whose refresh has failed for this long is DEAD: out of the
    # ring until a refresh succeeds again.
    router_dead_after_s: float = Field(default=10.0, gt=0)
    # Routing/migration wide events retained in the router's ring.
    router_events_max: int = Field(default=1024, ge=1)
    # Per-replica deadline for federated fleet queries (the router-side
    # scatter-gather behind GET /v1/slo, /v1/traces, /v1/events,
    # /v1/tenants and /v1/fleet/debug/bundle — docs/fleet.md "Fleet
    # observability"). A replica slower than this is accounted in
    # `replicas_failed`, never waited out.
    router_federation_timeout_s: float = Field(default=2.0, gt=0)
    # --- fleet-wide tenancy (new; see docs/fleet.md "Fleet-wide tenancy") ---
    # Peer router edges for HA, comma-separated base URLs (optionally
    # named, same spelling as APP_ROUTER_REPLICAS). Peers gossip session
    # pins and the quota-lease ledger every refresh tick, so killing one
    # edge loses no pins and double-issues no quota beyond one lease TTL.
    router_peers: str | None = None
    # Lifetime of a quota lease the router grants a replica. Shorter =
    # faster fleet-wide convergence after membership churn (the declared
    # double-issue bound is one TTL); longer = more partition tolerance
    # before replicas fall back to their local 1/N split.
    router_quota_ttl_s: float = Field(default=3.0, gt=0)
    # Replica side of the lease protocol: comma-separated router base URLs
    # this replica leases quota slices from (usually the same list every
    # client uses). Unset disables leasing — each replica enforces its
    # full local quota, the pre-fleet behavior.
    quota_lease_urls: str | None = None
    # Lease refresh cadence; keep comfortably under APP_ROUTER_QUOTA_TTL_S
    # so a healthy replica never expires into the 1/N fallback.
    quota_lease_interval_s: float = Field(default=1.0, gt=0)
    # This replica's name in lease requests and the router ledger. Unset
    # derives "host:port" from the listen address.
    replica_name: str | None = None

    # --- edge static analysis (new; see docs/analysis.md) ---
    # Master switch for the pre-flight code gate at both API edges: one AST
    # pass per submission that fail-fasts syntax errors without consuming a
    # warm sandbox, evaluates the policy below, and pre-resolves deps for
    # the pod. Disable only to A/B the gate's cost.
    analysis_enabled: bool = True
    # The gate runs ON the event loop (it is sub-ms for real submissions);
    # source whose UTF-8 encoding exceeds this is "unanalyzable" instead of
    # being parsed — a multi-MB body must never stall every in-flight
    # request for seconds. Unanalyzable = refused fail-closed when a policy
    # is declared, admitted with the in-pod dep scan when none is
    # (docs/analysis.md).
    analysis_max_source_bytes: int = Field(default=262_144, ge=1)
    # Policy rules, comma-separated (same spelling convention as
    # APP_SLO_LATENCY_MS). Imports match top-level or dotted-subtree names
    # ("socket", "google.auth"); calls match alias-resolved dotted names
    # ("os.fork"), "pkg.*" wildcards, or built-in shape names
    # (fork_in_loop / raw_socket / subprocess); paths match absolute-path
    # literal prefixes ("/etc"). deny → HTTP 422 / gRPC INVALID_ARGUMENT
    # (SLI-good client faults); warn → response annotation + metric.
    # NOT a security boundary: matching is static only — __import__(...),
    # importlib, getattr indirection evade it. The sandbox enforces
    # isolation; these rules just refuse doomed work early.
    policy_deny_imports: str | None = None
    policy_warn_imports: str | None = None
    policy_deny_calls: str | None = None
    policy_warn_calls: str | None = None
    policy_deny_paths: str | None = None
    policy_warn_paths: str | None = None
    # What an import whose target the dataflow layer cannot constant-fold
    # (`__import__(name)`, `importlib.import_module(user_choice)`,
    # `getattr(<module>, <non-constant>)`) means under this policy:
    # `warn` (default — fail-open: annotated `dynamic_import` finding +
    # bci_analysis_dynamic_imports_total), `deny` (422/INVALID_ARGUMENT;
    # also makes unanalyzable sources fail closed), or `off`. Resolvable
    # dynamic imports are not this knob's business: the dataflow layer
    # constant-folds them into the ordinary deny/warn import lists
    # (docs/analysis.md "Dataflow layer").
    policy_dynamic_import: Literal["off", "warn", "deny"] = "warn"

    # --- object storage (reference config.py:74; backends in docs/fleet.md) ---
    # Where snapshot bytes live. `local` (default) is a replica-private flat
    # directory; `shared` is the same layout on a volume mounted into every
    # replica (fsync'd commits, age-gated orphan recovery) so snapshot ids
    # resolve identically fleet-wide; `s3` is an S3-shaped HTTP object store
    # (PUT/GET/HEAD {endpoint}/{bucket}/{id}) for deployments with a real
    # object store — the jump the reference plans as "shared volume/S3 in
    # prod".
    storage_backend: Literal["local", "shared", "s3"] = "local"
    # s3 backend: base endpoint URL (e.g. http://minio:9000) and bucket.
    storage_s3_endpoint: str | None = None
    storage_s3_bucket: str = "bci-snapshots"
    storage_s3_timeout_s: float = Field(default=30.0, gt=0)
    # Shared-backend startup orphan sweep: only `.tmp-*` writer temps older
    # than this are reaped (a fresh temp may be another live replica's
    # in-flight upload). The local backend always uses 0 — nothing else
    # writes its private root.
    storage_orphan_age_s: float = Field(default=3600.0, ge=0)
    file_storage_path: str = "./.tmp/files"
    # Optional TTL sweep of stored objects (the reference leaves cleanup to
    # the operator, its README.md:167). Unset disables; objects age from
    # their last snapshot (content-addressed rewrites refresh mtime).
    storage_max_age_s: float | None = Field(default=None, gt=0)
    storage_sweep_interval_s: float = Field(default=3600.0, gt=0)

    # --- TPU slice topology (new; consumed by the pod-group scheduler) ---
    # Accelerator type label value, e.g. "tpu-v5-lite-podslice" on GKE.
    tpu_accelerator_type: str | None = None
    # Topology label value, e.g. "2x4" (8 chips, 1 host) or "8x8" (64 chips, 8 hosts).
    tpu_topology: str | None = None
    # Hosts per slice: >1 makes the scheduler gang-schedule a pod *group* and
    # plumb jax.distributed coordination env into every member.
    tpu_hosts_per_slice: int = 1
    tpu_chips_per_host: int = 8
    # Extra nodeSelector entries for TPU node pools.
    tpu_node_selector: dict[str, str] = Field(default_factory=dict)

    # Shared persistent XLA compile-cache directory exported to sandboxes as
    # JAX_COMPILATION_CACHE_DIR (opt-in; point at a shared volume in k8s).
    # Single-use sandboxes then pay each unique program's compile once per
    # deployment instead of once per request.
    jax_cache_dir: str | None = None

    # --- local backend ---
    # Path to the native executor binary; when unset, the pure-Python in-process
    # executor (the test fake the reference never had; SURVEY.md §4) is used.
    local_executor_binary: str | None = None
    local_workspace_root: str = "./.tmp/workspaces"
    # Opt-in native-mode hardening: spawn each sandbox server inside its own
    # mount namespace (unshare) with the object-storage root overmounted by
    # an empty tmpfs, and the capability bounding set emptied (setpriv) so
    # user code cannot umount its way back to other sessions' files. Without
    # setpriv on PATH the overmount only guards against accidental access.
    # This is a mitigation, NOT an isolation boundary — native mode still
    # runs user code as the service's own user on a shared kernel; for
    # untrusted multi-tenant input use the Kubernetes backend (single-use
    # pod + optional gVisor via executor_pod_spec_extra). See
    # docs/architecture.md "Isolation and trust model".
    sandbox_unshare: bool = False
    # Disable auto `pip install` of guessed deps (tests / air-gapped envs).
    disable_dep_install: bool = False
    # Directory prepended to every sandbox process's PYTHONPATH so the
    # sitecustomize shim (display patches + numpy→XLA reroute; reference
    # executor/sitecustomize.py:1-31) loads. Defaults to the shim shipped in
    # this package; set to "none" (or "") to disable — the env surface drops
    # empty values (env_ignore_empty), so APP_SHIM_DIR=none is the way to
    # disable it on a deployment.
    shim_dir: str | None = None

    def resolved_execution_hard_cap_s(self) -> float:
        """The stuck-execution watchdog cap: explicit when set, otherwise the
        sum of the sandbox execution bound and the data-plane client timeout
        — anything still in flight past that is wedged, not slow."""
        if self.execution_hard_cap_s is not None:
            return self.execution_hard_cap_s
        return self.execution_timeout_s + self.executor_http_timeout_s

    def redacted_dump(self) -> dict[str, Any]:
        """``model_dump()`` safe to serve from ``GET /v1/debug/bundle``:
        secret-shaped fields (TLS material, anything named like a
        credential) come back as ``"<redacted>"``, and bytes never leak
        even if a new secret field forgets the naming convention."""
        markers = ("cert", "key", "token", "secret", "password")
        out: dict[str, Any] = {}
        for name, value in self.model_dump().items():
            if value and (
                isinstance(value, bytes)
                or any(marker in name for marker in markers)
            ):
                out[name] = "<redacted>"
            else:
                out[name] = value
        return out

    def resolved_shim_dir(self) -> str | None:
        if self.shim_dir is not None:
            disabled = self.shim_dir.strip().lower() in ("", "none", "off", "disabled")
            return None if disabled else self.shim_dir
        from pathlib import Path

        return str(Path(__file__).resolve().parent / "runtime" / "shim")

    logging_config: dict[str, Any] = Field(default_factory=_default_logging_config)

    def resolved_logging_config(self) -> dict[str, Any]:
        """``logging_config`` with ``log_format`` applied: json mode swaps
        the ``default`` formatter for the structured JsonLogFormatter.
        A deployment that injected its own APP_LOGGING_CONFIG without a
        ``default`` formatter is left untouched."""
        import copy

        cfg = copy.deepcopy(self.logging_config)
        if self.log_format == "json" and "default" in cfg.get("formatters", {}):
            cfg["formatters"]["default"] = {
                "()": "bee_code_interpreter_tpu.observability.logging.JsonLogFormatter",
            }
        return cfg

    @classmethod
    def from_env(cls, env: dict[str, str] | None = None, prefix: str = "APP_") -> "Config":
        env = os.environ if env is None else env
        kwargs: dict[str, Any] = {}
        for name, field in cls.model_fields.items():
            raw = env.get(prefix + name.upper())
            if raw is None or raw == "":  # env_ignore_empty semantics (reference config.py:19)
                continue
            ann = str(field.annotation)
            if "dict" in ann or "list" in ann:
                kwargs[name] = json.loads(raw)
            elif "bytes" in ann:
                kwargs[name] = raw.encode()
            elif field.annotation is bool or "bool" in ann:
                kwargs[name] = raw.lower() in ("1", "true", "yes", "on")
            else:
                kwargs[name] = raw
        return cls(**kwargs)
