"""Leased interactive sessions over the warm sandbox pool (docs/sessions.md).

The stateless path pays a full workspace restore + snapshot round-trip per
execution; a *session* amortizes that across a conversation: the client
acquires one warm sandbox (``POST /v1/sessions``), runs N executions against
it (restore skipped — state lives in the sandbox — and snapshot deferred to
explicit checkpoints), and releases it. The interpreter becomes a REPL
surface for agents.

Guarantees the :class:`SessionManager` owns:

- **Bounded leases.** ``APP_SESSION_MAX`` caps concurrent leases (each one
  pins a warm sandbox the stateless pool can't use); ``APP_SESSION_TTL_S``
  bounds total lease lifetime and ``APP_SESSION_IDLE_S`` the gap between
  executions. A background sweep expires violators; expiry while an execute
  is in flight is deferred to the next sweep (the execute itself is bounded
  by the edge deadline and the supervisor's hard cap).
- **Drain integration.** A draining service takes no new leases (the edges'
  drain gate answers 503/UNAVAILABLE before the manager is reached) and the
  sweep expires existing leases with ``reason="drain"`` so teardown never
  waits on an idle REPL.
- **Supervisor integration.** A leased sandbox is out of the pool queue, so
  the idle reaper never probes it, and it is in the inflight registry only
  WHILE an execute runs — healthy-but-idle is owned, not stuck; a wedged
  leased execute is still watchdog-killed.
- **Checkpoint/rollback.** A checkpoint snapshots the live workspace's
  tracked files through the content-addressed ``Storage`` and returns an id;
  rollback restores any prior checkpoint (best-effort deleting files created
  since). Checkpoint file maps are plain ``{path: object_id}`` — a client
  can feed one to the stateless ``/v1/execute`` too.
- **Accounting.** Fleet journal events ``leased`` (with the owner session
  id) and ``lease_expired``/``released``/``reaped`` on end; metrics
  ``bci_session_active``, ``bci_session_lease_seconds``,
  ``bci_session_expirations_total{reason}``; a ``session`` attribute on the
  request's root trace span.
"""

from __future__ import annotations

import asyncio
import logging
import secrets
import time
from dataclasses import dataclass, field
from typing import Callable

from bee_code_interpreter_tpu.observability import collect_transfer, unwrap_executor
from bee_code_interpreter_tpu.resilience import Deadline, SandboxTransientError
from bee_code_interpreter_tpu.sessions.lease import LeaseOutcome, build_lease
from bee_code_interpreter_tpu.tenancy.context import current_tenant_context
from bee_code_interpreter_tpu.utils.validation import Hash

logger = logging.getLogger(__name__)


class SessionError(Exception):
    """Base class for session-API faults the edges map to statuses."""


class SessionNotFound(SessionError):
    """Unknown, expired, or already-released session id (HTTP 404)."""


class SessionLimitExceeded(SessionError):
    """The ``APP_SESSION_MAX`` lease cap — or a tenant's own ``sessions``
    cap (docs/tenancy.md) — is reached (HTTP 429)."""

    def __init__(
        self,
        limit: int,
        retry_after_s: float = 1.0,
        tenant: str | None = None,
    ) -> None:
        scope = f"tenant {tenant!r} " if tenant is not None else ""
        super().__init__(
            f"{scope}session limit reached ({limit} active leases)"
        )
        self.retry_after_s = retry_after_s
        self.tenant = tenant


class CheckpointNotFound(SessionError):
    """Unknown checkpoint id for this session (HTTP 404)."""


class InvalidSessionRequest(SessionError):
    """Malformed lease parameters (HTTP 422 / gRPC INVALID_ARGUMENT).

    The HTTP edge's pydantic model rejects these before the manager is
    reached; the gRPC JSON-bytes edge has no generated message to validate
    with, so the manager is the backstop — and it must reject BEFORE any
    sandbox is checked out."""


@dataclass
class Checkpoint:
    checkpoint_id: str
    files: dict[str, Hash]
    created_unix: float


@dataclass
class Session:
    """One leased sandbox + its client-visible state."""

    session_id: str
    lease: object  # sessions.lease.RemoteLease | LocalLease
    ttl_s: float
    idle_s: float
    created_mono: float
    created_unix: float
    last_used_mono: float
    executions: int = 0
    # `tenant` is the bounded-cardinality label (observability); the cap
    # is enforced on `tenant_id`, the RESOLVED tenant — unknown ids all
    # share the default tenant's quota, they don't each get a fresh one.
    tenant: str | None = None
    tenant_id: str | None = None
    checkpoints: dict[str, Checkpoint] = field(default_factory=dict)
    closed: bool = False
    close_reason: str | None = None
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)

    @property
    def expires_unix(self) -> float:
        return self.created_unix + self.ttl_s

    def to_dict(self, now_mono: float) -> dict:
        return {
            "session_id": self.session_id,
            "sandbox": self.lease.name,
            "created_unix": self.created_unix,
            "expires_at": self.expires_unix,
            "ttl_s": self.ttl_s,
            "idle_timeout_s": self.idle_s,
            "age_s": now_mono - self.created_mono,
            "idle_s": now_mono - self.last_used_mono,
            "executions": self.executions,
            "tenant": self.tenant,
            "checkpoints": sorted(self.checkpoints),
            "tracked_files": len(self.lease.tracked_paths),
        }


class SessionManager:
    """Owns every lease in the service. One per process, shared by both API
    edges (``ApplicationContext.sessions``) — the transports can never
    disagree about which sessions exist."""

    def __init__(
        self,
        executor,
        storage,
        *,
        max_sessions: int = 16,
        ttl_s: float = 900.0,
        idle_s: float = 120.0,
        sweep_interval_s: float = 1.0,
        drain_grace_s: float = 0.0,
        retry_after_s: float = 1.0,
        metrics=None,
        drain=None,
        recorder=None,  # observability.FlightRecorder for lifecycle events
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        # The lease works against the raw pool backend: the resilience
        # fronts (retry/replay/hedge) wrap single-shot executes and are
        # deliberately NOT applied to leased ones — replaying onto a fresh
        # sandbox would silently discard the session state the client is
        # paying to keep.
        self._backend = unwrap_executor(executor)
        self._storage = storage
        self._max_sessions = max_sessions
        self._ttl_s = ttl_s
        self._idle_s = idle_s
        self._sweep_interval_s = max(0.05, sweep_interval_s)
        # Lease-handoff window (docs/fleet.md): a fleet router needs time
        # after drain begins to checkpoint each live lease and re-lease it
        # on another replica; the sweep only force-expires leases
        # (reason="drain") once this grace has elapsed. 0 = original
        # behavior, first sweep reclaims everything.
        self._drain_grace_s = drain_grace_s
        self._drain_seen_mono: float | None = None
        self._retry_after_s = retry_after_s
        self._drain = drain
        self._recorder = recorder
        self._clock = clock
        self._sessions: dict[str, Session] = {}
        # Creates in flight between the cap check and registration: the
        # checkout awaits, so the cap must be check-AND-reserve, not
        # check-then-act, or a burst of concurrent creates blows past it.
        # The per-tenant reservation (docs/tenancy.md) works the same way.
        self._creating = 0
        self._creating_by_tenant: dict[str, int] = {}
        self._task: asyncio.Task | None = None
        self.expired_total: dict[str, int] = {}
        self._lease_seconds = None
        self._expirations_total = None
        if metrics is not None:
            metrics.gauge(
                "bci_session_active",
                "Session leases currently holding a warm sandbox",
                lambda: len(self._sessions),
            )
            self._lease_seconds = metrics.histogram(
                "bci_session_lease_seconds",
                "Session lease duration, acquire to end",
            )
            self._expirations_total = metrics.counter(
                "bci_session_expirations_total",
                "Session leases ended, by reason (ttl/idle/drain/shutdown/"
                "released/sandbox_died)",
            )

    # ------------------------------------------------------------- lifecycle

    @property
    def active_count(self) -> int:
        return len(self._sessions)

    def start(self) -> asyncio.Task:
        """Start the background expiry sweep (requires a running loop);
        idempotent."""
        if self._task is not None and not self._task.done():
            return self._task
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self._task

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while True:
            try:
                await asyncio.sleep(self._sweep_interval_s)
                await self.sweep_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # One bad sweep must not end lease expiry for the process.
                logger.exception("Session expiry sweep failed")

    # ------------------------------------------------------------------- api

    def get(self, session_id: str) -> Session:
        session = self._sessions.get(session_id)
        if session is None or session.closed:
            raise SessionNotFound(f"unknown or expired session {session_id!r}")
        return session

    @staticmethod
    def _clamped_bound(value, cap: float, what: str) -> float:
        """A request may shorten a lease bound, never extend it — and a
        malformed value must be rejected BEFORE a sandbox is checked out
        (a post-checkout TypeError would leak the lease forever)."""
        if value is None:
            return cap
        try:
            bound = float(value)
        except (TypeError, ValueError):
            raise InvalidSessionRequest(f"{what} must be a number") from None
        if bound <= 0:
            raise InvalidSessionRequest(f"{what} must be > 0")
        return min(bound, cap)

    async def create(
        self,
        files: dict[str, Hash] | None = None,
        ttl_s: float | None = None,
        idle_s: float | None = None,
        deadline: Deadline | None = None,
    ) -> Session:
        """Acquire one warm sandbox under a lease. A request may shorten the
        TTL / idle bounds, never extend them past the configured caps."""
        ttl = self._clamped_bound(ttl_s, self._ttl_s, "ttl_s")
        idle = self._clamped_bound(idle_s, self._idle_s, "idle_s")
        if files and (
            not isinstance(files, dict)
            or any(
                not isinstance(k, str) or not isinstance(v, str)
                for k, v in files.items()
            )
        ):
            raise InvalidSessionRequest(
                "files must be a {path: object id} object"
            )
        # Reserve the cap slot synchronously: the checkout below awaits, and
        # two concurrent creates racing one free slot must not both win.
        if len(self._sessions) + self._creating >= self._max_sessions:
            raise SessionLimitExceeded(
                self._max_sessions, retry_after_s=self._retry_after_s
            )
        # Per-tenant cap (docs/tenancy.md): each lease pins a warm sandbox,
        # so a tenant's `sessions` quota bounds how much of the fleet THAT
        # tenant can hold — same check-and-reserve discipline as the global
        # cap, so a burst of one tenant's creates cannot race past it.
        tctx = current_tenant_context()
        tenant_label = tctx.label if tctx is not None else None
        tenant_id = tctx.tenant.id if tctx is not None else None
        tenant_cap = (
            tctx.tenant.max_sessions if tctx is not None else None
        )
        if tenant_cap is not None:
            # Count by the RESOLVED tenant, not the label: spoofed unknown
            # ids all share the default tenant's allotment.
            held = sum(
                1
                for s in self._sessions.values()
                if s.tenant_id == tenant_id
            ) + self._creating_by_tenant.get(tenant_id, 0)
            if held >= tenant_cap:
                raise SessionLimitExceeded(
                    tenant_cap,
                    retry_after_s=self._retry_after_s,
                    tenant=tenant_id,
                )
        self._creating += 1
        if tenant_id is not None:
            self._creating_by_tenant[tenant_id] = (
                self._creating_by_tenant.get(tenant_id, 0) + 1
            )
        try:
            handle = await self._backend.checkout_for_lease(deadline=deadline)
            session_id = f"sess-{secrets.token_hex(8)}"
            lease = build_lease(self._backend, handle, self._storage)
            now = self._clock()
            session = Session(
                session_id=session_id,
                lease=lease,
                ttl_s=ttl,
                idle_s=idle,
                created_mono=now,
                created_unix=time.time(),
                last_used_mono=now,
                tenant=tenant_label,
                tenant_id=tenant_id,
            )
            self._journal("leased", session, reason="acquired")
            try:
                for path, object_id in (files or {}).items():
                    await lease.upload(path, object_id, deadline=deadline)
            except BaseException:
                # The initial restore failed (bad object id, dead sandbox,
                # deadline): the lease must not leak.
                self._end_lease(session, "reaped", "restore_failed", "sandbox_died")
                raise
            self._sessions[session_id] = session
        finally:
            self._creating -= 1
            if tenant_id is not None:
                remaining = self._creating_by_tenant.get(tenant_id, 1) - 1
                if remaining > 0:
                    self._creating_by_tenant[tenant_id] = remaining
                else:
                    self._creating_by_tenant.pop(tenant_id, None)
        self._emit("created", session)
        logger.info(
            "Session %s leased sandbox %s (ttl=%.0fs idle=%.0fs)",
            session_id,
            lease.name,
            session.ttl_s,
            session.idle_s,
        )
        return session

    async def execute(
        self,
        session_id: str,
        source_code: str,
        files: dict[str, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        deadline: Deadline | None = None,
        on_event=None,  # async (kind, text) -> None enables streaming
    ) -> tuple[Session, LeaseOutcome]:
        """One execution inside the lease. Serialized per session (a REPL is
        a conversation, not a fan-out); restore is skipped and snapshot
        deferred — new ``files`` the client sends are uploaded as deltas."""
        session = self.get(session_id)
        async with session.lock:
            if session.closed:  # expired while we waited for the lock
                raise SessionNotFound(
                    f"session {session_id!r} expired ({session.close_reason})"
                )
            session.last_used_mono = self._clock()
            lease = session.lease
            try:
                with collect_transfer() as transfer:
                    for path, object_id in (files or {}).items():
                        await lease.upload(path, object_id, deadline=deadline)
                    self._journal("executing", session)
                    outcome = await lease.execute(
                        source_code,
                        env or {},
                        timeout_s,
                        deadline=deadline,
                        on_event=on_event,
                    )
            except SandboxTransientError as e:
                # The sandbox died (or was watchdog-killed) under the lease:
                # its state is gone, so the session is over. No transparent
                # replay — a fresh sandbox would not BE this session.
                self._end_lease(
                    session,
                    "reaped",
                    getattr(e, "reap_reason", "died_mid_lease"),
                    "sandbox_died",
                    detail=str(e)[:200],
                )
                raise
            except asyncio.CancelledError:
                # Client vanished (or the edge deadline fired) mid-execute:
                # the cancelled data-plane call killed the in-flight run, but
                # the sandbox server — and the session state — survive. The
                # lease stays open; if the client never comes back, the
                # TTL/idle sweep reaps it (chaos scenario 10 asserts this).
                session.last_used_mono = self._clock()
                self._journal("leased", session)
                raise
            session.executions += 1
            session.last_used_mono = self._clock()
            if outcome.usage is not None:
                outcome.usage.update(transfer.as_dict())
            # Back to idle-in-lease: the fleet view shows an owned, idle
            # sandbox (not an executing one) between REPL turns.
            self._journal("leased", session)
            return session, outcome

    async def checkpoint(
        self, session_id: str, deadline: Deadline | None = None
    ) -> tuple[Session, Checkpoint]:
        """Snapshot the live workspace's tracked files through storage; the
        deferred-snapshot bill is paid here, once, instead of per execute."""
        session = self.get(session_id)
        async with session.lock:
            if session.closed:
                raise SessionNotFound(f"session {session_id!r} expired")
            session.last_used_mono = self._clock()
            files = await session.lease.snapshot(
                sorted(session.lease.tracked_paths), deadline=deadline
            )
            checkpoint = Checkpoint(
                checkpoint_id=f"ckpt-{len(session.checkpoints) + 1}-{secrets.token_hex(4)}",
                files=files,
                created_unix=time.time(),
            )
            session.checkpoints[checkpoint.checkpoint_id] = checkpoint
            session.last_used_mono = self._clock()
            return session, checkpoint

    async def rollback(
        self,
        session_id: str,
        checkpoint_id: str,
        deadline: Deadline | None = None,
    ) -> tuple[Session, Checkpoint]:
        """Restore a prior checkpoint into the live workspace: checkpoint
        files re-uploaded, files created since best-effort deleted."""
        session = self.get(session_id)
        async with session.lock:
            if session.closed:
                raise SessionNotFound(f"session {session_id!r} expired")
            checkpoint = session.checkpoints.get(checkpoint_id)
            if checkpoint is None:
                raise CheckpointNotFound(
                    f"session {session_id!r} has no checkpoint {checkpoint_id!r}"
                )
            session.last_used_mono = self._clock()
            strays = session.lease.tracked_paths - set(checkpoint.files)
            await session.lease.restore(
                checkpoint.files, sorted(strays), deadline=deadline
            )
            session.last_used_mono = self._clock()
            return session, checkpoint

    async def release(self, session_id: str) -> Session:
        """Clean client release (``DELETE /v1/sessions/{id}``)."""
        session = self.get(session_id)
        async with session.lock:
            if not session.closed:
                self._end_lease(session, "released", "lease_released", "released")
        return session

    # ---------------------------------------------------------------- expiry

    async def sweep_once(self) -> int:
        """Expire leases past their TTL / idle bound (or all of them while
        draining). Sessions with an execute in flight are skipped — the run
        is deadline- and watchdog-bounded; the next sweep gets them."""
        draining = self._drain is not None and self._drain.draining
        now = self._clock()
        if not draining:
            self._drain_seen_mono = None
        elif self._drain_seen_mono is None:
            self._drain_seen_mono = now
        # During the handoff grace, drain does not force-expire leases (the
        # router is evacuating them); TTL/idle still apply as usual.
        drain_expire = draining and (
            now - self._drain_seen_mono >= self._drain_grace_s
        )
        expired = 0
        for session in list(self._sessions.values()):
            if session.closed or session.lock.locked():
                continue
            if drain_expire:
                reason = "drain"
            elif now - session.created_mono >= session.ttl_s:
                reason = "ttl"
            elif now - session.last_used_mono >= session.idle_s:
                reason = "idle"
            else:
                continue
            logger.info(
                "Expiring session %s (%s) after %d execution(s)",
                session.session_id,
                reason,
                session.executions,
            )
            self._end_lease(session, "lease_expired", reason, reason)
            expired += 1
        return expired

    async def close_all(self, reason: str = "shutdown") -> int:
        """Deterministic teardown (``ctx.aclose``): every lease ends NOW."""
        closed = 0
        for session in list(self._sessions.values()):
            if not session.closed:
                self._end_lease(session, "lease_expired", reason, reason)
                closed += 1
        return closed

    # ------------------------------------------------------------- internals

    def _emit(self, op: str, session: Session, reason: str | None = None) -> None:
        """One wide event per lease lifecycle op (docs/observability.md
        "Flight recorder"): sweep-driven expiries have no request to ride
        on, so the manager is their emission point — and create/release get
        the same treatment so the session's whole life reads from ONE
        filterable stream (``/v1/events?session=...``)."""
        if self._recorder is None:
            return
        self._recorder.record(
            {
                "kind": "session",
                "name": f"session.{op}",
                "outcome": reason or op,
                "session": session.session_id,
                "sandbox": session.lease.name,
                "executions": session.executions,
                "duration_ms": (self._clock() - session.created_mono) * 1000.0,
            }
        )

    def _journal(self, state: str, session: Session, reason: str | None = None) -> None:
        journal = getattr(self._backend, "journal", None)
        if journal is None:
            return
        attrs: dict = {"session": session.session_id}
        if session.tenant is not None:
            attrs["tenant"] = session.tenant
        journal.record(session.lease.name, state, reason=reason, **attrs)

    def _end_lease(
        self,
        session: Session,
        state: str,
        journal_reason: str,
        metric_reason: str,
        detail: str | None = None,
    ) -> None:
        """The ONE spelling for a lease's end: journal terminal event with
        the real reason, sandbox torn down via the backend (which kicks a
        refill), duration + reason accounted in metrics."""
        if session.closed:
            return
        session.closed = True
        session.close_reason = metric_reason
        self._sessions.pop(session.session_id, None)
        self._backend.release_lease(
            session.lease.handle, state=state, reason=journal_reason, detail=detail
        )
        if self._lease_seconds is not None:
            self._lease_seconds.observe(self._clock() - session.created_mono)
        if self._expirations_total is not None:
            self._expirations_total.inc(reason=metric_reason)
        self.expired_total[metric_reason] = (
            self.expired_total.get(metric_reason, 0) + 1
        )
        self._emit("ended", session, reason=metric_reason)

    def tenant_counts(self) -> dict[str, int]:
        """Active leases per tenant label (``GET /v1/tenants``)."""
        counts: dict[str, int] = {}
        for session in self._sessions.values():
            if session.tenant is not None:
                counts[session.tenant] = counts.get(session.tenant, 0) + 1
        return counts

    def snapshot(self) -> dict:
        """Operator view for ``GET /v1/sessions`` and the debug bundle."""
        now = self._clock()
        snap = {
            "sessions": [s.to_dict(now) for s in self._sessions.values()],
            "active": len(self._sessions),
            "max": self._max_sessions,
            "ended_by_reason": dict(self.expired_total),
        }
        tenants = self.tenant_counts()
        if tenants:
            snap["by_tenant"] = tenants
        return snap
