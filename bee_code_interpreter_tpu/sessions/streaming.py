"""Transport-neutral streaming pump (docs/sessions.md "Streaming").

Both streaming edges — the HTTP SSE response and the gRPC ``ExecuteStream``
server stream — consume the same event sequence: zero or more output chunks

    {"stream": "stdout"|"stderr", "data": "<text>"}

closed by EXACTLY one terminal event,

    {"event": "result", "result": <backend Result | LeaseOutcome>}   or
    {"event": "error", "error": <exception>}

:func:`streamed_events` adapts the backends' callback-shaped
``execute_stream(..., on_event=...)`` API into that async-iterator shape,
and guarantees the underlying execution is cancelled if the consumer
abandons the iterator (client vanished mid-stream) — the sandbox side then
unwinds through its own teardown, so a dead client never leaves a run
dangling against a workspace nothing will read.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Awaitable, Callable


async def streamed_events(
    run: Callable[[Callable], Awaitable],
) -> AsyncIterator[dict]:
    """Drive ``run(on_event)`` (an awaitable returning the final result)
    while yielding its chunk events as they arrive, then one terminal
    event. The runner is cancelled if the consumer stops iterating."""
    queue: asyncio.Queue[dict] = asyncio.Queue()

    async def on_event(kind: str, text: str) -> None:
        await queue.put({"stream": kind, "data": text})

    async def runner() -> None:
        try:
            result = await run(on_event)
        except BaseException as e:  # terminal errors are in-band events
            await queue.put({"event": "error", "error": e})
        else:
            await queue.put({"event": "result", "result": result})

    task = asyncio.ensure_future(runner())
    try:
        while True:
            item = await queue.get()
            yield item
            if "event" in item:
                return
    finally:
        if not task.done():
            task.cancel()
        try:
            await task
        except (asyncio.CancelledError, Exception):
            # The consumer is gone; the cancellation (or the error already
            # reported in-band) has nowhere useful to propagate.
            pass
