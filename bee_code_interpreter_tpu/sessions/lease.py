"""Leased-sandbox execution: the data-plane half of a session.

A session holds ONE warm sandbox across N executions (docs/sessions.md).
This module adapts the two sandbox shapes behind a uniform lease API the
:class:`~bee_code_interpreter_tpu.sessions.manager.SessionManager` drives:

- :class:`RemoteLease` — a pool sandbox (Kubernetes pod group or native
  server process) addressed over the executor HTTP wire. Executes skip the
  workspace restore (state lives in the sandbox) and defer the snapshot:
  each run reports *changed paths* only; bytes move at checkpoint time.
  Gang semantics are preserved: uploads go to every worker, executes run
  SPMD on all of them, each changed path is owned by the first worker that
  reported it (worker 0 wins collisions — process-0-owns-I/O).
- :class:`LocalLease` — the in-process backend's lease: a persistent
  workspace + ``ExecutorCore`` living for the lease's lifetime.

Either way the lease tracks the set of logical paths known to exist in the
workspace (initial restore ∪ changed paths reported by executes); that set
is what a checkpoint snapshots and what a rollback prunes against.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from bee_code_interpreter_tpu.observability import merge_worker_usage
from bee_code_interpreter_tpu.resilience import Deadline, SandboxFatalError
from bee_code_interpreter_tpu.services.code_executor import LeaseHandle
from bee_code_interpreter_tpu.utils.validation import Hash


@dataclass
class LeaseOutcome:
    """One execution inside a lease. ``changed_paths`` replaces the
    stateless path's ``files`` map: the snapshot is deferred, so there are
    no object ids until the client checkpoints."""

    stdout: str
    stderr: str
    exit_code: int
    changed_paths: list[str] = field(default_factory=list)
    usage: dict | None = None


class RemoteLease:
    """A pool sandbox held for a session, driven over the executor HTTP
    wire through the owning backend's driver methods."""

    def __init__(self, backend, handle: LeaseHandle) -> None:
        self._backend = backend
        self.handle = handle
        self.name = handle.name
        self._addrs = handle.addrs
        # logical path -> the worker addr that wrote it (uploads exist on
        # every worker; worker 0 is the canonical owner).
        self._path_owner: dict[str, str] = {}

    @property
    def tracked_paths(self) -> set[str]:
        return set(self._path_owner)

    async def upload(
        self, path: str, object_id: Hash, deadline: Deadline | None = None
    ) -> None:
        await asyncio.gather(
            *(
                self._backend._upload_file(addr, path, object_id, deadline=deadline)
                for addr in self._addrs
            )
        )
        self._path_owner.setdefault(path, self._addrs[0])

    async def execute(
        self,
        source_code: str,
        env: dict[str, str],
        timeout_s: float | None,
        deadline: Deadline | None = None,
        on_event=None,  # async (kind, text) -> None enables streaming
    ) -> LeaseOutcome:
        backend = self._backend
        timeout = backend._effective_timeout(timeout_s)
        # Tracked while executing so the supervisor watchdog still kills a
        # WEDGED leased execute — only the idle-between-executes state is
        # exempt from the watchdog, never a run in flight.
        with backend.inflight.track(self.name, kill=self.handle.kill):
            if on_event is not None:
                responses = list(
                    await asyncio.gather(
                        backend._post_execute_stream(
                            self._addrs[0],
                            source_code,
                            env,
                            timeout,
                            on_event=on_event,
                            deadline=deadline,
                        ),
                        *(
                            backend._post_execute(
                                addr, source_code, env, timeout, deadline=deadline
                            )
                            for addr in self._addrs[1:]
                        ),
                    )
                )
            else:
                responses = list(
                    await asyncio.gather(
                        *(
                            backend._post_execute(
                                addr, source_code, env, timeout, deadline=deadline
                            )
                            for addr in self._addrs
                        )
                    )
                )
        primary = responses[0]
        exit_code = next(
            (r["exit_code"] for r in responses if r["exit_code"] != 0), 0
        )
        changed: dict[str, None] = {}
        for addr, response in zip(self._addrs, responses):
            for path in response["files"]:
                changed.setdefault(path)
                self._path_owner.setdefault(path, addr)
        usage = merge_worker_usage([r.get("usage") for r in responses])
        return LeaseOutcome(
            stdout=primary["stdout"],
            stderr=primary["stderr"],
            exit_code=exit_code,
            changed_paths=list(changed),
            usage=usage,
        )

    async def snapshot(
        self, paths, deadline: Deadline | None = None
    ) -> dict[str, Hash]:
        """Download ``paths`` from their owners into content-addressed
        storage (the deferred snapshot, paid at checkpoint time). A path the
        workspace no longer has (user code deleted it) is dropped from the
        result AND from the tracked set."""

        async def grab(path: str):
            addr = self._path_owner.get(path, self._addrs[0])
            try:
                return path, await self._backend._download_file(
                    addr, path, deadline=deadline
                )
            except SandboxFatalError:
                return path, None  # deleted since it was last reported

        out: dict[str, Hash] = {}
        for path, object_id in await asyncio.gather(*(grab(p) for p in paths)):
            if object_id is None:
                self._path_owner.pop(path, None)
            else:
                out[path] = object_id
        return out

    async def restore(
        self,
        files: dict[str, Hash],
        delete_paths,
        deadline: Deadline | None = None,
    ) -> None:
        """Rollback: put every checkpoint file back on every worker and
        best-effort delete the strays created since (executors without the
        DELETE route keep them; docs/sessions.md spells the caveat)."""
        await asyncio.gather(
            *(
                self._backend._upload_file(addr, path, object_id, deadline=deadline)
                for addr in self._addrs
                for path, object_id in files.items()
            )
        )
        await asyncio.gather(
            *(
                self._backend._delete_file(addr, path, deadline=deadline)
                for addr in self._addrs
                for path in delete_paths
            )
        )
        self._path_owner = {path: self._addrs[0] for path in files}


class LocalLease:
    """The in-process backend's lease: a persistent workspace + core; the
    same API as :class:`RemoteLease` with direct file I/O instead of the
    HTTP wire."""

    def __init__(self, backend, handle: LeaseHandle, storage) -> None:
        self._backend = backend
        self.handle = handle
        self.name = handle.name
        self._core = handle.core
        self._storage = storage
        self._tracked: set[str] = set()

    @property
    def tracked_paths(self) -> set[str]:
        return set(self._tracked)

    async def upload(
        self, path: str, object_id: Hash, deadline: Deadline | None = None
    ) -> None:
        real = self._core.resolve(path)
        real.parent.mkdir(parents=True, exist_ok=True)
        with open(real, "wb") as f:
            async with self._storage.reader(object_id) as reader:
                async for chunk in reader:
                    f.write(chunk)
        self._tracked.add(path)

    async def execute(
        self,
        source_code: str,
        env: dict[str, str],
        timeout_s: float | None,
        deadline: Deadline | None = None,
        on_event=None,
    ) -> LeaseOutcome:
        timeout = self._backend._clamp_timeout(timeout_s)
        if deadline is not None:
            deadline.check("leased execute")
            timeout = deadline.clamp(
                timeout or self._core.default_timeout_s
            )
        if on_event is None:
            outcome = await self._core.execute(
                source_code, env=env, timeout_s=timeout
            )
        else:
            outcome = None
            gen = self._core.execute_stream(
                source_code, env=env, timeout_s=timeout
            )
            try:
                async for kind, payload in gen:
                    if kind == "end":
                        outcome = payload
                    else:
                        await on_event(kind, payload)
            finally:
                await gen.aclose()
        self._tracked.update(outcome.files)
        return LeaseOutcome(
            stdout=outcome.stdout,
            stderr=outcome.stderr,
            exit_code=outcome.exit_code,
            changed_paths=list(outcome.files),
            usage=outcome.usage,
        )

    async def snapshot(
        self, paths, deadline: Deadline | None = None
    ) -> dict[str, Hash]:
        out: dict[str, Hash] = {}
        for path in paths:
            real = self._core.resolve(path)
            if not real.is_file():
                self._tracked.discard(path)
                continue
            async with self._storage.writer() as writer:
                with open(real, "rb") as f:
                    while chunk := f.read(1 << 20):
                        await writer.write(chunk)
            out[path] = writer.hash
        return out

    async def restore(
        self,
        files: dict[str, Hash],
        delete_paths,
        deadline: Deadline | None = None,
    ) -> None:
        for path in delete_paths:
            real = self._core.resolve(path)
            if real.is_file():
                real.unlink(missing_ok=True)
        for path, object_id in files.items():
            await self.upload(path, object_id, deadline=deadline)
        self._tracked = set(files)


def build_lease(backend, handle: LeaseHandle, storage):
    """The right lease flavor for what the backend checked out: an
    in-process core (local backend) or data-plane addresses (pool
    backends)."""
    if handle.core is not None:
        return LocalLease(backend, handle, storage)
    return RemoteLease(backend, handle)
