"""Sessions: leased sandboxes, checkpoint/rollback, and output streaming.

The third workload class next to one-shot execute and custom tools
(ROADMAP item 3, docs/sessions.md): a client leases one warm sandbox for a
conversation of N executions (restore skipped, snapshot deferred to
explicit checkpoints), can roll the workspace back to any checkpoint, and
can stream stdout/stderr as the sandbox produces them — on both the
sessionful and the stateless path, over both transports.

Layered like ``resilience/`` and ``observability/``: primitives here
(manager, lease drivers, the streaming pump), wiring at the edges (api/)
and in the backends (services/ checkout/lease hooks, runtime/ chunked
read loop).
"""

from bee_code_interpreter_tpu.sessions.lease import (
    LeaseOutcome,
    LocalLease,
    RemoteLease,
    build_lease,
)
from bee_code_interpreter_tpu.sessions.manager import (
    Checkpoint,
    CheckpointNotFound,
    InvalidSessionRequest,
    Session,
    SessionError,
    SessionLimitExceeded,
    SessionManager,
    SessionNotFound,
)
from bee_code_interpreter_tpu.sessions.streaming import streamed_events

__all__ = [
    "Checkpoint",
    "CheckpointNotFound",
    "InvalidSessionRequest",
    "LeaseOutcome",
    "LocalLease",
    "RemoteLease",
    "Session",
    "SessionError",
    "SessionLimitExceeded",
    "SessionManager",
    "SessionNotFound",
    "build_lease",
    "streamed_events",
]
