"""Content-addressed object storage for workspace file snapshots.

The reference stores files in a flat directory under *random* 64-hex ids despite
its docstring claiming sha256 addressing (reference: src/code_interpreter/services/
storage.py:34-90, the random id at :52). We implement what the docstring promised:
the object id IS the sha256 of the content, computed while streaming the write and
atomically published under that id on commit. This gives free dedup across
executions (identical workspace files snapshot to the same object) while keeping
the exact same API contract — clients treat ids as opaque ``Hash`` strings.

Fleet tier (docs/fleet.md): the byte persistence behind that contract is a
pluggable **backend seam**, because "where the bytes live" is exactly what
changes when one replica becomes N (the reference plans the same jump:
"shared volume/S3 in prod", its storage.py docstring):

- :class:`LocalDirectoryBackend` — the original flat directory, private to
  one replica.
- :class:`SharedDirectoryBackend` — the same layout on a *shared* mounted
  volume: commits fsync file and directory before/after the atomic rename
  (a network mount that loses the rename loses the snapshot), and the
  startup orphan sweep only reaps temp files old enough that no live
  replica can still be writing them.
- :class:`S3HttpBackend` — an S3-shaped HTTP object store
  (``PUT/GET/HEAD {endpoint}/{bucket}/{object_id}``), exercised in-repo
  against ``tests.fakes.FakeS3``. TTL cleanup belongs to bucket lifecycle
  rules, so :meth:`Storage.sweep` is an accounted no-op there.

Because ids are content hashes, an object written through ANY backend
instance is readable by any other instance pointed at the same root/bucket —
the property that makes snapshots replica-agnostic (the conformance suite in
``tests/test_storage_backends.py`` proves it per backend rather than
assuming it).

Async file I/O uses a worker thread via asyncio.to_thread per chunk, mirroring
the reference's anyio usage without the dependency on anyio.Path semantics.
"""

from __future__ import annotations

import asyncio
import hashlib
import logging
import os
import secrets
import time
from contextlib import asynccontextmanager
from pathlib import Path
from typing import AsyncIterator

from bee_code_interpreter_tpu.utils.validation import Hash

logger = logging.getLogger(__name__)


class ObjectReader:
    """Facade over a backend read handle; chunked async iteration."""

    def __init__(self, handle, chunk_size: int = 1 << 20) -> None:
        self._handle = handle
        self._chunk_size = chunk_size

    async def read(self, size: int = -1) -> bytes:
        return await self._handle.read(size)

    async def __aiter__(self) -> AsyncIterator[bytes]:
        while chunk := await self._handle.read(self._chunk_size):
            yield chunk

    async def _close(self) -> None:
        await self._handle.close()


class ObjectWriter:
    """Streams bytes to a backend staging area while hashing; the final id
    is the sha256 hex, published atomically on commit."""

    def __init__(self, handle) -> None:
        self._handle = handle
        self._hasher = hashlib.sha256()
        self.hash: Hash | None = None

    async def write(self, data: bytes) -> None:
        self._hasher.update(data)
        await self._handle.write(data)

    async def _finalize(self) -> None:
        self.hash = self._hasher.hexdigest()
        await self._handle.commit(self.hash)

    async def _abort(self) -> None:
        await self._handle.abort()


# --------------------------------------------------------------- fs backends


class _FsReadHandle:
    def __init__(self, path: Path) -> None:
        self._path = path
        self._file = None

    async def open(self) -> "_FsReadHandle":
        self._file = await asyncio.to_thread(open, self._path, "rb")
        return self

    async def read(self, size: int = -1) -> bytes:
        return await asyncio.to_thread(self._file.read, size)

    async def close(self) -> None:
        await asyncio.to_thread(self._file.close)


class _FsWriteHandle:
    def __init__(self, root: Path, durable: bool) -> None:
        self._root = root
        self._tmp_path = root / f".tmp-{secrets.token_hex(8)}"
        self._durable = durable
        self._file = None

    async def open(self) -> "_FsWriteHandle":
        self._file = await asyncio.to_thread(open, self._tmp_path, "wb")
        return self

    async def write(self, data: bytes) -> None:
        await asyncio.to_thread(self._file.write, data)

    async def commit(self, object_id: Hash) -> None:
        def _commit() -> None:
            if self._durable:
                # Shared mount: the bytes AND the rename must survive the
                # writer replica dying right after commit — another replica
                # may already be resolving this id.
                self._file.flush()
                os.fsync(self._file.fileno())
            self._file.close()
            # Content-addressed: identical content → same path; rename is
            # atomic and overwriting an identical object is a no-op.
            os.replace(self._tmp_path, self._root / object_id)
            if self._durable:
                fd = os.open(self._root, os.O_RDONLY)
                try:
                    os.fsync(fd)
                finally:
                    os.close(fd)

        await asyncio.to_thread(_commit)

    async def abort(self) -> None:
        def _abort() -> None:
            self._file.close()
            try:
                os.unlink(self._tmp_path)
            except FileNotFoundError:
                pass

        await asyncio.to_thread(_abort)


class LocalDirectoryBackend:
    """Flat-directory object store keyed by content hash — one replica's
    private store (the original ``Storage`` behavior)."""

    name = "local"
    _durable = False

    def __init__(
        self, root: str | os.PathLike, orphan_min_age_s: float = 0.0
    ) -> None:
        self.root = Path(root)
        self._orphan_min_age_s = orphan_min_age_s
        self.orphans_recovered: int | None = None  # set by the first sweep

    async def recover_orphans(self) -> int:
        """Startup sweep of orphaned writer temps: a crash mid-ObjectWriter
        leaks ``.tmp-*`` files forever in the flat object dir (nothing else
        ever touches them — the TTL sweep deliberately skips in-flight
        temps). Runs ONCE — kicked by the first ``start_write`` (or
        explicitly at boot), off-loop like every other directory walk here,
        counted and logged. Only temps that already existed when the sweep
        started are candidates (the cutoff is captured first), so a writer
        racing the sweep can never lose its fresh temp; the min-age gate
        additionally matters on shared roots, where a recent ``.tmp-*`` may
        be another live replica's upload. ``.tmp-sweep-`` guards belong to
        the TTL sweep's own crash recovery and are left for it."""
        if self.orphans_recovered is not None:
            return self.orphans_recovered
        self.orphans_recovered = 0  # claimed: concurrent writers skip
        cutoff = time.time() - self._orphan_min_age_s

        def _recover() -> int:
            if not self.root.is_dir():
                return 0
            removed = 0
            for entry in self.root.iterdir():
                name = entry.name
                if not name.startswith(".tmp-") or name.startswith(
                    ".tmp-sweep-"
                ):
                    continue
                try:
                    if entry.stat().st_mtime < cutoff:
                        entry.unlink()
                        removed += 1
                except OSError:
                    continue
            return removed

        self.orphans_recovered = await asyncio.to_thread(_recover)
        if self.orphans_recovered:
            logger.info(
                "Storage recovered %d orphaned temp file(s) in %s",
                self.orphans_recovered,
                self.root,
            )
        return self.orphans_recovered

    def _object_path(self, object_id: Hash) -> Path:
        # Hash pattern forbids "/" and ".." so a plain join cannot escape root.
        return self.root / object_id

    async def open_read(self, object_id: Hash) -> _FsReadHandle:
        return await _FsReadHandle(self._object_path(object_id)).open()

    async def start_write(self) -> _FsWriteHandle:
        if self.orphans_recovered is None:
            await self.recover_orphans()
        await asyncio.to_thread(self.root.mkdir, 0o777, True, True)
        return await _FsWriteHandle(self.root, self._durable).open()

    async def exists(self, object_id: Hash) -> bool:
        return await asyncio.to_thread(self._object_path(object_id).exists)

    async def touch(self, object_id: Hash) -> None:
        try:
            await asyncio.to_thread(os.utime, self._object_path(object_id))
        except OSError:
            pass

    def describe(self) -> dict:
        return {"backend": self.name, "root": str(self.root)}

    async def aclose(self) -> None:
        pass

    async def sweep(self, max_age_s: float) -> int:
        """Delete objects untouched for longer than ``max_age_s``; returns the
        count removed.

        The reference leaves cleanup to the operator ("temporary solution ...
        S3 TTL", its README.md:167); this makes the TTL a service feature for
        the flat-directory store. Objects age from last *use*: writes refresh
        mtime via os.replace (commit) and reads refresh it explicitly
        (``Storage.reader``), so anything an active session touches stays.

        Stale-unlink race closed with a per-object rename guard: the entry is
        atomically renamed aside, re-stat'ed, and renamed back if something
        refreshed it between the first stat and the rename. A concurrent
        identical-content write is unaffected either way (os.replace creates
        a fresh object under the public name). The one remaining race — a
        reader touching the object in the instant it is renamed aside — is
        surfaced to that reader as a missing object, the same outcome S3
        lifecycle rules produce.

        A crash between the rename-aside and its resolution would otherwise
        strand the object as ``.tmp-sweep-*`` forever (every future sweep
        skips ``.tmp-`` names), so each sweep first recovers orphaned guards:
        put fresh ones back under their public name, unlink expired ones.
        """

        def _sweep_sync() -> int:
            root = self.root
            if not root.is_dir():
                return 0
            cutoff = time.time() - max_age_s
            removed = 0
            for entry in root.iterdir():
                if not entry.name.startswith(".tmp-sweep-"):
                    continue
                public = root / entry.name.removeprefix(".tmp-sweep-")
                try:
                    if entry.stat().st_mtime >= cutoff:
                        # A live object a crashed sweep renamed aside. Restore
                        # no-clobber (link fails with EEXIST): a fresh write
                        # that recreated the public name is newer — prefer it.
                        try:
                            os.link(entry, public)
                        except FileExistsError:
                            pass
                        entry.unlink()
                    else:
                        entry.unlink()
                        removed += 1
                except OSError:
                    continue
            for entry in root.iterdir():
                try:
                    if entry.name.startswith(".tmp-"):
                        continue  # in-flight write
                    if entry.stat().st_mtime >= cutoff:
                        continue
                    guard = root / f".tmp-sweep-{entry.name}"
                    entry.rename(guard)
                except OSError:
                    # Missing (raced), a directory, permission-denied — skip
                    # this entry, keep sweeping the rest.
                    continue
                try:
                    if guard.stat().st_mtime >= cutoff:
                        # refreshed between stat and rename: put it back
                        guard.rename(entry)
                        continue
                    guard.unlink()
                    removed += 1
                except OSError:
                    continue
            return removed

        return await asyncio.to_thread(_sweep_sync)


class SharedDirectoryBackend(LocalDirectoryBackend):
    """The flat-directory layout on a volume MOUNTED INTO EVERY REPLICA
    (docs/fleet.md "Storage backends"): commits are fsync'd so a replica
    dying right after publishing a snapshot cannot strand the readers on
    other replicas, and the startup orphan sweep is age-gated (default 1h)
    because a ``.tmp-*`` in a shared root may be another live replica's
    in-flight upload, not a leak."""

    name = "shared"
    _durable = True

    def __init__(
        self, root: str | os.PathLike, orphan_min_age_s: float = 3600.0
    ) -> None:
        super().__init__(root, orphan_min_age_s=orphan_min_age_s)


# --------------------------------------------------------------- s3 backend


class _S3ReadHandle:
    """Whole-object buffer: snapshot objects are workspace files (bounded by
    the sandbox workspace), and the driver re-chunks uploads from ``read``
    calls anyway."""

    def __init__(self, body: bytes) -> None:
        self._body = memoryview(body)
        self._pos = 0

    async def read(self, size: int = -1) -> bytes:
        if size is None or size < 0:
            chunk = self._body[self._pos :]
            self._pos = len(self._body)
        else:
            chunk = self._body[self._pos : self._pos + size]
            self._pos += len(chunk)
        return bytes(chunk)

    async def close(self) -> None:
        self._pos = len(self._body)


class _S3WriteHandle:
    def __init__(self, backend: "S3HttpBackend") -> None:
        self._backend = backend
        self._chunks: list[bytes] = []

    async def write(self, data: bytes) -> None:
        self._chunks.append(bytes(data))

    async def commit(self, object_id: Hash) -> None:
        await self._backend._put(object_id, b"".join(self._chunks))
        self._chunks.clear()

    async def abort(self) -> None:
        self._chunks.clear()


class S3HttpBackend:
    """S3-shaped HTTP object store: ``PUT/GET/HEAD {endpoint}/{bucket}/{id}``.

    Deliberately speaks only the unauthenticated path-style subset every
    S3-compatible store (and the in-repo ``tests.fakes.FakeS3``) accepts —
    credentials/signing belong to the deployment's ambient auth (IRSA,
    sidecar proxy), exactly like the reference's "S3 in prod" plan. Missing
    objects surface as ``FileNotFoundError`` so every backend answers the
    same way."""

    name = "s3"

    def __init__(
        self,
        endpoint: str,
        bucket: str,
        timeout_s: float = 30.0,
        client=None,
    ) -> None:
        import httpx

        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket.strip("/")
        self._client = client or httpx.AsyncClient(timeout=timeout_s)
        self.orphans_recovered = 0  # no staging temps: uploads are one PUT
        self._sweep_noted = False

    async def recover_orphans(self) -> int:
        return 0  # uploads are a single PUT; nothing to strand

    def _url(self, object_id: Hash) -> str:
        return f"{self.endpoint}/{self.bucket}/{object_id}"

    async def _put(self, object_id: Hash, body: bytes) -> None:
        response = await self._client.put(self._url(object_id), content=body)
        if response.status_code >= 300:
            raise OSError(
                f"s3 put {object_id} failed: HTTP {response.status_code}"
            )

    async def open_read(self, object_id: Hash) -> _S3ReadHandle:
        response = await self._client.get(self._url(object_id))
        if response.status_code == 404:
            raise FileNotFoundError(f"no such object: {object_id}")
        if response.status_code >= 300:
            raise OSError(
                f"s3 get {object_id} failed: HTTP {response.status_code}"
            )
        return _S3ReadHandle(response.content)

    async def start_write(self) -> _S3WriteHandle:
        return _S3WriteHandle(self)

    async def exists(self, object_id: Hash) -> bool:
        response = await self._client.head(self._url(object_id))
        return response.status_code < 300

    async def touch(self, object_id: Hash) -> None:
        pass  # object age is the bucket's concern (lifecycle rules)

    async def sweep(self, max_age_s: float) -> int:
        if not self._sweep_noted:
            self._sweep_noted = True
            logger.info(
                "Storage TTL sweep is a no-op on the s3 backend; configure "
                "bucket lifecycle rules instead (docs/fleet.md)"
            )
        return 0

    def describe(self) -> dict:
        return {
            "backend": self.name,
            "endpoint": self.endpoint,
            "bucket": self.bucket,
        }

    async def aclose(self) -> None:
        await self._client.aclose()


# ------------------------------------------------------------------- facade


class Storage:
    """Content-addressed object store over a pluggable backend.

    API shape mirrors the reference (storage.py:44-90): async ``reader``/
    ``writer`` context managers plus whole-object ``read``/``write``/
    ``exists`` helpers. Default backend is the replica-private local
    directory; ``Storage.from_config`` picks by ``APP_STORAGE_BACKEND``.
    """

    def __init__(
        self,
        storage_path: str | os.PathLike | None = None,
        touch_on_read: bool = False,
        backend=None,
    ) -> None:
        if backend is None:
            if storage_path is None:
                raise ValueError("Storage needs a storage_path or a backend")
            backend = LocalDirectoryBackend(storage_path)
        self.backend = backend
        # Only pay the per-read touch when a TTL sweep actually ages objects
        # (ApplicationContext sets this from storage_max_age_s); reads are on
        # the warm-execute hot path.
        self._touch_on_read = touch_on_read

    @classmethod
    def from_config(cls, config) -> "Storage":
        """The composition-root construction (docs/fleet.md "Storage
        backends"): ``APP_STORAGE_BACKEND`` selects the seam, everything
        else keeps its existing meaning (``APP_FILE_STORAGE_PATH`` is the
        local/shared root; the TTL sweep opts reads into touch)."""
        kind = config.storage_backend
        if kind == "s3":
            if not config.storage_s3_endpoint:
                raise ValueError(
                    "APP_STORAGE_BACKEND=s3 requires APP_STORAGE_S3_ENDPOINT"
                )
            backend = S3HttpBackend(
                config.storage_s3_endpoint,
                config.storage_s3_bucket,
                timeout_s=config.storage_s3_timeout_s,
            )
        elif kind == "shared":
            backend = SharedDirectoryBackend(
                config.file_storage_path,
                orphan_min_age_s=config.storage_orphan_age_s,
            )
        else:
            backend = LocalDirectoryBackend(config.file_storage_path)
        return cls(
            touch_on_read=config.storage_max_age_s is not None,
            backend=backend,
        )

    @property
    def orphans_recovered(self) -> int | None:
        """Orphaned writer temps reaped by the backend's startup sweep
        (None until the sweep has run — first write, or
        :meth:`recover_orphans`)."""
        return self.backend.orphans_recovered

    async def recover_orphans(self) -> int:
        """Run the backend's once-only orphan sweep now (normally kicked by
        the first write; ``__main__`` calls this at boot so the count is
        logged deterministically)."""
        return await self.backend.recover_orphans()

    def describe(self) -> dict:
        return self.backend.describe()

    @asynccontextmanager
    async def reader(self, object_id: Hash) -> AsyncIterator[ObjectReader]:
        reader = ObjectReader(await self.backend.open_read(object_id))
        if self._touch_on_read:
            # Reads mark the object as in use: sessions that only restore
            # a file (never modify it) must still keep it alive under the
            # TTL sweep, which ages by mtime.
            await self.backend.touch(object_id)
        try:
            yield reader
        finally:
            await reader._close()

    @asynccontextmanager
    async def writer(self) -> AsyncIterator[ObjectWriter]:
        writer = ObjectWriter(await self.backend.start_write())
        try:
            yield writer
        except BaseException:
            await writer._abort()
            raise
        else:
            await writer._finalize()

    async def read(self, object_id: Hash) -> bytes:
        async with self.reader(object_id) as r:
            return await r.read()

    async def write(self, data: bytes) -> Hash:
        async with self.writer() as w:
            await w.write(data)
        return w.hash

    async def exists(self, object_id: Hash) -> bool:
        return await self.backend.exists(object_id)

    async def sweep(self, max_age_s: float) -> int:
        """TTL-expire stored objects (see the backend docstrings; the s3
        backend defers to bucket lifecycle rules and returns 0)."""
        return await self.backend.sweep(max_age_s)

    async def aclose(self) -> None:
        await self.backend.aclose()
