"""Content-addressed object storage for workspace file snapshots.

The reference stores files in a flat directory under *random* 64-hex ids despite
its docstring claiming sha256 addressing (reference: src/code_interpreter/services/
storage.py:34-90, the random id at :52). We implement what the docstring promised:
the object id IS the sha256 of the content, computed while streaming the write and
atomically renamed into place on close. This gives free dedup across executions
(identical workspace files snapshot to the same object) while keeping the exact
same API contract — clients treat ids as opaque ``Hash`` strings either way.

Async file I/O uses a worker thread via asyncio.to_thread per chunk, mirroring the
reference's anyio usage without the dependency on anyio.Path semantics.
"""

from __future__ import annotations

import asyncio
import hashlib
import os
import secrets
from contextlib import asynccontextmanager
from pathlib import Path
from typing import AsyncIterator

from bee_code_interpreter_tpu.utils.validation import Hash


class ObjectReader:
    def __init__(self, path: Path, chunk_size: int = 1 << 20) -> None:
        self._path = path
        self._chunk_size = chunk_size
        self._file = None

    async def _open(self) -> None:
        self._file = await asyncio.to_thread(open, self._path, "rb")

    async def read(self, size: int = -1) -> bytes:
        return await asyncio.to_thread(self._file.read, size)

    async def __aiter__(self) -> AsyncIterator[bytes]:
        while chunk := await asyncio.to_thread(self._file.read, self._chunk_size):
            yield chunk

    async def _close(self) -> None:
        await asyncio.to_thread(self._file.close)


class ObjectWriter:
    """Streams bytes to a temp file while hashing; final id is the sha256 hex."""

    def __init__(self, root: Path) -> None:
        self._root = root
        self._tmp_path = root / f".tmp-{secrets.token_hex(8)}"
        self._hasher = hashlib.sha256()
        self._file = None
        self.hash: Hash | None = None

    async def _open(self) -> None:
        self._file = await asyncio.to_thread(open, self._tmp_path, "wb")

    async def write(self, data: bytes) -> None:
        self._hasher.update(data)
        await asyncio.to_thread(self._file.write, data)

    async def _finalize(self) -> None:
        await asyncio.to_thread(self._file.close)
        self.hash = self._hasher.hexdigest()
        final = self._root / self.hash
        # Content-addressed: identical content → same path; rename is atomic and
        # overwriting an identical object is a no-op.
        await asyncio.to_thread(os.replace, self._tmp_path, final)

    async def _abort(self) -> None:
        await asyncio.to_thread(self._file.close)
        try:
            await asyncio.to_thread(os.unlink, self._tmp_path)
        except FileNotFoundError:
            pass


class Storage:
    """Flat-directory object store keyed by content hash.

    API shape mirrors the reference (storage.py:44-90): async ``reader``/``writer``
    context managers plus whole-object ``read``/``write``/``exists`` helpers.
    """

    def __init__(
        self, storage_path: str | os.PathLike, touch_on_read: bool = False
    ) -> None:
        self._root = Path(storage_path)
        # Only pay the per-read utime when a TTL sweep actually ages objects
        # (ApplicationContext sets this from storage_max_age_s); reads are on
        # the warm-execute hot path.
        self._touch_on_read = touch_on_read

    async def _ensure_root(self) -> None:
        await asyncio.to_thread(self._root.mkdir, 0o777, True, True)

    def _object_path(self, object_id: Hash) -> Path:
        # Hash pattern forbids "/" and ".." so a plain join cannot escape root.
        return self._root / object_id

    @asynccontextmanager
    async def reader(self, object_id: Hash) -> AsyncIterator[ObjectReader]:
        path = self._object_path(object_id)
        reader = ObjectReader(path)
        await reader._open()
        if self._touch_on_read:
            try:
                # Reads mark the object as in use: sessions that only restore
                # a file (never modify it) must still keep it alive under the
                # TTL sweep, which ages by mtime.
                await asyncio.to_thread(os.utime, path)
            except OSError:
                pass
        try:
            yield reader
        finally:
            await reader._close()

    @asynccontextmanager
    async def writer(self) -> AsyncIterator[ObjectWriter]:
        await self._ensure_root()
        writer = ObjectWriter(self._root)
        await writer._open()
        try:
            yield writer
        except BaseException:
            await writer._abort()
            raise
        else:
            await writer._finalize()

    async def read(self, object_id: Hash) -> bytes:
        async with self.reader(object_id) as r:
            return await r.read()

    async def write(self, data: bytes) -> Hash:
        async with self.writer() as w:
            await w.write(data)
        return w.hash

    async def exists(self, object_id: Hash) -> bool:
        return await asyncio.to_thread(self._object_path(object_id).exists)

    async def sweep(self, max_age_s: float) -> int:
        """Delete objects untouched for longer than ``max_age_s``; returns the
        count removed.

        The reference leaves cleanup to the operator ("temporary solution ...
        S3 TTL", its README.md:167); this makes the TTL a service feature for
        the flat-directory store. Objects age from last *use*: writes refresh
        mtime via os.replace (ObjectWriter._finalize) and reads refresh it
        explicitly (reader()), so anything an active session touches stays.

        Stale-unlink race closed with a per-object rename guard: the entry is
        atomically renamed aside, re-stat'ed, and renamed back if something
        refreshed it between the first stat and the rename. A concurrent
        identical-content write is unaffected either way (os.replace creates
        a fresh object under the public name). The one remaining race — a
        reader touching the object in the instant it is renamed aside — is
        surfaced to that reader as a missing object, the same outcome S3
        lifecycle rules produce.

        A crash between the rename-aside and its resolution would otherwise
        strand the object as ``.tmp-sweep-*`` forever (every future sweep
        skips ``.tmp-`` names), so each sweep first recovers orphaned guards:
        put fresh ones back under their public name, unlink expired ones.
        """

        def _sweep_sync() -> int:
            import time

            if not self._root.is_dir():
                return 0
            cutoff = time.time() - max_age_s
            removed = 0
            for entry in self._root.iterdir():
                if not entry.name.startswith(".tmp-sweep-"):
                    continue
                public = self._root / entry.name.removeprefix(".tmp-sweep-")
                try:
                    if entry.stat().st_mtime >= cutoff:
                        # A live object a crashed sweep renamed aside. Restore
                        # no-clobber (link fails with EEXIST): a fresh write
                        # that recreated the public name is newer — prefer it.
                        try:
                            os.link(entry, public)
                        except FileExistsError:
                            pass
                        entry.unlink()
                    else:
                        entry.unlink()
                        removed += 1
                except OSError:
                    continue
            for entry in self._root.iterdir():
                try:
                    if entry.name.startswith(".tmp-"):
                        continue  # in-flight write
                    if entry.stat().st_mtime >= cutoff:
                        continue
                    guard = self._root / f".tmp-sweep-{entry.name}"
                    entry.rename(guard)
                except OSError:
                    # Missing (raced), a directory, permission-denied — skip
                    # this entry, keep sweeping the rest.
                    continue
                try:
                    if guard.stat().st_mtime >= cutoff:
                        # refreshed between stat and rename: put it back
                        guard.rename(entry)
                        continue
                    guard.unlink()
                    removed += 1
                except OSError:
                    continue
            return removed

        return await asyncio.to_thread(_sweep_sync)
