"""Native-process code executor: warm pool of local C++ executor servers.

The k8s-free deployment mode for a single TPU VM: the control plane and the
sandboxes share one host, with each sandbox being a fresh instance of the
native executor server (executor/src/server.cpp — the TPU-native counterpart
of the reference's in-pod Rust server, executor/server.rs) listening on a
loopback port with its own throwaway workspace directory.

Pool semantics mirror the Kubernetes backend (and through it the reference's
pod pool, kubernetes_code_executor.py:151-264): a deque of warm, /healthz-ready
server processes kept at a target length with spawning-count accounting;
sandboxes are single-use — after one execution the process is killed and its
workspace deleted, so no state survives a run except through the returned
file map. The data plane is the shared HTTP wire contract (ExecutorHttpDriver),
byte-identical to what the pod network carries.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import functools
import logging
import os
import secrets
import shutil
import socket
import subprocess
import sys
import tempfile
from collections import deque
from contextlib import asynccontextmanager
from dataclasses import dataclass
from pathlib import Path

import httpx

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.observability import (
    FleetJournal,
    collect_transfer,
    merge_worker_usage,
    span,
)
from bee_code_interpreter_tpu.resilience import (
    Deadline,
    InflightRegistry,
    RetryPolicy,
    SandboxTransientError,
    journal_sandbox_teardown,
    retryable,
)
from bee_code_interpreter_tpu.services.code_executor import LeaseHandle, Result
from bee_code_interpreter_tpu.services.executor_http_driver import ExecutorHttpDriver
from bee_code_interpreter_tpu.services.storage import Storage
from bee_code_interpreter_tpu.utils.validation import AbsolutePath, Hash

logger = logging.getLogger(__name__)

REPO_EXECUTOR_DIR = Path(__file__).resolve().parent.parent.parent / "executor"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# Orphan protection (the local analogue of the reference's ownerReferences
# cascade-GC, kubernetes_code_executor.py:215-224): the C++ server sets
# PR_SET_PDEATHSIG on itself and watches APP_PARENT_PID when
# APP_DIE_WITH_PARENT=1 (executor/src/server.cpp main()), so warm sandboxes
# never outlive the control plane even on SIGKILL. Doing it in the child
# instead of a preexec_fn lets Popen use vfork instead of a classic fork of
# the (large) service process — the fork was measured blocking the event loop
# ~35 ms per pool refill, which showed up directly in in-flight request p50.
# (CPython only takes the posix_spawn path with close_fds=False, which a
# sandbox must not use — service fds would leak into user code.)


@dataclass
class NativeSandbox:
    """One warm native executor-server process."""

    proc: subprocess.Popen
    addr: str  # 127.0.0.1:port
    workspace: Path
    name: str = ""  # fleet-journal identity, e.g. "native-43117-a1b2"
    # Dispatched at first-healthy, before its warm worker finished
    # preloading: the server gates the execute internally, so the preload
    # tail counts against the HTTP request and needs timeout headroom.
    overlap_dispatch: bool = False

    def destroy(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        shutil.rmtree(self.workspace, ignore_errors=True)


class NativeProcessCodeExecutor(ExecutorHttpDriver):
    def __init__(
        self,
        storage: Storage,
        config: Config,
        binary: str | Path | None = None,
        http_client: httpx.AsyncClient | None = None,
        metrics=None,
        journal: FleetJournal | None = None,
    ) -> None:
        self._storage = storage
        self._config = config
        # Lifecycle journal (docs/observability.md): same transition
        # vocabulary as the Kubernetes pool, one process per "pod".
        # `is None`, not truthiness: an empty journal is len()==0 — falsy —
        # and replacing the injected one would strand /v1/fleet on a twin.
        self.journal = (
            journal if journal is not None else FleetJournal(metrics=metrics)
        )
        self._binary = Path(binary or config.local_executor_binary or "")
        if not self._binary.is_file():
            raise FileNotFoundError(
                f"native executor binary not found: {self._binary} "
                "(build with `make -C executor`)"
            )
        self._http = http_client or httpx.AsyncClient(
            timeout=config.executor_http_timeout_s
        )
        self._workspace_root = Path(config.local_workspace_root)
        self._queue: deque[NativeSandbox] = deque()
        self._spawning_count = 0
        self._fill_lock = asyncio.Lock()
        # Background refills are CPU-bound (each spawn boots a python warm
        # worker through its preload imports); unbounded concurrency lets a
        # burst of refills starve the serving path's event loop — on a
        # small host that showed up as multi-second acquire stalls and
        # inflated control-plane overhead. Request-blocking spawns (pool
        # empty) bypass this gate on purpose: the waiting request IS the
        # priority.
        self._refill_gate = asyncio.Semaphore(
            max(1, (os.cpu_count() or 2) - 1)
        )
        # Dynamic warm-pool target (docs/autoscaling.md): the PoolAutoscaler
        # writes this in APP_AUTOSCALE_MODE=act; None means the static
        # configured target. Every refill reads `pool_target`.
        self.pool_target_override: int | None = None
        self._closed = False
        # The event loop holds only weak refs to tasks; fire-and-forget refills
        # must be anchored here or GC can cancel them mid-spawn.
        self._background_tasks: set[asyncio.Task] = set()
        # Executions in flight, killable by the supervisor's stuck-execution
        # watchdog (resilience/supervisor.py).
        self.inflight = InflightRegistry()
        # Dedicated spawn thread: PR_SET_PDEATHSIG fires when the spawning
        # *thread* exits (prctl(2)), so sandboxes must not be forked from
        # default-executor workers whose lifetime we don't control. This
        # thread lives exactly as long as the pool.
        self._spawn_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="sandbox-spawn"
        )
        self._stdlib_file_path: str | None = None
        self._stdlib_lock = asyncio.Lock()
        # Native sandboxes are local: startup/IPC failures settle fast, so the
        # backoff floor is 20x tighter than the pod path's.
        self._execute_retry = RetryPolicy(
            attempts=config.executor_retry_attempts,
            wait_min_s=0.2,
            wait_max_s=2.0,
            retry_on=(SandboxTransientError,),
        )
        self._spawn_retry = RetryPolicy(
            attempts=config.executor_retry_attempts,
            wait_min_s=0.2,
            wait_max_s=2.0,
            retry_on=(RuntimeError,),
        )
        # Per-request phase breakdown of the most recent execute() (diagnostic
        # surface for bench.py / scripts/measure-latency.py: lets a latency
        # regression be attributed to acquire/upload/server/download/overhead
        # instead of guessed at). Overwritten per request; read it before
        # issuing the next one.
        self.last_execute_phases: dict[str, float | bool] = {}

    async def _stdlib_file(self) -> str | None:
        """Stdlib module list for the dep guesser, generated once per service
        process by asking the *sandbox* interpreter (same APP_PYTHON
        resolution the C++ server uses — its stdlib can differ from the
        control plane venv's); sandboxes read the file instead of each paying
        a python startup to ask. None when dep-install is disabled (the list
        is never consulted). The probe runs off-loop (a python startup must
        not stall in-flight requests), lands in a private per-process runtime
        dir — NOT under workspace_root, where sandboxed user code could
        overwrite it via ``../`` and poison later guesses — and is fresh
        every service start so an interpreter upgrade can't serve a stale
        list. Falls back to this interpreter's own list if the probe fails.
        (The executor image pregenerates /stdlib_names.txt the same way.)"""
        if self._config.disable_dep_install:
            return None
        async with self._stdlib_lock:
            if self._stdlib_file_path is None:
                python = os.environ.get("APP_PYTHON", "python3")
                probe = "import sys; print('\\n'.join(sorted(sys.stdlib_module_names)))"

                def generate() -> str:
                    try:
                        return subprocess.run(
                            [python, "-c", probe],
                            capture_output=True, text=True, timeout=30, check=True,
                        ).stdout
                    except (OSError, subprocess.SubprocessError):
                        return "\n".join(sorted(sys.stdlib_module_names)) + "\n"

                names = await asyncio.get_running_loop().run_in_executor(
                    self._spawn_pool, generate
                )
                runtime_dir = Path(tempfile.mkdtemp(prefix="bci-runtime-"))
                path = runtime_dir / "stdlib_names.txt"
                path.write_text(names)
                self._stdlib_file_path = str(path)
        return self._stdlib_file_path

    @property
    def pool_ready_count(self) -> int:
        return len(self._queue)

    @property
    def pool_spawning_count(self) -> int:
        return self._spawning_count

    @property
    def pool_target(self) -> int:
        """The refill target: the autoscaler's override when one is
        actuated, the static configured length otherwise."""
        if self.pool_target_override is not None:
            return self.pool_target_override
        return self._config.executor_pod_queue_target_length

    # ------------------------------------------------------------- execution

    @retryable("_execute_retry", op="execute")
    async def execute(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        deadline: Deadline | None = None,
    ) -> Result:
        files = files or {}
        env = env or {}
        if deadline is not None:
            deadline.check("execute")
        perf = asyncio.get_running_loop().time
        t_start = perf()
        was_warm = bool(self._queue)
        # Ambient byte-accounting scope for this execution (sync contextvars;
        # the driver's upload/download calls report into it).
        with collect_transfer() as transfer:
            return await self._execute_on_sandbox(
                source_code, files, env, timeout_s, deadline,
                transfer, perf, t_start, was_warm,
            )

    async def _execute_on_sandbox(
        self, source_code, files, env, timeout_s, deadline,
        transfer, perf, t_start, was_warm,
    ) -> Result:
        async with self.sandbox(deadline=deadline) as box:
            t_acquired = perf()
            await asyncio.gather(
                *(
                    self._upload_file(box.addr, path, object_id, deadline=deadline)
                    for path, object_id in files.items()
                )
            )
            t_uploaded = perf()
            self.journal.record(box.name, "executing")
            # Tracked so the supervisor watchdog can kill a wedged sandbox:
            # the process kill resets this call's transport, and the task
            # cancel converts to a transient failure (hung_execute).
            with self.inflight.track(
                box.name, kill=lambda: self._kill_sandbox(box)
            ):
                response = await self._post_execute(
                    box.addr,
                    source_code,
                    env,
                    self._effective_timeout(timeout_s),
                    # preload budget (matches the pooled warm-wait bound) on
                    # top of the client timeout for overlap-dispatched
                    # sandboxes — a near-limit execution must not lose its
                    # margin to the preload it overlapped
                    client_timeout_s=(
                        self._config.executor_http_timeout_s + 15.0
                        if box.overlap_dispatch
                        else None
                    ),
                    deadline=deadline,
                )
            t_executed = perf()
            out_files: dict[str, str] = {}
            for path, object_id in zip(
                response["files"],
                await asyncio.gather(
                    *(
                        self._download_file(box.addr, p, deadline=deadline)
                        for p in response["files"]
                    )
                ),
            ):
                out_files[path] = object_id
            t_done = perf()
            # sandbox_ms is the server-reported subprocess wall time; the gap
            # post_execute_ms − sandbox_ms is pure control-plane + HTTP
            # overhead — where event-loop contention (e.g. pool refills)
            # shows up.
            sandbox_ms = float(response.get("duration_ms") or 0.0)
            self.last_execute_phases = {
                "acquire_ms": (t_acquired - t_start) * 1000,
                "warm_pop": was_warm,
                "upload_ms": (t_uploaded - t_acquired) * 1000,
                "post_execute_ms": (t_executed - t_uploaded) * 1000,
                "sandbox_ms": sandbox_ms,
                "overhead_ms": (t_executed - t_uploaded) * 1000 - sandbox_ms,
                "download_ms": (t_done - t_executed) * 1000,
                "total_ms": (t_done - t_start) * 1000,
            }
            # The C++ server doesn't measure usage (its response has no
            # block); the Python server does — merge handles either, and the
            # driver's byte counts are always present.
            usage = merge_worker_usage([response.get("usage")])
            usage.update(transfer.as_dict())
            return Result(
                stdout=response["stdout"],
                stderr=response["stderr"],
                exit_code=response["exit_code"],
                files=out_files,
                usage=usage,
            )

    # ------------------------------------------------------------------ pool

    async def _checkout_sandbox(
        self, deadline: Deadline | None = None
    ) -> NativeSandbox:
        """Pop a live warm server (discarding corpses) or spawn one, journal
        the assignment, kick a refill — the acquisition half shared by the
        single-use execute path and session leases."""
        box = None
        while self._queue:
            candidate = self._queue.popleft()
            if candidate.proc.poll() is None:
                box = candidate
                self.journal.record(box.name, "assigned", reason="warm_pop")
                break
            logger.warning("Warm sandbox on %s died in queue; discarding", candidate.addr)
            self.journal.record(
                candidate.name,
                "reaped",
                reason="died_in_queue",
                detail=f"exit {candidate.proc.returncode}",
            )
            candidate.destroy()
        if box is None:
            # Pool drained: dispatch at first healthy instead of polling for
            # preload-done — the server queues the execute until its warm
            # worker is ready (or falls back cold), so the request overlaps
            # with the tail of the preload rather than waiting it out here.
            with span("spawn"):
                spawn = self.spawn_sandbox(wait_warm=False)
                box = await (
                    deadline.run(spawn, what="sandbox spawn")
                    if deadline
                    else spawn
                )
            self.journal.record(box.name, "assigned", reason="cold_spawn")
        self._spawn_background(self.fill_sandbox_queue())
        return box

    @asynccontextmanager
    async def sandbox(self, deadline: Deadline | None = None):
        """Pop a warm server or spawn one; single-use teardown + async refill.
        A sandbox whose process died while queued (OOM, crash) is discarded,
        not handed to a request."""
        box = await self._checkout_sandbox(deadline)
        try:
            yield box
        except BaseException as e:
            # Mirror of the pod-group path: a transient failure mid-execute
            # means the sandbox process is presumed dead/wedged, and the
            # journal reason is what replay observability keys on.
            journal_sandbox_teardown(self.journal, box.name, e)
            raise
        else:
            journal_sandbox_teardown(self.journal, box.name, None)
        finally:
            # Teardown must not block the response (reference deletes pods
            # fire-and-forget, kubernetes_code_executor.py:262-264).
            asyncio.get_running_loop().run_in_executor(None, box.destroy)

    def _spawn_background(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._background_tasks.add(task)
        task.add_done_callback(self._background_tasks.discard)

    def _kill_sandbox(self, box: NativeSandbox) -> None:
        """Watchdog teardown of a wedged sandbox (sync, fire-and-forget):
        killing the process resets the in-flight HTTP call's transport."""
        asyncio.get_running_loop().run_in_executor(None, box.destroy)

    # ---------------------------------------------------------------- leases

    async def checkout_for_lease(
        self, deadline: Deadline | None = None
    ) -> LeaseHandle:
        """Check a warm server out of the pool for a session lease
        (docs/sessions.md): popped out of the queue, so the supervisor's
        idle reaper never probes it while the session holds it."""
        box = await self._checkout_sandbox(deadline)
        return LeaseHandle(
            name=box.name,
            addrs=[box.addr],
            kill=lambda: self._kill_sandbox(box),
            handle=box,
        )

    def release_lease(
        self,
        lease: LeaseHandle,
        state: str = "released",
        reason: str = "lease_released",
        detail: str | None = None,
    ) -> None:
        """End a lease: terminal journal event, sandbox torn down, refill
        kicked (mirror of the Kubernetes backend)."""
        self.journal.record(lease.name, state, reason=reason, detail=detail)
        lease.kill()
        self._spawn_background(self.fill_sandbox_queue())

    async def execute_stream(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        on_event=None,  # async (kind, text) -> None per stdout/stderr chunk
        deadline: Deadline | None = None,
    ) -> Result:
        """Streaming execute over a single-use native sandbox: output chunks
        forward to ``on_event`` as the server produces them; workspace
        restore before / snapshot after are unchanged. No retry/replay wraps
        this path — delivered chunks cannot be un-delivered."""
        files = files or {}
        env = env or {}
        if deadline is not None:
            deadline.check("execute")
        with collect_transfer() as transfer:
            async with self.sandbox(deadline=deadline) as box:
                await asyncio.gather(
                    *(
                        self._upload_file(box.addr, path, object_id, deadline=deadline)
                        for path, object_id in files.items()
                    )
                )
                self.journal.record(box.name, "executing")
                with self.inflight.track(
                    box.name, kill=lambda: self._kill_sandbox(box)
                ):
                    response = await self._post_execute_stream(
                        box.addr,
                        source_code,
                        env,
                        self._effective_timeout(timeout_s),
                        on_event=on_event,
                        deadline=deadline,
                    )
                out_files: dict[str, str] = {}
                for path, object_id in zip(
                    response["files"],
                    await asyncio.gather(
                        *(
                            self._download_file(box.addr, p, deadline=deadline)
                            for p in response["files"]
                        )
                    ),
                ):
                    out_files[path] = object_id
                usage = merge_worker_usage([response.get("usage")])
                usage.update(transfer.as_dict())
                return Result(
                    stdout=response["stdout"],
                    stderr=response["stderr"],
                    exit_code=response["exit_code"],
                    files=out_files,
                    usage=usage,
                )

    async def _sandbox_healthy(self, box: NativeSandbox) -> bool:
        """The process is alive AND its /healthz answers — a live-but-wedged
        server (stuck preload, leaked lock) is as dead as a crashed one."""
        if box.proc.poll() is not None:
            return False
        try:
            response = await self._http.get(
                f"http://{box.addr}/healthz",
                timeout=self._config.health_probe_timeout_s,
            )
            return response.status_code == 200
        except httpx.HTTPError:
            return False

    def trim_excess_warm(self) -> int:
        """Supervisor hook for the autoscaler's act-mode shrink
        (docs/autoscaling.md): reap queued warm servers beyond the current
        refill target (mirror of the Kubernetes backend — a scale-down
        must shrink the live pool, not just stop refills)."""
        trimmed = 0
        while len(self._queue) > self.pool_target:
            box = self._queue.pop()
            self.journal.record(box.name, "reaped", reason="scaled_down")
            self._kill_sandbox(box)
            trimmed += 1
        return trimmed

    async def reap_unhealthy_idle(self) -> int:
        """Supervisor hook: probe every queued warm sandbox and reap the
        ones that died or wedged in place. Returns the number reaped."""
        candidates = list(self._queue)
        if not candidates:
            return 0
        # Probe the whole queue concurrently: a mass-death event must not
        # cost one probe timeout PER corpse before healing starts.
        results = await asyncio.gather(
            *(self._sandbox_healthy(b) for b in candidates)
        )
        reaped = 0
        for box, healthy in zip(candidates, results):
            if healthy:
                continue
            try:
                self._queue.remove(box)
            except ValueError:
                continue  # checked out by a request while we probed
            exited = box.proc.poll() is not None
            detail = (
                f"exit {box.proc.returncode}" if exited else "healthz probe failed"
            )
            logger.warning(
                "Supervisor reaping unhealthy idle sandbox %s (%s)",
                box.name,
                detail,
            )
            self.journal.record(
                box.name, "reaped", reason="unhealthy_idle", detail=detail
            )
            self._kill_sandbox(box)
            reaped += 1
        return reaped

    async def fill_sandbox_queue(self) -> None:
        if self._closed:
            return
        async with self._fill_lock:
            missing = self.pool_target - len(self._queue) - self._spawning_count
            if missing <= 0:
                return
            self._spawning_count += missing
        # Each spawn settles its own accounting — a failed spawn must never
        # abandon its siblings or leave a phantom spawning count behind.
        results = await asyncio.gather(
            *(self._spawn_into_queue() for _ in range(missing))
        )
        if not all(results):
            logger.warning(
                "Sandbox pool refill finished with failures: %d/%d spawned",
                sum(results),
                missing,
            )

    async def _spawn_into_queue(self) -> bool:
        try:
            async with self._refill_gate:
                box = await self.spawn_sandbox()
        except Exception:
            logger.exception("Sandbox spawn failed")
            return False
        finally:
            self._spawning_count -= 1
        if self._closed:
            # raced with shutdown: don't repopulate a dead pool
            self.journal.record(box.name, "reaped", reason="shutdown")
            box.destroy()
            return False
        self._queue.append(box)
        return True

    @retryable("_spawn_retry", op="spawn")
    async def spawn_sandbox(self, wait_warm: bool = True) -> NativeSandbox:
        port = _free_port()
        # The port alone is NOT unique: _free_port() releases its probe
        # socket before the sandbox binds, so concurrent spawns can draw the
        # same number — two journal records must never share one identity.
        name = f"native-{port}-{secrets.token_hex(2)}"
        self.journal.record(name, "spawning")
        try:
            return await self._spawn_sandbox(port, name, wait_warm)
        except BaseException as e:
            # EVERY spawn failure — mkdir, the stdlib probe, Popen, the
            # readiness wait, a deadline cancellation — must close the
            # journal record, or the pod sits in _live as a phantom
            # 'spawning' forever (and a persistently failing refill loop
            # would accumulate phantoms without bound).
            self.journal.record(
                name,
                "failed",
                reason="spawn_failed",
                detail=(str(e) or type(e).__name__)[:200],
            )
            raise

    async def _spawn_sandbox(
        self, port: int, name: str, wait_warm: bool
    ) -> NativeSandbox:
        cfg = self._config
        addr = f"127.0.0.1:{port}"
        workspace = self._workspace_root / secrets.token_hex(8)
        workspace.mkdir(parents=True, exist_ok=True)

        env = dict(os.environ)
        env.update(
            APP_LISTEN_ADDR=addr,
            APP_WORKSPACE=str(workspace),
            APP_EXECUTION_TIMEOUT_S=str(cfg.execution_timeout_s),
            APP_REQUIREMENTS=str(REPO_EXECUTOR_DIR / "requirements.txt"),
            APP_REQUIREMENTS_SKIP=str(REPO_EXECUTOR_DIR / "requirements-skip.txt"),
            APP_PYPI_MAP=str(REPO_EXECUTOR_DIR / "pypi_map.tsv"),
        )
        if cfg.disable_dep_install:
            env["APP_DISABLE_DEP_INSTALL"] = "1"
        shim = cfg.resolved_shim_dir()
        if shim:
            env["APP_SHIM_DIR"] = str(shim)
        if cfg.jax_cache_dir:
            env["APP_JAX_CACHE_DIR"] = cfg.jax_cache_dir
        env["APP_DIE_WITH_PARENT"] = "1"  # server watches us via PDEATHSIG+ppid
        env["APP_PARENT_PID"] = str(os.getpid())
        # Hermetic-mode scrub prefixes: envscrub.py is the single source of
        # truth; the C++ server's built-in list is only a fallback.
        from bee_code_interpreter_tpu.utils.envscrub import TUNNEL_PLUGIN_PREFIXES

        env["APP_SCRUB_PREFIXES"] = ",".join(TUNNEL_PLUGIN_PREFIXES)
        stdlib_file = await self._stdlib_file()
        if stdlib_file:
            env["APP_STDLIB_FILE"] = stdlib_file

        argv: list[str] = [str(self._binary)]
        if cfg.sandbox_unshare:
            # Mount-namespace hardening: the server (and every python child
            # it spawns) sees an empty tmpfs where the object-storage root
            # is, so user code cannot read other sessions' files. Mount-ns
            # only: a net namespace would cut the loopback HTTP transport,
            # and a pid namespace breaks the APP_PARENT_PID watchdog (k8s
            # mode provides those via pod isolation instead).
            #
            # The process holding the namespace has CAP_SYS_ADMIN over it
            # (real or userns-mapped root), so user code could umount2() the
            # tmpfs and uncover the real directory — after the mount, the
            # capability bounding set is emptied (setpriv) so no descendant
            # can ever regain it; verified by the umount-bypass test. If
            # setpriv is missing the overmount still guards against
            # accidental access but a deliberate umount bypasses it — warn.
            storage_root = Path(cfg.file_storage_path).resolve()
            storage_root.mkdir(parents=True, exist_ok=True)  # mount target
            env["BCI_HIDE_DIR"] = str(storage_root)
            lockdown = (
                ["setpriv", "--bounding-set", "-all"]
                if shutil.which("setpriv")
                else []
            )
            if not lockdown:
                logger.warning(
                    "sandbox_unshare: setpriv not found - the storage "
                    "overmount cannot be capability-locked and deliberate "
                    "user code could umount it"
                )
            argv = [
                "unshare",
                "--mount",
                *([] if os.geteuid() == 0 else ["--map-root-user"]),
                "sh",
                "-c",
                'mount -t tmpfs tmpfs "$BCI_HIDE_DIR" && exec "$@"',
                "sh",
                *lockdown,
                str(self._binary),
            ]

        # Off-loop spawn: even vfork costs ~ms, and refills run concurrently
        # with in-flight requests.
        proc = await asyncio.get_running_loop().run_in_executor(
            self._spawn_pool,
            functools.partial(
                subprocess.Popen,
                argv,
                env=env,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            ),
        )
        box = NativeSandbox(
            proc=proc, addr=addr, workspace=workspace, name=name,
            overlap_dispatch=not wait_warm,
        )
        try:
            loop = asyncio.get_running_loop()
            deadline = loop.time() + cfg.pod_ready_timeout_s
            warm_deadline: float | None = None  # set at first healthy
            while True:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"native executor exited at startup (code {proc.returncode})"
                    )
                try:
                    response = await self._http.get(f"http://{addr}/healthz")
                    if response.status_code == 200:
                        # Best-effort: hold the sandbox back until its warm
                        # worker finished preloading, so requests never pay
                        # the preload wait. A slow preload (up to 15 s past
                        # healthy, or the ready deadline if sooner) queues the
                        # healthy-but-cold sandbox anyway — the server's own
                        # warm-wait/cold-fallback covers it.
                        if not wait_warm:
                            return self._spawned_ready(box)
                        if warm_deadline is None:
                            warm_deadline = min(loop.time() + 15.0, deadline)
                        if response.json().get("warm", True):
                            return self._spawned_ready(box)
                        if loop.time() > warm_deadline:
                            return self._spawned_ready(box)
                except (httpx.TransportError, ValueError):
                    pass
                if loop.time() > deadline:
                    raise RuntimeError(
                        f"native executor on {addr} never became ready"
                    )
                await asyncio.sleep(0.05)
        except BaseException:
            # BaseException: a deadline-driven cancel must also reap the
            # half-started sandbox process, not leak it. (The caller's
            # journal guard records the 'failed' event.)
            box.destroy()
            raise

    def _spawned_ready(self, box: NativeSandbox) -> NativeSandbox:
        self.journal.record(box.name, "ready")
        return box

    def shutdown(self, close_http: bool = True) -> None:
        """Kill every warm sandbox (no idle processes left behind).

        Sets the closed flag first so refills already in flight destroy their
        sandboxes instead of repopulating a dead pool.
        """
        self._closed = True
        while self._queue:
            box = self._queue.popleft()
            self.journal.record(box.name, "reaped", reason="shutdown")
            box.destroy()
        # The spawn thread's exit triggers PDEATHSIG in any sandbox it forked
        # — including one currently serving a request. That is the intended
        # contract: shutdown() terminates the backend; an execution still in
        # flight dies with it (its handler is being torn down with the loop
        # anyway). Queued sandboxes were destroyed above; in-flight refills
        # see the closed flag and destroy their own.
        self._spawn_pool.shutdown(wait=False)
        if not close_http:
            return
        # Legacy sync path: the aclose can only be scheduled, and a loop shut
        # down right after may cancel it before it runs. The drain path uses
        # the deterministic ``aclose()`` instead.
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            pass
        else:
            task = loop.create_task(self._http.aclose())
            self._background_tasks.add(task)
            task.add_done_callback(self._background_tasks.discard)

    async def aclose(self) -> None:
        """Deterministic drain-path shutdown: tear the pool down, then close
        the HTTP client *awaited in-loop* — not as a fire-and-forget task the
        closing loop could cancel before it ever ran."""
        self.shutdown(close_http=False)
        await self._http.aclose()
