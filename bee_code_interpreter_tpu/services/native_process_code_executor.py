"""Native-process code executor: warm pool of local C++ executor servers.

The k8s-free deployment mode for a single TPU VM: the control plane and the
sandboxes share one host, with each sandbox being a fresh instance of the
native executor server (executor/src/server.cpp — the TPU-native counterpart
of the reference's in-pod Rust server, executor/server.rs) listening on a
loopback port with its own throwaway workspace directory.

Pool semantics mirror the Kubernetes backend (and through it the reference's
pod pool, kubernetes_code_executor.py:151-264): a deque of warm, /healthz-ready
server processes kept at a target length with spawning-count accounting;
sandboxes are single-use — after one execution the process is killed and its
workspace deleted, so no state survives a run except through the returned
file map. The data plane is the shared HTTP wire contract (ExecutorHttpDriver),
byte-identical to what the pod network carries.
"""

from __future__ import annotations

import asyncio
import logging
import os
import secrets
import shutil
import socket
import subprocess
from collections import deque
from contextlib import asynccontextmanager
from dataclasses import dataclass
from pathlib import Path

import httpx
from tenacity import (
    retry,
    retry_if_exception_type,
    stop_after_attempt,
    wait_exponential,
)

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.services.code_executor import Result
from bee_code_interpreter_tpu.services.executor_http_driver import ExecutorHttpDriver
from bee_code_interpreter_tpu.services.storage import Storage
from bee_code_interpreter_tpu.utils.validation import AbsolutePath, Hash

logger = logging.getLogger(__name__)

REPO_EXECUTOR_DIR = Path(__file__).resolve().parent.parent.parent / "executor"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _die_with_parent() -> None:
    """PR_SET_PDEATHSIG: the kernel kills the sandbox if the service dies.

    The local analogue of the reference's ownerReferences cascade-GC
    (kubernetes_code_executor.py:215-224) — warm sandboxes must never outlive
    the control plane, even on SIGKILL. Linux-only; elsewhere orphans are only
    cleaned up by the cooperative shutdown() path.
    """
    try:
        import ctypes
        import signal as _signal

        PR_SET_PDEATHSIG = 1
        ctypes.CDLL("libc.so.6", use_errno=True).prctl(
            PR_SET_PDEATHSIG, _signal.SIGKILL, 0, 0, 0
        )
    except Exception:
        pass


@dataclass
class NativeSandbox:
    """One warm native executor-server process."""

    proc: subprocess.Popen
    addr: str  # 127.0.0.1:port
    workspace: Path

    def destroy(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        shutil.rmtree(self.workspace, ignore_errors=True)


class NativeProcessCodeExecutor(ExecutorHttpDriver):
    def __init__(
        self,
        storage: Storage,
        config: Config,
        binary: str | Path | None = None,
        http_client: httpx.AsyncClient | None = None,
    ) -> None:
        self._storage = storage
        self._config = config
        self._binary = Path(binary or config.local_executor_binary or "")
        if not self._binary.is_file():
            raise FileNotFoundError(
                f"native executor binary not found: {self._binary} "
                "(build with `make -C executor`)"
            )
        self._http = http_client or httpx.AsyncClient(
            timeout=config.executor_http_timeout_s
        )
        self._workspace_root = Path(config.local_workspace_root)
        self._queue: deque[NativeSandbox] = deque()
        self._spawning_count = 0
        self._fill_lock = asyncio.Lock()
        self._closed = False
        # The event loop holds only weak refs to tasks; fire-and-forget refills
        # must be anchored here or GC can cancel them mid-spawn.
        self._background_tasks: set[asyncio.Task] = set()

    @property
    def pool_ready_count(self) -> int:
        return len(self._queue)

    @property
    def pool_spawning_count(self) -> int:
        return self._spawning_count

    # ------------------------------------------------------------- execution

    @retry(
        retry=retry_if_exception_type(RuntimeError),
        stop=stop_after_attempt(3),
        wait=wait_exponential(min=0.2, max=2),
        reraise=True,
    )
    async def execute(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
    ) -> Result:
        files = files or {}
        env = env or {}
        async with self.sandbox() as box:
            await asyncio.gather(
                *(
                    self._upload_file(box.addr, path, object_id)
                    for path, object_id in files.items()
                )
            )
            response = await self._post_execute(
                box.addr, source_code, env, self._config.execution_timeout_s
            )
            out_files: dict[str, str] = {}
            for path, object_id in zip(
                response["files"],
                await asyncio.gather(
                    *(self._download_file(box.addr, p) for p in response["files"])
                ),
            ):
                out_files[path] = object_id
            return Result(
                stdout=response["stdout"],
                stderr=response["stderr"],
                exit_code=response["exit_code"],
                files=out_files,
            )

    # ------------------------------------------------------------------ pool

    @asynccontextmanager
    async def sandbox(self):
        """Pop a warm server or spawn one; single-use teardown + async refill."""
        box = self._queue.popleft() if self._queue else await self.spawn_sandbox()
        self._spawn_background(self.fill_sandbox_queue())
        try:
            yield box
        finally:
            # Teardown must not block the response (reference deletes pods
            # fire-and-forget, kubernetes_code_executor.py:262-264).
            asyncio.get_running_loop().run_in_executor(None, box.destroy)

    def _spawn_background(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._background_tasks.add(task)
        task.add_done_callback(self._background_tasks.discard)

    async def fill_sandbox_queue(self) -> None:
        if self._closed:
            return
        async with self._fill_lock:
            missing = (
                self._config.executor_pod_queue_target_length
                - len(self._queue)
                - self._spawning_count
            )
            if missing <= 0:
                return
            self._spawning_count += missing
        # Each spawn settles its own accounting — a failed spawn must never
        # abandon its siblings or leave a phantom spawning count behind.
        results = await asyncio.gather(
            *(self._spawn_into_queue() for _ in range(missing))
        )
        if not all(results):
            logger.warning(
                "Sandbox pool refill finished with failures: %d/%d spawned",
                sum(results),
                missing,
            )

    async def _spawn_into_queue(self) -> bool:
        try:
            box = await self.spawn_sandbox()
        except Exception:
            logger.exception("Sandbox spawn failed")
            return False
        finally:
            self._spawning_count -= 1
        if self._closed:
            box.destroy()  # raced with shutdown: don't repopulate a dead pool
            return False
        self._queue.append(box)
        return True

    @retry(
        retry=retry_if_exception_type(RuntimeError),
        stop=stop_after_attempt(3),
        wait=wait_exponential(min=0.2, max=2),
        reraise=True,
    )
    async def spawn_sandbox(self) -> NativeSandbox:
        cfg = self._config
        port = _free_port()
        addr = f"127.0.0.1:{port}"
        workspace = self._workspace_root / secrets.token_hex(8)
        workspace.mkdir(parents=True, exist_ok=True)

        env = dict(os.environ)
        env.update(
            APP_LISTEN_ADDR=addr,
            APP_WORKSPACE=str(workspace),
            APP_EXECUTION_TIMEOUT_S=str(cfg.execution_timeout_s),
            APP_REQUIREMENTS=str(REPO_EXECUTOR_DIR / "requirements.txt"),
            APP_REQUIREMENTS_SKIP=str(REPO_EXECUTOR_DIR / "requirements-skip.txt"),
            APP_PYPI_MAP=str(REPO_EXECUTOR_DIR / "pypi_map.tsv"),
        )
        if cfg.disable_dep_install:
            env["APP_DISABLE_DEP_INSTALL"] = "1"
        shim = cfg.resolved_shim_dir()
        if shim:
            env["APP_SHIM_DIR"] = str(shim)

        proc = subprocess.Popen(
            [str(self._binary)],
            env=env,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            preexec_fn=_die_with_parent,
        )
        box = NativeSandbox(proc=proc, addr=addr, workspace=workspace)
        try:
            deadline = (
                asyncio.get_running_loop().time() + cfg.pod_ready_timeout_s
            )
            while True:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"native executor exited at startup (code {proc.returncode})"
                    )
                try:
                    response = await self._http.get(f"http://{addr}/healthz")
                    if response.status_code == 200:
                        return box
                except httpx.TransportError:
                    pass
                if asyncio.get_running_loop().time() > deadline:
                    raise RuntimeError(
                        f"native executor on {addr} never became ready"
                    )
                await asyncio.sleep(0.05)
        except Exception:
            box.destroy()
            raise

    def shutdown(self) -> None:
        """Kill every warm sandbox (no idle processes left behind).

        Sets the closed flag first so refills already in flight destroy their
        sandboxes instead of repopulating a dead pool.
        """
        self._closed = True
        while self._queue:
            self._queue.popleft().destroy()
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            pass
        else:
            task = loop.create_task(self._http.aclose())
            self._background_tasks.add(task)
            task.add_done_callback(self._background_tasks.discard)
