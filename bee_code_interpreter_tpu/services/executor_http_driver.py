"""Shared HTTP data-plane driver for executor sandboxes.

Both sandbox backends — Kubernetes pod groups and local native-server
processes — speak the same wire contract (reference executor/server.rs:186-192;
ours executor/src/server.cpp): ``PUT/GET /workspace/{path}`` for the workspace
snapshot and ``POST /execute`` for the run. This mixin holds the driver side of
that contract (reference kubernetes_code_executor.py:95-142), addressed by
``host:port`` so the transport is identical whether the sandbox is across the
pod network or on localhost.

Resilience semantics (docs/resilience.md):

- Failures are *typed*: 5xx / timeouts / connection errors raise
  ``SandboxTransientError`` (retryable); 4xx raises ``SandboxFatalError``
  (the sandbox answered — retrying cannot change the answer).
- Every call accepts the request ``Deadline``; the per-call HTTP timeout is
  the deadline's remaining budget, never an independent fixed number.
- A backend may set ``self._http_breaker``; each call is then gated and its
  outcome recorded, with fatal (4xx) responses counting as breaker successes.

Observability (docs/observability.md): each call runs under a trace stage
span (``upload``/``execute``/``download``), and every request carries the
W3C ``traceparent`` plus ``X-Request-Id`` headers so the executor server
continues the same trace inside the pod and its logs correlate back to the
edge request. Completed uploads/downloads report their byte counts into
the ambient per-execution accounting scope (``observability/accounting.py``)
so ``ExecuteResponse.usage`` can attribute data-plane traffic per request.
"""

from __future__ import annotations

import json
from contextlib import nullcontext

import httpx

from bee_code_interpreter_tpu.analysis.context import predicted_deps
from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.observability import (
    outbound_headers,
    record_transfer,
    span,
)
from bee_code_interpreter_tpu.resilience import (
    CircuitBreaker,
    Deadline,
    SandboxTransientError,
    classify_http_status,
)
from bee_code_interpreter_tpu.services.storage import Storage
from bee_code_interpreter_tpu.utils.validation import Hash


class ExecutorHttpDriver:
    """Mixin: requires ``self._http`` (httpx.AsyncClient) and ``self._storage``."""

    _http: httpx.AsyncClient
    _storage: Storage
    _config: Config
    _http_breaker: CircuitBreaker | None = None  # backends may install one

    def _data_plane_guard(self):
        breaker = getattr(self, "_http_breaker", None)
        return breaker.guard() if breaker is not None else nullcontext()

    def _deadline_kwargs(self, deadline: Deadline | None, what: str) -> dict:
        """Per-call httpx timeout: the CONFIGURED per-call bound, shrunk to
        the remaining deadline budget — never replaced by it. A bare
        ``remaining()`` would let one black-holed pod consume the whole
        request deadline and starve the retry of its second attempt."""
        if deadline is None:
            return {}
        deadline.check(what)
        return {"timeout": deadline.clamp(self._config.executor_http_timeout_s)}

    async def _upload_file(
        self,
        addr: str,
        path: str,
        object_id: Hash,
        deadline: Deadline | None = None,
    ) -> None:
        sent = 0

        async def body():
            nonlocal sent
            async with self._storage.reader(object_id) as reader:
                async for chunk in reader:
                    sent += len(chunk)
                    yield chunk

        what = f"file upload to {addr}"
        kwargs = self._deadline_kwargs(deadline, what)
        with span("upload", addr=addr, path=path):
            async with self._data_plane_guard():
                try:
                    response = await self._http.put(
                        self._sandbox_url(addr, path),
                        content=body(),
                        headers=outbound_headers(),
                        **kwargs,
                    )
                except httpx.TimeoutException as e:
                    raise SandboxTransientError(f"{what} timed out: {e}") from e
                except httpx.TransportError as e:
                    raise SandboxTransientError(f"{what} failed: {e}") from e
                if response.status_code >= 300:
                    raise classify_http_status(response.status_code, what)
        # Only completed moves count toward the execution's usage block.
        record_transfer("upload", sent)

    async def _download_file(
        self, addr: str, path: str, deadline: Deadline | None = None
    ) -> Hash:
        what = f"file download from {addr}"
        kwargs = self._deadline_kwargs(deadline, what)
        received = 0
        with span("download", addr=addr, path=path):
            async with self._data_plane_guard():
                try:
                    async with self._storage.writer() as writer:
                        async with self._http.stream(
                            "GET",
                            self._sandbox_url(addr, path),
                            headers=outbound_headers(),
                            **kwargs,
                        ) as response:
                            if response.status_code >= 300:
                                raise classify_http_status(
                                    response.status_code, what
                                )
                            async for chunk in response.aiter_bytes():
                                received += len(chunk)
                                await writer.write(chunk)
                except httpx.TimeoutException as e:
                    raise SandboxTransientError(f"{what} timed out: {e}") from e
                except httpx.TransportError as e:
                    raise SandboxTransientError(f"{what} failed: {e}") from e
        record_transfer("download", received)
        return writer.hash

    def _effective_timeout(self, timeout_s: float | None) -> float:
        """A request may shorten the execution deadline, never extend it past
        the service-configured bound (requires ``self._config``)."""
        bound = self._config.execution_timeout_s
        if timeout_s is None or timeout_s <= 0:
            return bound
        return min(timeout_s, bound)

    async def _post_execute(
        self,
        addr: str,
        source_code: str,
        env: dict[str, str],
        timeout_s: float,
        client_timeout_s: float | None = None,
        deadline: Deadline | None = None,
    ) -> dict:
        """``client_timeout_s`` overrides the shared client's read timeout
        for this one request — used when the sandbox was dispatched before
        its warm worker finished preloading, so the preload tail counts
        against the HTTP budget and needs headroom over ``timeout_s``."""
        what = f"execute on {addr}"
        kwargs: dict = {}
        if client_timeout_s is not None:
            kwargs["timeout"] = client_timeout_s
        if deadline is not None:
            deadline.check(what)
            # The sandbox-side execution timeout and the HTTP read timeout
            # both shrink to the remaining request budget (the read timeout
            # keeps its configured per-call bound as the ceiling).
            timeout_s = deadline.clamp(timeout_s)
            kwargs["timeout"] = deadline.clamp(
                kwargs.get("timeout", self._config.executor_http_timeout_s)
            )
        body = {
            "source_code": source_code,
            "env": env,
            "timeout": timeout_s,
        }
        # Edge dep pre-resolution (docs/analysis.md): when the API edge
        # already ran its AST pass, its prediction rides the execute call so
        # the sandbox pays set lookups instead of a second parse. Absent
        # when no analyzer ran — the sandbox then scans as before.
        deps = predicted_deps()
        if deps is not None:
            body["predicted_deps"] = deps
        with span("execute", addr=addr):
            async with self._data_plane_guard():
                try:
                    response = await self._http.post(
                        f"http://{addr}/execute",
                        json=body,
                        headers=outbound_headers(),
                        **kwargs,
                    )
                except httpx.TimeoutException as e:
                    raise SandboxTransientError(f"{what} timed out: {e}") from e
                except httpx.TransportError as e:
                    raise SandboxTransientError(f"{what} failed: {e}") from e
                if response.status_code != 200:
                    raise classify_http_status(
                        response.status_code, f"{what} ({response.text[:200]})"
                    )
        return response.json()

    async def _post_execute_stream(
        self,
        addr: str,
        source_code: str,
        env: dict[str, str],
        timeout_s: float,
        on_event=None,  # async (kind, text) -> None, called per output chunk
        deadline: Deadline | None = None,
    ) -> dict:
        """Streaming twin of :meth:`_post_execute`: drives the sandbox's
        ``POST /execute/stream`` ndjson wire, forwarding each output chunk
        to ``on_event`` as it arrives and returning the terminal envelope
        (same dict shape the non-streaming call returns). The configured
        per-call HTTP timeout applies *between* chunks (httpx read timeout),
        so a silent sandbox still fails transient — a chatty long run keeps
        the stream alive the way a long response body would."""
        what = f"streaming execute on {addr}"
        kwargs: dict = {}
        if deadline is not None:
            deadline.check(what)
            timeout_s = deadline.clamp(timeout_s)
            kwargs["timeout"] = deadline.clamp(
                self._config.executor_http_timeout_s
            )
        body = {
            "source_code": source_code,
            "env": env,
            "timeout": timeout_s,
        }
        deps = predicted_deps()
        if deps is not None:
            body["predicted_deps"] = deps
        end: dict | None = None
        unsupported = False
        with span("execute", addr=addr, stream="1"):
            async with self._data_plane_guard():
                try:
                    async with self._http.stream(
                        "POST",
                        f"http://{addr}/execute/stream",
                        json=body,
                        headers=outbound_headers(),
                        **kwargs,
                    ) as response:
                        if response.status_code in (404, 405):
                            # Executor predates the stream route (native C++
                            # server); fall back to the buffered call OUTSIDE
                            # this breaker guard (nesting would double-count
                            # the half-open slot).
                            await response.aread()
                            unsupported = True
                        elif response.status_code != 200:
                            await response.aread()
                            raise classify_http_status(
                                response.status_code,
                                f"{what} ({response.text[:200]})",
                            )
                        else:
                            async for line in response.aiter_lines():
                                if not line.strip():
                                    continue
                                event = json.loads(line)
                                if event.get("event") == "end":
                                    end = event
                                elif on_event is not None:
                                    await on_event(
                                        event["stream"], event["data"]
                                    )
                except httpx.TimeoutException as e:
                    raise SandboxTransientError(f"{what} timed out: {e}") from e
                except httpx.TransportError as e:
                    raise SandboxTransientError(f"{what} failed: {e}") from e
                except (json.JSONDecodeError, KeyError) as e:
                    raise SandboxTransientError(
                        f"{what} produced a malformed event: {e}"
                    ) from e
        if unsupported:
            # Degraded delivery: one buffered run, whole output as a single
            # chunk per stream, exact terminal envelope either way.
            end = await self._post_execute(
                addr, source_code, env, timeout_s, deadline=deadline
            )
            if on_event is not None:
                for kind in ("stdout", "stderr"):
                    if end.get(kind):
                        await on_event(kind, end[kind])
            return end
        if end is None:
            # The connection closed without a terminal envelope: the sandbox
            # died mid-stream. Transient — the SANDBOX is gone, but the
            # caller decides whether a replay is safe (it is not once chunks
            # reached a client).
            raise SandboxTransientError(f"{what} ended without a terminal event")
        return end

    async def _delete_file(
        self, addr: str, path: str, deadline: Deadline | None = None
    ) -> bool:
        """Best-effort workspace file removal (session rollback). True when
        the file was deleted, False when the sandbox doesn't have it — or
        doesn't speak DELETE at all (404/405 from older executors): rollback
        then restores checkpoint content but cannot evict strays."""
        what = f"file delete on {addr}"
        kwargs = self._deadline_kwargs(deadline, what)
        with span("delete", addr=addr, path=path):
            async with self._data_plane_guard():
                try:
                    response = await self._http.delete(
                        self._sandbox_url(addr, path),
                        headers=outbound_headers(),
                        **kwargs,
                    )
                except httpx.TimeoutException as e:
                    raise SandboxTransientError(f"{what} timed out: {e}") from e
                except httpx.TransportError as e:
                    raise SandboxTransientError(f"{what} failed: {e}") from e
                if response.status_code in (404, 405):
                    return False
                if response.status_code >= 300:
                    raise classify_http_status(response.status_code, what)
        return True

    def _sandbox_url(self, addr: str, logical_path: str) -> str:
        rel = logical_path.removeprefix("/workspace/").lstrip("/")
        return f"http://{addr}/workspace/{rel}"
