"""Shared HTTP data-plane driver for executor sandboxes.

Both sandbox backends — Kubernetes pod groups and local native-server
processes — speak the same wire contract (reference executor/server.rs:186-192;
ours executor/src/server.cpp): ``PUT/GET /workspace/{path}`` for the workspace
snapshot and ``POST /execute`` for the run. This mixin holds the driver side of
that contract (reference kubernetes_code_executor.py:95-142), addressed by
``host:port`` so the transport is identical whether the sandbox is across the
pod network or on localhost.
"""

from __future__ import annotations

import httpx

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.services.storage import Storage
from bee_code_interpreter_tpu.utils.validation import Hash


class ExecutorHttpDriver:
    """Mixin: requires ``self._http`` (httpx.AsyncClient) and ``self._storage``."""

    _http: httpx.AsyncClient
    _storage: Storage
    _config: Config

    async def _upload_file(self, addr: str, path: str, object_id: Hash) -> None:
        async def body():
            async with self._storage.reader(object_id) as reader:
                async for chunk in reader:
                    yield chunk

        response = await self._http.put(self._sandbox_url(addr, path), content=body())
        if response.status_code >= 300:
            raise RuntimeError(f"file upload to {addr} failed: {response.status_code}")

    async def _download_file(self, addr: str, path: str) -> Hash:
        async with self._storage.writer() as writer:
            async with self._http.stream(
                "GET", self._sandbox_url(addr, path)
            ) as response:
                if response.status_code >= 300:
                    raise RuntimeError(
                        f"file download from {addr} failed: {response.status_code}"
                    )
                async for chunk in response.aiter_bytes():
                    await writer.write(chunk)
        return writer.hash

    def _effective_timeout(self, timeout_s: float | None) -> float:
        """A request may shorten the execution deadline, never extend it past
        the service-configured bound (requires ``self._config``)."""
        bound = self._config.execution_timeout_s
        if timeout_s is None or timeout_s <= 0:
            return bound
        return min(timeout_s, bound)

    async def _post_execute(
        self,
        addr: str,
        source_code: str,
        env: dict[str, str],
        timeout_s: float,
        client_timeout_s: float | None = None,
    ) -> dict:
        """``client_timeout_s`` overrides the shared client's read timeout
        for this one request — used when the sandbox was dispatched before
        its warm worker finished preloading, so the preload tail counts
        against the HTTP budget and needs headroom over ``timeout_s``."""
        kwargs: dict = {}
        if client_timeout_s is not None:
            kwargs["timeout"] = client_timeout_s
        response = await self._http.post(
            f"http://{addr}/execute",
            json={"source_code": source_code, "env": env, "timeout": timeout_s},
            **kwargs,
        )
        if response.status_code != 200:
            raise RuntimeError(
                f"execute on {addr} failed: {response.status_code} {response.text}"
            )
        return response.json()

    def _sandbox_url(self, addr: str, logical_path: str) -> str:
        rel = logical_path.removeprefix("/workspace/").lstrip("/")
        return f"http://{addr}/workspace/{rel}"
