"""Custom-tool parsing and execution.

Capability parity with the reference's CustomToolExecutor
(src/code_interpreter/services/custom_tool_executor.py:27-296), implemented
fresh from the behavioral contract pinned by the reference e2e suite
(test/e2e/test_http.py:100-302):

- ``parse``: pure control-plane AST analysis of a single-function tool source —
  structural validation, exact rejection messages for positional-only args /
  ``*args`` / ``**kwargs`` / missing annotations, ReST docstring field parsing
  (interleaved ``:param:``/``:return:`` fields, multi-line descriptions), and a
  draft-07 JSON Schema for the call arguments generated through pydantic with a
  draft-07 tuple form (``items`` list + ``additionalItems: false``).
- ``execute``: synthesizes a wrapper script (user imports hoisted to the top so
  the dependency guesser sees them), runs it through the sandbox code executor,
  validates/coerces the JSON input against the tool's type hints via pydantic
  inside the sandbox, suppresses tool-body stdout, and prints the
  JSON-serialized result as the script's only stdout.

Type-annotation evaluation is sandboxed: only ``typing``, ``pathlib`` and
``datetime`` imports contribute to the eval namespace, and the annotation AST is
whitelist-checked before eval (reference :223-296).
"""

from __future__ import annotations

import ast
import importlib
import json
import re
import textwrap
from dataclasses import dataclass, field
from typing import Any

import pydantic
from pydantic.json_schema import GenerateJsonSchema

from bee_code_interpreter_tpu.services.code_executor import CodeExecutor

ALLOWED_ANNOTATION_MODULES = frozenset({"typing", "pathlib", "datetime"})

_BUILTIN_TYPES: dict[str, Any] = {
    "int": int, "float": float, "str": str, "bool": bool, "bytes": bytes,
    "list": list, "dict": dict, "tuple": tuple, "set": set, "frozenset": frozenset,
    "None": None, "type": type, "object": object, "complex": complex,
}


class CustomToolParseError(Exception):
    def __init__(self, error_messages: list[str]) -> None:
        super().__init__("; ".join(error_messages))
        self.error_messages = error_messages


class CustomToolExecuteError(Exception):
    """Tool ran but exited nonzero; ``stderr`` carries the failure."""

    def __init__(self, stderr: str) -> None:
        super().__init__(stderr)
        self.stderr = stderr


@dataclass
class CustomTool:
    name: str
    description: str
    input_schema: dict[str, Any]


@dataclass
class _Docstring:
    body: str = ""
    params: dict[str, str] = field(default_factory=dict)
    returns: str = ""


class _Draft7JsonSchema(GenerateJsonSchema):
    """pydantic schema generation in JSON Schema draft-07 dialect.

    pydantic v2 emits 2020-12 ``prefixItems`` tuples; the wire contract (pinned
    by reference test_http.py:144-152) is the draft-07 positional-``items`` form.
    """

    schema_dialect = "http://json-schema.org/draft-07/schema#"

    def tuple_schema(self, schema):  # type: ignore[override]
        out = super().tuple_schema(schema)
        if "prefixItems" in out:
            out["items"] = out.pop("prefixItems")
            out.pop("maxItems", None)
            out["additionalItems"] = False
        return out


_FIELD_RE = re.compile(r"^:(?:param\s+(?P<name>\w+)|returns?):\s?(?P<rest>.*)$")


def _parse_docstring(raw: str | None) -> _Docstring:
    """ReST-style docstring parser: free-form body, then interleaved
    ``:param name:`` / ``:return:`` fields whose descriptions may span lines
    (continuations joined with a newline; pinned by test_http.py:116-124,136-141).
    """
    if not raw:
        return _Docstring()
    import inspect

    doc = _Docstring()
    body_lines: list[str] = []
    fields: list[tuple[str | None, list[str]]] = []  # (param name | None=return, lines)
    for line in inspect.cleandoc(raw).splitlines():
        m = _FIELD_RE.match(line.strip())
        if m:
            fields.append((m.group("name"), [m.group("rest").strip()]))
        elif fields:
            fields[-1][1].append(line)
        else:
            body_lines.append(line)
    doc.body = "\n".join(body_lines).strip()
    for name, acc in fields:
        text = "\n".join(acc).strip()
        if name is None:
            doc.returns = text
        else:
            doc.params[name] = text
    return doc


def _is_safe_type_ast(node: ast.AST) -> bool:
    """Whitelist check on annotation expressions before eval (reference :277-296)."""
    if isinstance(node, ast.Name):
        return True
    if isinstance(node, ast.Attribute):
        return _is_safe_type_ast(node.value)
    if isinstance(node, ast.Subscript):
        return _is_safe_type_ast(node.value) and _is_safe_type_ast(node.slice)
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_is_safe_type_ast(e) for e in node.elts)
    if isinstance(node, ast.Constant):
        return node.value is None or node.value is Ellipsis or isinstance(node.value, str)
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitOr):
        return _is_safe_type_ast(node.left) and _is_safe_type_ast(node.right)
    return False


def _build_namespace(import_nodes: list[ast.Import | ast.ImportFrom]) -> dict[str, Any]:
    """Eval namespace from the tool's imports, restricted to safe modules.

    Imports of other modules (e.g. ``requests``) are silently ignored for
    annotation purposes — they exist for the tool body, not the signature
    (reference :223-249; behavior pinned by test_http.py:171-189).
    """
    ns: dict[str, Any] = dict(_BUILTIN_TYPES)
    for node in import_nodes:
        if isinstance(node, ast.Import):
            for alias in node.names:
                top = alias.name.split(".")[0]
                if top not in ALLOWED_ANNOTATION_MODULES:
                    continue
                module = importlib.import_module(alias.name)
                if alias.asname:
                    ns[alias.asname] = module
                else:
                    ns[top] = importlib.import_module(top)
        elif isinstance(node, ast.ImportFrom):
            if node.level != 0 or not node.module:
                continue
            if node.module.split(".")[0] not in ALLOWED_ANNOTATION_MODULES:
                continue
            module = importlib.import_module(node.module)
            for alias in node.names:
                ns[alias.asname or alias.name] = getattr(module, alias.name)
    return ns


class CustomToolExecutor:
    def __init__(self, code_executor: CodeExecutor) -> None:
        self._code_executor = code_executor

    # ------------------------------------------------------------------ parse

    def parse(self, tool_source_code: str) -> CustomTool:
        tool, _imports = self._parse_validated(tool_source_code)
        return tool

    def _parse_validated(
        self, tool_source_code: str
    ) -> tuple[CustomTool, list[ast.Import | ast.ImportFrom]]:
        # Uniformly indented source (an agent lifting a method out of a larger
        # file) must parse — the reference dedents before parsing
        # (/root/reference/src/code_interpreter/services/custom_tool_executor.py:59).
        try:
            tree = ast.parse(textwrap.dedent(tool_source_code))
        except SyntaxError as e:
            raise CustomToolParseError([f"Syntax error: {e.msg} (line {e.lineno})"]) from e

        imports: list[ast.Import | ast.ImportFrom] = []
        functions: list[ast.FunctionDef | ast.AsyncFunctionDef] = []
        for node in tree.body:
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                imports.append(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                functions.append(node)
            else:
                raise CustomToolParseError(
                    ["The tool source code must only contain a single function definition "
                     "and imports"]
                )
        if len(functions) != 1:
            raise CustomToolParseError(
                ["The tool source code must contain exactly one function definition"]
            )
        fn = functions[0]

        # Argument-form validation; messages pinned by reference
        # test_http.py:257-271.
        errors: list[str] = []
        if fn.args.posonlyargs:
            errors.append("The tool function must not have positional-only arguments")
        if fn.args.vararg:
            errors.append("The tool function must not have *args")
        if fn.args.kwarg:
            errors.append("The tool function must not have **kwargs")
        all_args = [*fn.args.args, *fn.args.kwonlyargs]
        if any(a.annotation is None for a in all_args):
            errors.append("The tool function arguments must have type annotations")
        if errors:
            raise CustomToolParseError(errors)

        doc = _parse_docstring(ast.get_docstring(fn, clean=False))
        namespace = _build_namespace(imports)

        properties: dict[str, Any] = {}
        required: list[str] = []
        # Defaults align right-to-left with fn.args.args; kwonly defaults align
        # with kwonlyargs positionally (None = no default).
        n_pos_defaults = len(fn.args.defaults)
        pos_with_default = {a.arg for a in fn.args.args[len(fn.args.args) - n_pos_defaults:]}
        kw_with_default = {
            a.arg for a, d in zip(fn.args.kwonlyargs, fn.args.kw_defaults) if d is not None
        }
        for arg in all_args:
            schema = self._type_to_json_schema(arg.annotation, namespace)
            if arg.arg in doc.params and doc.params[arg.arg]:
                schema["description"] = doc.params[arg.arg]
            properties[arg.arg] = schema
            if arg.arg not in pos_with_default and arg.arg not in kw_with_default:
                required.append(arg.arg)

        input_schema = {
            "$schema": "http://json-schema.org/draft-07/schema#",
            "type": "object",
            "title": fn.name,
            "properties": properties,
            "required": required,
            "additionalProperties": False,
        }

        description = doc.body
        return_type = ast.unparse(fn.returns) if fn.returns is not None else ""
        # "Returns:" suffix rules pinned by test_http.py:131-135 (type -- desc)
        # and :196-199 (desc only, no annotation).
        if return_type and doc.returns:
            suffix = f"Returns: {return_type} -- {doc.returns}"
        elif doc.returns:
            suffix = f"Returns: {doc.returns}"
        elif return_type:
            suffix = f"Returns: {return_type}"
        else:
            suffix = ""
        if suffix:
            description = f"{description}\n\n{suffix}" if description else suffix

        return (
            CustomTool(name=fn.name, description=description, input_schema=input_schema),
            imports,
        )

    def _type_to_json_schema(self, annotation: ast.expr, namespace: dict[str, Any]) -> dict:
        if not _is_safe_type_ast(annotation):
            raise CustomToolParseError(
                [f"Unsafe or unsupported type annotation: {ast.unparse(annotation)}"]
            )
        try:
            type_obj = eval(  # noqa: S307 — AST whitelist-checked, empty builtins
                compile(ast.Expression(annotation), "<annotation>", "eval"),
                {"__builtins__": {}},
                namespace,
            )
        except Exception as e:
            raise CustomToolParseError(
                [f"Unable to evaluate type annotation: {ast.unparse(annotation)}"]
            ) from e
        try:
            schema = pydantic.TypeAdapter(type_obj).json_schema(
                schema_generator=_Draft7JsonSchema, mode="validation"
            )
        except Exception as e:
            raise CustomToolParseError(
                [f"Type not expressible as JSON schema: {ast.unparse(annotation)}"]
            ) from e
        schema.pop("$schema", None)
        return schema

    # ---------------------------------------------------------------- execute

    async def execute(
        self,
        tool_source_code: str,
        tool_input_json: str,
        env: dict[str, str] | None = None,
        deadline=None,
    ) -> Any:
        """Run the tool in the sandbox; returns the (JSON-decodable) output value."""
        tool_source_code = textwrap.dedent(tool_source_code)
        tool, imports = self._parse_validated(tool_source_code)
        import_lines = "\n".join(ast.unparse(n) for n in imports)

        # Wrapper design (reference :157-195): imports hoisted verbatim so the
        # sandbox's dependency guesser sees them; tool exec'd in fresh globals;
        # input coerced per type hint with pydantic (datetime coercion pinned by
        # test_http.py:238-254); tool-body stdout suppressed; result printed as
        # the script's sole stdout.
        wrapper = f"""\
{import_lines}
import asyncio as _asyncio, contextlib as _contextlib, inspect as _inspect
import json as _json, sys as _sys, typing as _typing
import pydantic as _pydantic

_SOURCE = {tool_source_code!r}
_INPUT = {tool_input_json!r}
_NAME = {tool.name!r}

_globals = {{}}
with _contextlib.redirect_stdout(None):
    exec(compile(_SOURCE, "<tool>", "exec"), _globals)
    _fn = _globals[_NAME]
    try:
        _hints = _typing.get_type_hints(_fn)
    except Exception:
        _hints = {{}}
    _kwargs = {{}}
    for _k, _v in _json.loads(_INPUT).items():
        if _k in _hints:
            _kwargs[_k] = _pydantic.TypeAdapter(_hints[_k]).validate_python(_v)
        else:
            _kwargs[_k] = _v
    _result = _fn(**_kwargs)
    if _inspect.iscoroutine(_result):  # async def tools are supported
        _result = _asyncio.run(_result)

def _default(o):
    try:
        return _pydantic.TypeAdapter(type(o)).dump_python(o, mode="json")
    except Exception:
        return str(o)

print(_json.dumps(_result, default=_default))
"""
        result = await self._code_executor.execute(
            source_code=wrapper, env=env or {}, deadline=deadline
        )
        if result.exit_code != 0:
            raise CustomToolExecuteError(result.stderr)
        return json.loads(result.stdout)
