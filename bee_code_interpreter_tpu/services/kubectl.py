"""Async wrapper over the ``kubectl`` CLI.

Same "dumb wrapper" philosophy as the reference (services/kubectl.py:24-41):
no kubernetes-client dependency, just subprocess + JSON. Method name becomes
the subcommand (underscores → dashes), kwargs become ``--key=value`` flags,
positional args pass through; commands whose output kubectl can render as JSON
get ``--output=json`` added and parsed (reference :99-131 vs :133-178).

    pod = await kubectl.get("pod", "my-pod")              # parsed JSON
    await kubectl.wait("pod/my-pod", for_="condition=Ready", timeout="60s")
    await kubectl.delete("pod", "my-pod", ignore_not_found="true")

Trailing-underscore kwargs (``for_``) drop the underscore so reserved words
work. ``exec_raw`` returns the live process for streaming (reference :190-193).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Any

logger = logging.getLogger(__name__)

# Subcommands that accept -o json and return an object (reference kubectl.py:99-131).
JSON_OUTPUT_COMMANDS = frozenset(
    {"get", "create", "apply", "delete", "patch", "label", "annotate", "expose",
     "run", "scale", "wait"}
)


class KubectlError(RuntimeError):
    def __init__(self, argv: list[str], returncode: int, stderr: str) -> None:
        super().__init__(f"kubectl {' '.join(argv)} failed ({returncode}): {stderr.strip()}")
        self.argv = argv
        self.returncode = returncode
        self.stderr = stderr


class Kubectl:
    def __init__(self, kubectl_path: str = "kubectl", namespace: str | None = None) -> None:
        self._kubectl = kubectl_path
        self._namespace = namespace

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)

        async def run(*args: str, _input: str | bytes | None = None, **kwargs: Any):
            return await self._run(name.replace("_", "-"), *args, _input=_input, **kwargs)

        run.__name__ = name
        return run

    async def _run(
        self, command: str, *args: str, _input: str | bytes | None = None, **kwargs: Any
    ):
        argv = [command, *args]
        json_output = command in JSON_OUTPUT_COMMANDS and "output" not in kwargs
        if json_output:
            argv.append("--output=json")
        if self._namespace:
            argv.append(f"--namespace={self._namespace}")
        for key, value in kwargs.items():
            flag = key.rstrip("_").replace("_", "-")
            argv.append(f"--{flag}={value}")
        logger.info("kubectl %s", " ".join(argv))
        proc = await asyncio.create_subprocess_exec(
            self._kubectl, *argv,
            stdin=asyncio.subprocess.PIPE if _input is not None else None,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        if isinstance(_input, str):
            _input = _input.encode()
        stdout, stderr = await proc.communicate(_input)
        if proc.returncode != 0:
            raise KubectlError(argv, proc.returncode, stderr.decode(errors="replace"))
        text = stdout.decode(errors="replace")
        if json_output and text.strip():
            try:
                return json.loads(text)
            except json.JSONDecodeError:
                return text
        return text

    async def exec_raw(self, *args: str) -> asyncio.subprocess.Process:
        """Live process for streaming use (reference kubectl.py:190-193)."""
        return await asyncio.create_subprocess_exec(
            self._kubectl, "exec", *args,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
