"""Kubernetes code executor: warm TPU pod-group pool + remote execution driver.

The heart of the service, rebuilt TPU-first from the reference's
KubernetesCodeExecutor (kubernetes_code_executor.py:39-264). The schedulable
unit here is a **pod group** — one executor pod per TPU host of a slice
(SURVEY.md §2 "Parallelism strategies": multi-host slices need gang semantics
the reference never had). A single-host slice is simply a group of one, which
degenerates to exactly the reference's behavior.

Lifecycle (mirrors reference :151-264, generalized to groups):

- A deque of *Ready* pod groups is kept at a target length; refills happen
  asynchronously with spawning-count accounting so concurrent refills don't
  overshoot.
- Spawning a multi-host group: worker-0 pod is created first and its pod IP
  becomes the ``jax.distributed`` coordinator address baked into workers 1..N-1
  (created concurrently); then the whole group is awaited Ready all-or-nothing,
  and any failure tears down every member (gang semantics).
- Every pod carries ``ownerReferences`` to the service's own pod so Kubernetes
  garbage-collects orphans if the service dies (reference :215-224).
- Groups are **single-use**: after one execution the group is deleted
  fire-and-forget and the pool refilled (reference :248-264) — TPU state never
  leaks between executions.

Execution drives all workers SPMD-style: input files are uploaded to every
worker, ``POST /execute`` fires on all workers concurrently (every JAX process
must run the same program), and the result is worker 0's stdout/stderr (JAX
convention: process 0 owns I/O), with exit_code the first nonzero across
workers. Changed files are the **union across the gang** — per-host outputs
(e.g. orbax sharded checkpoint shards) exist only on their writer, so each
path is downloaded from the first worker that reported it (worker 0 wins
collisions on shared names) and streamed into content-addressed storage.

Resilience (docs/resilience.md): the request ``Deadline`` bounds every
downstream call; transient data-plane failures retry under a config-driven
``RetryPolicy`` (5xx/timeouts only — a 4xx is final); spawn and the HTTP data
plane each sit behind a ``CircuitBreaker`` so a flapping apiserver or pod
network fails fast (and can degrade to the local executor) instead of
queueing unboundedly.
"""

from __future__ import annotations

import asyncio
import logging
import os
import secrets
from collections import deque
from contextlib import asynccontextmanager
from dataclasses import dataclass

import httpx

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.observability import (
    FleetJournal,
    collect_transfer,
    merge_worker_usage,
    span,
)
from bee_code_interpreter_tpu.resilience import (
    BreakerState,
    CircuitBreaker,
    Deadline,
    DeadlineExceeded,
    InflightRegistry,
    RetryPolicy,
    SandboxFatalError,
    SandboxTransientError,
    journal_sandbox_teardown,
    retryable,
)
from bee_code_interpreter_tpu.services.code_executor import LeaseHandle, Result
from bee_code_interpreter_tpu.services.executor_http_driver import ExecutorHttpDriver
from bee_code_interpreter_tpu.services.kubectl import Kubectl
from bee_code_interpreter_tpu.services.storage import Storage
from bee_code_interpreter_tpu.utils.metrics import Registry
from bee_code_interpreter_tpu.utils.validation import AbsolutePath, Hash

logger = logging.getLogger(__name__)

JAX_COORDINATOR_PORT = 8476


@dataclass
class PodGroup:
    """One schedulable sandbox: a gang of executor pods spanning a TPU slice."""

    name: str
    pods: list[dict]  # pod JSON objects; index == worker id

    @property
    def pod_names(self) -> list[str]:
        return [p["metadata"]["name"] for p in self.pods]

    @property
    def pod_ips(self) -> list[str]:
        return [p["status"]["podIP"] for p in self.pods]


class KubernetesCodeExecutor(ExecutorHttpDriver):
    def __init__(
        self,
        kubectl: Kubectl,
        storage: Storage,
        config: Config,
        http_client: httpx.AsyncClient | None = None,
        metrics: Registry | None = None,
        spawn_breaker: CircuitBreaker | None = None,
        http_breaker: CircuitBreaker | None = None,
        ip_poll_interval_s: float = 1.0,
        journal: FleetJournal | None = None,
    ) -> None:
        self._kubectl = kubectl
        self._storage = storage
        self._config = config
        self._http = http_client or httpx.AsyncClient(
            timeout=config.executor_http_timeout_s
        )
        self._queue: deque[PodGroup] = deque()
        self._spawning_count = 0
        self._fill_lock = asyncio.Lock()
        self._self_pod: dict | None = None
        self._ip_poll_interval_s = ip_poll_interval_s
        # The event loop holds only weak refs to tasks; fire-and-forget refills
        # and deletions must be anchored here or GC can cancel them mid-flight.
        self._background_tasks: set[asyncio.Task] = set()
        # Executions in flight, killable by the supervisor's stuck-execution
        # watchdog (resilience/supervisor.py).
        self.inflight = InflightRegistry()
        # Dynamic warm-pool target (docs/autoscaling.md): the PoolAutoscaler
        # writes this in APP_AUTOSCALE_MODE=act; None means the static
        # configured target. Every refill reads `pool_target`.
        self.pool_target_override: int | None = None
        self._closed = False

        self._metrics = metrics
        # Lifecycle journal (docs/observability.md): every pod-group
        # transition lands here; served at GET /v1/fleet[/events].
        # `is None`, not truthiness: an empty journal is len()==0 — falsy —
        # and replacing the injected one would strand /v1/fleet on a twin.
        self.journal = (
            journal if journal is not None else FleetJournal(metrics=metrics)
        )
        self._retry_counter = (
            metrics.counter(
                "bci_executor_retry_attempts_total",
                "Retry attempts by operation",
            )
            if metrics is not None
            else None
        )
        self._breaker_transitions = (
            metrics.counter(
                "bci_breaker_transitions_total",
                "Circuit breaker state transitions",
            )
            if metrics is not None
            else None
        )
        # Recent (op, sleep_s) backoffs, for tests/diagnostics of the schedule.
        self.retry_backoffs: list[tuple[str, float]] = []

        self._execute_retry = RetryPolicy(
            attempts=config.executor_retry_attempts,
            wait_min_s=config.executor_retry_wait_min_s,
            wait_max_s=config.executor_retry_wait_max_s,
            retry_on=(SandboxTransientError,),
        )
        self._spawn_retry = RetryPolicy(
            attempts=config.executor_retry_attempts,
            wait_min_s=config.executor_retry_wait_min_s,
            wait_max_s=config.executor_retry_wait_max_s,
            retry_on=(RuntimeError,),
        )
        self.spawn_breaker = spawn_breaker or self._make_breaker("k8s-spawn")
        self.http_breaker = http_breaker or self._make_breaker(
            "k8s-http",
            # A 4xx means the sandbox answered — the data plane is healthy.
            is_failure=lambda e: not isinstance(e, SandboxFatalError),
        )
        self._http_breaker = self.http_breaker  # ExecutorHttpDriver hook
        for breaker in (self.spawn_breaker, self.http_breaker):
            # Externally constructed breakers (tests inject a ManualClock)
            # still get transitions recorded into this executor's metrics.
            if breaker.on_transition is None:
                breaker.on_transition = self._record_breaker_transition

    def _make_breaker(self, name: str, **kwargs) -> CircuitBreaker:
        cfg = self._config
        return CircuitBreaker(
            name,
            window=cfg.breaker_window,
            failure_rate_threshold=cfg.breaker_failure_rate_threshold,
            min_calls=cfg.breaker_min_calls,
            cooldown_s=cfg.breaker_cooldown_s,
            half_open_max_calls=cfg.breaker_half_open_max_calls,
            on_transition=self._record_breaker_transition,
            **kwargs,
        )

    def _record_breaker_transition(self, name: str, state: BreakerState) -> None:
        logger.warning("Circuit breaker %r -> %s", name, state.name)
        if self._breaker_transitions is not None:
            self._breaker_transitions.inc(breaker=name, to=state.name.lower())

    def _on_retry_backoff(self, op, attempt, sleep_s, exc) -> None:
        self.retry_backoffs.append((op, sleep_s))
        if self._retry_counter is not None:
            self._retry_counter.inc(op=op)

    @property
    def pool_ready_count(self) -> int:
        """Warm pod groups ready to serve (metrics/introspection)."""
        return len(self._queue)

    @property
    def pool_spawning_count(self) -> int:
        """Pod groups currently being spawned (metrics/introspection)."""
        return self._spawning_count

    @property
    def pool_target(self) -> int:
        """The refill target: the autoscaler's override when one is
        actuated, the static configured length otherwise."""
        if self.pool_target_override is not None:
            return self.pool_target_override
        return self._config.executor_pod_queue_target_length

    # ------------------------------------------------------------- execution

    @retryable("_execute_retry", op="execute")
    async def execute(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        deadline: Deadline | None = None,
    ) -> Result:
        files = files or {}
        env = env or {}
        if deadline is not None:
            deadline.check("execute")
        # Ambient byte-accounting scope for this execution (sync contextvars;
        # the driver's upload/download calls report into it).
        with collect_transfer() as transfer:
            return await self._execute_on_group(
                source_code, files, env, timeout_s, deadline, transfer
            )

    async def _execute_on_group(
        self, source_code, files, env, timeout_s, deadline, transfer
    ) -> Result:
        async with self.executor_pod_group(deadline=deadline) as group:
            addrs = self._group_addrs(group)
            # Restore the workspace snapshot on every worker (SPMD inputs).
            await asyncio.gather(
                *(
                    self._upload_file(addr, path, object_id, deadline=deadline)
                    for addr in addrs
                    for path, object_id in files.items()
                )
            )
            self.journal.record(group.name, "executing")
            # Run on all workers concurrently; every JAX process must execute
            # the same program for collectives to rendezvous. Tracked so the
            # supervisor watchdog can kill a wedged group: the kill tears the
            # pods down and this gather fails as transient (hung_execute).
            with self.inflight.track(
                group.name, kill=lambda: self._kill_group(group)
            ):
                responses = await asyncio.gather(
                    *(
                        self._post_execute(
                            addr,
                            source_code,
                            env,
                            self._effective_timeout(timeout_s),
                            deadline=deadline,
                        )
                        for addr in addrs
                    )
                )
            return await self._assemble_group_result(
                addrs, responses, transfer, deadline
            )

    def _group_addrs(self, group: PodGroup) -> list[str]:
        return [f"{ip}:{self._config.executor_port}" for ip in group.pod_ips]

    async def _assemble_group_result(
        self, addrs, responses, transfer, deadline
    ) -> Result:
        """Gang responses → one :class:`Result`: worker 0's stdout/stderr
        (process-0-owns-I/O convention), first nonzero exit code, changed
        files unioned across the gang (each path downloaded from its writer;
        worker 0 wins collisions on shared names), usage merged."""
        primary = responses[0]
        exit_code = next(
            (r["exit_code"] for r in responses if r["exit_code"] != 0), 0
        )
        path_owner: dict[str, str] = {}
        for addr, response in zip(addrs, responses):
            for path in response["files"]:
                path_owner.setdefault(path, addr)
        out_files = dict(
            zip(
                path_owner,
                await asyncio.gather(
                    *(
                        self._download_file(addr, path, deadline=deadline)
                        for path, addr in path_owner.items()
                    )
                ),
            )
        )
        # Gang usage: CPU sums, RSS/wall max across workers; the
        # driver's data-plane byte counts ride in the same block.
        usage = merge_worker_usage([r.get("usage") for r in responses])
        usage.update(transfer.as_dict())
        return Result(
            stdout=primary["stdout"],
            stderr=primary["stderr"],
            exit_code=exit_code,
            files=out_files,
            usage=usage,
        )

    async def execute_stream(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        on_event=None,  # async (kind, text) -> None per stdout/stderr chunk
        deadline: Deadline | None = None,
    ) -> Result:
        """Streaming execute (docs/sessions.md "Streaming"): same single-use
        sandbox lifecycle as :meth:`execute`, but worker 0's output chunks
        are forwarded to ``on_event`` as the sandbox produces them (workers
        1..N-1 run the regular call concurrently — the I/O convention already
        makes worker 0 the only stdout that matters). No retry/replay/hedge
        layer wraps this path: chunks already delivered to a client cannot
        be un-delivered, so a mid-stream death surfaces as an error event,
        never as a silent second run."""
        files = files or {}
        env = env or {}
        if deadline is not None:
            deadline.check("execute")
        with collect_transfer() as transfer:
            async with self.executor_pod_group(deadline=deadline) as group:
                addrs = self._group_addrs(group)
                await asyncio.gather(
                    *(
                        self._upload_file(addr, path, object_id, deadline=deadline)
                        for addr in addrs
                        for path, object_id in files.items()
                    )
                )
                self.journal.record(group.name, "executing")
                timeout = self._effective_timeout(timeout_s)
                with self.inflight.track(
                    group.name, kill=lambda: self._kill_group(group)
                ):
                    responses = await asyncio.gather(
                        self._post_execute_stream(
                            addrs[0],
                            source_code,
                            env,
                            timeout,
                            on_event=on_event,
                            deadline=deadline,
                        ),
                        *(
                            self._post_execute(
                                addr, source_code, env, timeout, deadline=deadline
                            )
                            for addr in addrs[1:]
                        ),
                    )
                return await self._assemble_group_result(
                    addrs, list(responses), transfer, deadline
                )

    # ------------------------------------------------------------------ pool

    @asynccontextmanager
    async def executor_pod_group(self, deadline: Deadline | None = None):
        """Pop a warm group or spawn one; single-use teardown + async refill
        (reference executor_pod ctx-mgr :248-264).

        Preemption-aware (SURVEY.md §5: v5e pods are preemptible): a popped
        group is health-probed before use — a group whose pod was preempted or
        OOM-killed while queued is torn down and skipped instead of burning a
        request attempt on it.

        On-demand spawns (pool empty) go through the spawn circuit breaker
        and are hard-bounded by the request deadline.
        """
        group = await self._checkout_group(deadline)
        try:
            yield group
        except BaseException as e:
            # A transient data-plane failure means the sandbox is presumed
            # dead or wedged (a pod dying mid-execute lands here); the
            # journal reason is what the replay acceptance asserts on.
            journal_sandbox_teardown(self.journal, group.name, e)
            raise
        else:
            journal_sandbox_teardown(self.journal, group.name, None)
        finally:
            self._kill_group(group)

    async def _checkout_group(self, deadline: Deadline | None = None) -> PodGroup:
        """Pop a healthy warm group (probing and discarding corpses) or spawn
        one, journal the assignment, and kick a refill — the acquisition half
        shared by the single-use execute path and session leases."""
        group = None
        while group is None:
            if not self._queue:
                group = await self._spawn_guarded(deadline)
                self.journal.record(group.name, "assigned", reason="cold_spawn")
                break
            candidate = self._queue.popleft()
            try:
                healthy = await self._group_healthy(candidate, deadline=deadline)
            except DeadlineExceeded:
                # The request ran out of budget mid-probe: hand the
                # (unjudged) group back to the pool instead of leaking it.
                self._queue.appendleft(candidate)
                raise
            if healthy:
                group = candidate
                self.journal.record(group.name, "assigned", reason="warm_pop")
            else:
                logger.warning(
                    "Warm pod group %s unhealthy (preempted?); discarding",
                    candidate.name,
                )
                self.journal.record(candidate.name, "reaped", reason="unhealthy")
                self._kill_group(candidate)
        self._spawn_background(self.fill_executor_pod_queue())
        return group

    # ---------------------------------------------------------------- leases

    async def checkout_for_lease(
        self, deadline: Deadline | None = None
    ) -> LeaseHandle:
        """Check a warm group out of the pool for a session lease
        (docs/sessions.md): the holder owns it across N executions. Popped
        out of the queue, so the supervisor's idle reaper never probes it,
        and nothing is in the inflight registry while it idles between
        executes — an owned sandbox is not "stuck"."""
        group = await self._checkout_group(deadline)
        return LeaseHandle(
            name=group.name,
            addrs=self._group_addrs(group),
            kill=lambda: self._kill_group(group),
            handle=group,
        )

    def release_lease(
        self,
        lease: LeaseHandle,
        state: str = "released",
        reason: str = "lease_released",
        detail: str | None = None,
    ) -> None:
        """End a lease: one terminal journal event with the real reason
        (released / lease_expired / reaped — the session manager spells it),
        sandbox torn down, pool refill kicked."""
        self.journal.record(lease.name, state, reason=reason, detail=detail)
        lease.kill()
        self._spawn_background(self.fill_executor_pod_queue())

    async def _spawn_guarded(self, deadline: Deadline | None) -> PodGroup:
        """Request-path spawn: breaker-gated and deadline-bounded. A hang or
        failure anywhere in the spawn (create, IP wait, readiness) counts
        against the breaker; while OPEN the caller gets BreakerOpenError
        immediately, which the service layer can turn into local fallback.

        The ``spawn`` stage span covers the breaker check too (its state is
        recorded as a span attribute), so a trace shows whether the request
        paid a real cold spawn or was rejected at the gate."""
        with span("spawn", breaker=self.spawn_breaker.state.name.lower()):
            async with self.spawn_breaker.guard():
                if deadline is None:
                    return await self.spawn_pod_group()
                return await deadline.run(
                    self.spawn_pod_group(deadline=deadline),
                    what="pod group spawn",
                )

    async def _group_healthy(
        self, group: PodGroup, deadline: Deadline | None = None
    ) -> bool:
        """Every worker answers /healthz (sub-second; runs on the pod
        network). The probe timeout is ``APP_HEALTH_PROBE_TIMEOUT_S``,
        clamped on the request path to the remaining checkout deadline so a
        near-expiry request never spends its whole budget probing."""
        timeout = self._config.health_probe_timeout_s
        if deadline is not None:
            # A probe needs a real floor: clamping to a near-expired budget
            # would time the probe out instantly and reap a HEALTHY pod —
            # under overload (when deadlines run short) that turns each
            # expiring request into a warm-pool destruction event. Out of
            # budget means the REQUEST is out of time, not the pod.
            floor = min(timeout, 0.25)
            if deadline.remaining() <= floor:
                raise DeadlineExceeded("warm sandbox health probe")
            timeout = deadline.clamp(timeout)

        async def probe(ip: str) -> bool:
            try:
                response = await self._http.get(
                    f"http://{ip}:{self._config.executor_port}/healthz",
                    timeout=timeout,
                )
                return response.status_code == 200
            except httpx.HTTPError:
                return False

        results = await asyncio.gather(*(probe(ip) for ip in group.pod_ips))
        return all(results)

    def _kill_group(self, group: PodGroup) -> None:
        """Fire-and-forget deletion of every pod in a group — the one
        teardown spelling shared by single-use release, idle reaps, the
        watchdog (where the deletions also break the in-flight /execute
        transport on a real cluster; the tracked task's cancel guarantees
        it deterministically), and refill-vs-close races."""
        for pod_name in group.pod_names:
            self._spawn_background(self._delete_pod(pod_name))

    def trim_excess_warm(self) -> int:
        """Supervisor hook for the autoscaler's act-mode shrink
        (docs/autoscaling.md): reap queued warm groups beyond the current
        refill target — without this a scale-down would only stop refills,
        and an idle pool would hold its peak size forever. Trims the
        newest-queued first so the survivors' FIFO checkout order is
        untouched. Returns the number reaped."""
        trimmed = 0
        while len(self._queue) > self.pool_target:
            group = self._queue.pop()
            self.journal.record(group.name, "reaped", reason="scaled_down")
            self._kill_group(group)
            trimmed += 1
        return trimmed

    async def reap_unhealthy_idle(self) -> int:
        """Supervisor hook: probe every *queued* warm group and reap the
        ones that died in place (preemption, OOM, node loss) instead of
        discovering them at checkout time. Returns the number reaped."""
        candidates = list(self._queue)
        if not candidates:
            return 0
        # Probe the whole queue concurrently: a mass-death event (node loss)
        # must not cost one probe timeout PER corpse before healing starts.
        results = await asyncio.gather(
            *(self._group_healthy(g) for g in candidates)
        )
        reaped = 0
        for group, healthy in zip(candidates, results):
            if healthy:
                continue
            try:
                self._queue.remove(group)
            except ValueError:
                continue  # checked out by a request while we probed
            logger.warning(
                "Supervisor reaping unhealthy idle pod group %s", group.name
            )
            self.journal.record(group.name, "reaped", reason="unhealthy_idle")
            self._kill_group(group)
            reaped += 1
        return reaped

    async def aclose(self) -> None:
        """Drain-path teardown: reap the warm queue (awaited, not
        fire-and-forget) and close the data-plane client deterministically.
        The closed flag makes refills still in flight delete their spawned
        groups instead of repopulating a dead pool."""
        self._closed = True
        deletions: list = []
        while self._queue:
            group = self._queue.popleft()
            self.journal.record(group.name, "reaped", reason="shutdown")
            deletions.extend(self._delete_pod(p) for p in group.pod_names)
        if deletions:
            await asyncio.gather(*deletions)
        await self._http.aclose()

    def _spawn_background(self, coro) -> None:
        task = asyncio.ensure_future(coro)
        self._background_tasks.add(task)
        task.add_done_callback(self._background_tasks.discard)

    async def fill_executor_pod_queue(self) -> None:
        """Keep the warm queue at target length (reference :151-189)."""
        if self._closed:
            return
        async with self._fill_lock:
            missing = self.pool_target - len(self._queue) - self._spawning_count
            if missing <= 0:
                return
            self._spawning_count += missing
        logger.info("Filling executor pool: spawning %d pod group(s)", missing)
        # Each spawn settles its own accounting — a failed spawn must never
        # abandon its siblings or leave a phantom spawning count behind.
        results = await asyncio.gather(
            *(self._spawn_into_queue() for _ in range(missing))
        )
        spawned = sum(results)
        if spawned < missing:
            logger.warning(
                "Pool refill finished with failures: %d/%d spawned", spawned, missing
            )
        else:
            logger.info("Pool refill complete: %d/%d spawned", spawned, missing)

    async def _spawn_into_queue(self) -> bool:
        try:
            if self.spawn_breaker.state is not BreakerState.CLOSED:
                # Backend suspect (OPEN or probing HALF_OPEN): background
                # refills pause entirely — no hammering a down apiserver, and
                # recovery probing stays request-driven (a refill must neither
                # consume the half-open probe slot nor mask the serving path).
                return False
            group = await self.spawn_pod_group()
        except Exception:
            logger.exception("Pod group spawn failed")
            # Refills feed the breaker only while it is CLOSED.
            if self.spawn_breaker.state is BreakerState.CLOSED:
                self.spawn_breaker.record_failure()
            return False
        finally:
            self._spawning_count -= 1
        if self.spawn_breaker.state is BreakerState.CLOSED:
            self.spawn_breaker.record_success()
        if self._closed:
            # raced with teardown: a freshly spawned group appended to a dead
            # executor's queue would never be deleted — leaked cluster pods
            # after every graceful restart.
            self.journal.record(group.name, "reaped", reason="shutdown")
            self._kill_group(group)
            return False
        self._queue.append(group)
        return True

    @retryable("_spawn_retry", op="spawn")
    async def spawn_pod_group(self, deadline: Deadline | None = None) -> PodGroup:
        """Create a gang of executor pods, all-or-nothing Ready
        (reference spawn_executor_pod :196-246, generalized)."""
        n = max(1, self._config.tpu_hosts_per_slice)
        name = f"{self._config.executor_pod_name_prefix}{secrets.token_hex(3)}"
        created: list[str] = []
        # Retry attempts use fresh names, so each attempt is its own journal
        # entry — a flapping apiserver shows up as N failed spawns, not one.
        self.journal.record(name, "spawning", workers=n)
        try:
            # Worker 0 first: its IP is the jax.distributed coordinator address
            # for the rest of the gang.
            w0_name = f"{name}-w0" if n > 1 else name
            await self._create_worker_pod(w0_name, name, worker_id=0, num_workers=n)
            created.append(w0_name)
            coordinator_ip = None
            if n > 1:
                coordinator_ip = await self._wait_pod_ip(w0_name, deadline=deadline)
                await asyncio.gather(
                    *(
                        self._create_worker_pod(
                            f"{name}-w{i}",
                            name,
                            worker_id=i,
                            num_workers=n,
                            coordinator_ip=coordinator_ip,
                        )
                        for i in range(1, n)
                    )
                )
                created.extend(f"{name}-w{i}" for i in range(1, n))

            ready_timeout = self._config.pod_ready_timeout_s
            if deadline is not None:
                ready_timeout = deadline.clamp(ready_timeout)
            # Gang readiness: every member Ready or the whole group dies.
            await asyncio.gather(
                *(
                    self._kubectl.wait(
                        f"pod/{pod_name}",
                        for_="condition=Ready",
                        timeout=f"{int(ready_timeout)}s",
                    )
                    for pod_name in created
                )
            )
            pods = await asyncio.gather(
                *(self._kubectl.get("pod", pod_name) for pod_name in created)
            )
            self.journal.record(name, "ready")
            return PodGroup(name=name, pods=list(pods))
        except BaseException as e:
            # str() of a bare CancelledError is empty; fall back to the type.
            self.journal.record(
                name,
                "failed",
                reason="spawn_failed",
                detail=(str(e) or type(e).__name__)[:200],
            )
            # Delete-on-failure (reference :242-246), for every member — also
            # on cancellation (the deadline bound cancels a hung spawn). The
            # deletions ride the background-task set so teardown can still
            # observe them (asynclint: no dropped task handles).
            for pod_name in created:
                self._spawn_background(self._delete_pod(pod_name))
            if isinstance(e, DeadlineExceeded) or not isinstance(e, Exception):
                # DeadlineExceeded and bare BaseExceptions (CancelledError,
                # KeyboardInterrupt, SystemExit) must keep their type: wrapping
                # them in RuntimeError would make the spawn retry policy
                # swallow a Ctrl-C and re-attempt with multi-second backoffs.
                raise
            raise RuntimeError(f"spawning pod group {name} failed: {e}") from e

    async def _create_worker_pod(
        self,
        pod_name: str,
        group_name: str,
        worker_id: int,
        num_workers: int,
        coordinator_ip: str | None = None,
    ) -> None:
        cfg = self._config
        env = [
            {"name": "APP_LISTEN_ADDR", "value": f"0.0.0.0:{cfg.executor_port}"},
            {"name": "APP_EXECUTION_TIMEOUT_S", "value": str(cfg.execution_timeout_s)},
            {"name": "TPU_WORKER_ID", "value": str(worker_id)},
            {"name": "JAX_PROCESS_ID", "value": str(worker_id)},
            {"name": "JAX_NUM_PROCESSES", "value": str(num_workers)},
        ]
        if cfg.tpu_accelerator_type:
            env.append(
                {"name": "TPU_ACCELERATOR_TYPE", "value": cfg.tpu_accelerator_type}
            )
        if cfg.tpu_topology:
            env.append({"name": "TPU_TOPOLOGY", "value": cfg.tpu_topology})
        if cfg.jax_cache_dir:
            # Shared XLA compile cache (must point at a mounted shared volume,
            # via executor_pod_spec_extra): unique programs compile once per
            # deployment, not once per single-use pod.
            env.append({"name": "APP_JAX_CACHE_DIR", "value": cfg.jax_cache_dir})
        if num_workers > 1:
            # Worker 0 coordinates on its own IP; the others dial it.
            address = (
                f"{coordinator_ip}:{JAX_COORDINATOR_PORT}"
                if coordinator_ip
                else f"0.0.0.0:{JAX_COORDINATOR_PORT}"
            )
            env.append({"name": "JAX_COORDINATOR_ADDRESS", "value": address})

        resources = dict(cfg.executor_container_resources)
        if cfg.tpu_accelerator_type:
            limits = dict(resources.get("limits", {}))
            limits.setdefault("google.com/tpu", cfg.tpu_chips_per_host)
            resources["limits"] = limits

        spec: dict = {
            "containers": [
                {
                    "name": "executor",
                    "image": cfg.executor_image,
                    "ports": [{"containerPort": cfg.executor_port}],
                    "env": env,
                    "resources": resources,
                }
            ],
            "restartPolicy": "Never",
        }
        node_selector = dict(cfg.tpu_node_selector)
        if cfg.tpu_accelerator_type:
            node_selector.setdefault(
                "cloud.google.com/gke-tpu-accelerator", cfg.tpu_accelerator_type
            )
        if cfg.tpu_topology:
            node_selector.setdefault("cloud.google.com/gke-tpu-topology", cfg.tpu_topology)
        if node_selector:
            spec["nodeSelector"] = node_selector
        spec.update(cfg.executor_pod_spec_extra)

        manifest = {
            "apiVersion": "v1",
            "kind": "Pod",
            "metadata": {
                "name": pod_name,
                "labels": {
                    "app": "bee-code-interpreter-tpu-executor",
                    "executor-group": group_name,
                    "executor-worker": str(worker_id),
                },
                "ownerReferences": await self._owner_references(),
            },
            "spec": spec,
        }
        import json as _json

        await self._kubectl.create("-f", "-", _input=_json.dumps(manifest))

    async def _wait_pod_ip(
        self,
        pod_name: str,
        attempts: int = 60,
        deadline: Deadline | None = None,
    ) -> str:
        for _ in range(attempts):
            if deadline is not None:
                deadline.check(f"waiting for pod {pod_name} IP")
            pod = await self._kubectl.get("pod", pod_name)
            ip = pod.get("status", {}).get("podIP")
            if ip:
                return ip
            await asyncio.sleep(self._ip_poll_interval_s)
        raise RuntimeError(f"pod {pod_name} never got an IP")

    async def _owner_references(self) -> list[dict]:
        """Point every executor pod at our own pod for cascade GC
        (reference :215-224; needs HOSTNAME + in-cluster identity)."""
        if self._self_pod is None:
            hostname = os.environ.get("HOSTNAME", "")
            if not hostname:
                return []
            try:
                self._self_pod = await self._kubectl.get("pod", hostname)
            except Exception:
                logger.warning("Cannot resolve own pod %r; skipping ownerReferences", hostname)
                self._self_pod = {}
        if not self._self_pod:
            return []
        return [
            {
                "apiVersion": "v1",
                "kind": "Pod",
                "name": self._self_pod["metadata"]["name"],
                "uid": self._self_pod["metadata"]["uid"],
                "blockOwnerDeletion": False,
            }
        ]

    async def _delete_pod(self, pod_name: str) -> None:
        try:
            await self._kubectl.delete(
                "pod", pod_name, ignore_not_found="true", wait="false"
            )
        except Exception:
            logger.warning("Failed to delete pod %s", pod_name, exc_info=True)
