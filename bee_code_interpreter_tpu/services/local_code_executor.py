"""In-process code executor: fresh workspace per execution, no cluster.

The minimum runnable slice (SURVEY.md §7 step 3): same contract as the
Kubernetes backend — restore the client's {path → object id} map into a fresh
workspace, run the code through ``ExecutorCore``, snapshot changed files back
into content-addressed storage — but everything happens in this process on this
host (including its TPU chips, if any). Preserves the reference's single-use
hygiene (kubernetes_code_executor.py:262-264): each execution gets a brand-new
workspace directory, torn down afterwards; state only survives through the
returned file map.
"""

from __future__ import annotations

import logging
import secrets
import shutil
from pathlib import Path

from bee_code_interpreter_tpu.analysis.context import predicted_deps
from bee_code_interpreter_tpu.observability import span
from bee_code_interpreter_tpu.resilience import Deadline
from bee_code_interpreter_tpu.runtime.executor_core import ExecutorCore
from bee_code_interpreter_tpu.services.code_executor import Result
from bee_code_interpreter_tpu.services.storage import Storage
from bee_code_interpreter_tpu.utils.validation import AbsolutePath, Hash

logger = logging.getLogger(__name__)


class LocalCodeExecutor:
    def __init__(
        self,
        storage: Storage,
        workspace_root: str | Path = "./.tmp/workspaces",
        disable_dep_install: bool = True,
        execution_timeout_s: float = 60.0,
        shim_dir: str | Path | None = None,
    ) -> None:
        self._storage = storage
        self._workspace_root = Path(workspace_root)
        self._disable_dep_install = disable_dep_install
        self._execution_timeout_s = execution_timeout_s
        self._shim_dir = shim_dir
        # Shared across executions so an installed dep is installed once.
        self._installed_cache: set[str] = set()
        self._preinstalled: frozenset[str] | None = None

    def _preinstalled_set(self) -> frozenset[str]:
        """Distributions already importable in this interpreter (lazy, once).

        The pod executor loads this from the image's requirements.txt; in-process
        we ask importlib.metadata so `import numpy` never triggers pip.
        """
        if self._preinstalled is None:
            import importlib.metadata

            self._preinstalled = frozenset(
                d.metadata["Name"] for d in importlib.metadata.distributions()
                if d.metadata["Name"]
            )
        return self._preinstalled

    def _clamp_timeout(self, timeout_s: float | None) -> float | None:
        """A request may shorten the deadline, never extend past the
        service-configured bound."""
        if timeout_s is None or timeout_s <= 0:
            return None
        return min(timeout_s, self._execution_timeout_s)

    async def execute(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        deadline: Deadline | None = None,
    ) -> Result:
        files = files or {}
        if deadline is not None:
            # The subprocess timeout shrinks to the remaining request budget,
            # so a late-arriving execution can't run past the edge promise.
            deadline.check("execute")
            timeout_s = deadline.clamp(
                self._clamp_timeout(timeout_s) or self._execution_timeout_s
            )
        workspace = self._workspace_root / secrets.token_hex(8)
        core = ExecutorCore(
            workspace=workspace,
            preinstalled=(
                frozenset() if self._disable_dep_install else self._preinstalled_set()
            ),
            disable_dep_install=self._disable_dep_install,
            default_timeout_s=self._execution_timeout_s,
            shim_dir=self._shim_dir,
            installed_cache=self._installed_cache,
        )
        try:
            # Restore the client's workspace snapshot (reference
            # kubernetes_code_executor.py:100-113, via HTTP PUT; here direct
            # I/O). Stage spans: restore/execute/snapshot are this backend's
            # analogue of the pod path's upload/execute/download — and the
            # byte counts land in the same usage-block keys.
            restored_bytes = 0
            with span("restore", files=str(len(files))):
                for logical_path, object_id in files.items():
                    real = core.resolve(logical_path)
                    real.parent.mkdir(parents=True, exist_ok=True)
                    with open(real, "wb") as f:
                        async with self._storage.reader(object_id) as r:
                            async for chunk in r:
                                restored_bytes += len(chunk)
                                f.write(chunk)

            with span("execute"):
                outcome = await core.execute(
                    source_code,
                    env=env,
                    timeout_s=self._clamp_timeout(timeout_s),
                    # The edge's ambient dep prediction (docs/analysis.md)
                    # reaches the in-process core directly — no wire hop.
                    predicted_deps=predicted_deps(),
                )

            # Snapshot changed files back (reference :126-142).
            out_files: dict[str, str] = {}
            snapshot_bytes = 0
            with span("snapshot", files=str(len(outcome.files))):
                for logical_path in outcome.files:
                    real = core.resolve(logical_path)
                    async with self._storage.writer() as w:
                        with open(real, "rb") as f:
                            while chunk := f.read(1 << 20):
                                snapshot_bytes += len(chunk)
                                await w.write(chunk)
                    out_files[logical_path] = w.hash
            usage = dict(outcome.usage or {})
            usage.update(
                uploaded_bytes=restored_bytes,
                uploaded_files=len(files),
                downloaded_bytes=snapshot_bytes,
                downloaded_files=len(out_files),
            )
            return Result(
                stdout=outcome.stdout,
                stderr=outcome.stderr,
                exit_code=outcome.exit_code,
                files=out_files,
                usage=usage,
            )
        finally:
            shutil.rmtree(workspace, ignore_errors=True)
