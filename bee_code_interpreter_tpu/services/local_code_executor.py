"""In-process code executor: fresh workspace per execution, no cluster.

The minimum runnable slice (SURVEY.md §7 step 3): same contract as the
Kubernetes backend — restore the client's {path → object id} map into a fresh
workspace, run the code through ``ExecutorCore``, snapshot changed files back
into content-addressed storage — but everything happens in this process on this
host (including its TPU chips, if any). Preserves the reference's single-use
hygiene (kubernetes_code_executor.py:262-264): each execution gets a brand-new
workspace directory, torn down afterwards; state only survives through the
returned file map.
"""

from __future__ import annotations

import asyncio
import logging
import secrets
import shutil
from pathlib import Path

from bee_code_interpreter_tpu.analysis.context import predicted_deps
from bee_code_interpreter_tpu.observability import span
from bee_code_interpreter_tpu.resilience import Deadline
from bee_code_interpreter_tpu.runtime.executor_core import ExecutorCore
from bee_code_interpreter_tpu.services.code_executor import Result
from bee_code_interpreter_tpu.services.storage import Storage
from bee_code_interpreter_tpu.utils.validation import AbsolutePath, Hash

logger = logging.getLogger(__name__)


class LocalCodeExecutor:
    def __init__(
        self,
        storage: Storage,
        workspace_root: str | Path = "./.tmp/workspaces",
        disable_dep_install: bool = True,
        execution_timeout_s: float = 60.0,
        shim_dir: str | Path | None = None,
    ) -> None:
        self._storage = storage
        self._workspace_root = Path(workspace_root)
        self._disable_dep_install = disable_dep_install
        self._execution_timeout_s = execution_timeout_s
        self._shim_dir = shim_dir
        # Shared across executions so an installed dep is installed once.
        self._installed_cache: set[str] = set()
        self._preinstalled: frozenset[str] | None = None

    def _preinstalled_set(self) -> frozenset[str]:
        """Distributions already importable in this interpreter (lazy, once).

        The pod executor loads this from the image's requirements.txt; in-process
        we ask importlib.metadata so `import numpy` never triggers pip.
        """
        if self._preinstalled is None:
            import importlib.metadata

            self._preinstalled = frozenset(
                d.metadata["Name"] for d in importlib.metadata.distributions()
                if d.metadata["Name"]
            )
        return self._preinstalled

    def _clamp_timeout(self, timeout_s: float | None) -> float | None:
        """A request may shorten the deadline, never extend past the
        service-configured bound."""
        if timeout_s is None or timeout_s <= 0:
            return None
        return min(timeout_s, self._execution_timeout_s)

    async def execute(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        deadline: Deadline | None = None,
    ) -> Result:
        files = files or {}
        if deadline is not None:
            # The subprocess timeout shrinks to the remaining request budget,
            # so a late-arriving execution can't run past the edge promise.
            deadline.check("execute")
            timeout_s = deadline.clamp(
                self._clamp_timeout(timeout_s) or self._execution_timeout_s
            )
        workspace = self._workspace_root / secrets.token_hex(8)
        core = self._make_core(workspace)
        try:
            # Restore the client's workspace snapshot (reference
            # kubernetes_code_executor.py:100-113, via HTTP PUT; here direct
            # I/O). Stage spans: restore/execute/snapshot are this backend's
            # analogue of the pod path's upload/execute/download — and the
            # byte counts land in the same usage-block keys.
            with span("restore", files=str(len(files))):
                restored_bytes = await self._restore_files(core, files)

            with span("execute"):
                outcome = await core.execute(
                    source_code,
                    env=env,
                    timeout_s=self._clamp_timeout(timeout_s),
                    # The edge's ambient dep prediction (docs/analysis.md)
                    # reaches the in-process core directly — no wire hop.
                    predicted_deps=predicted_deps(),
                )

            # Snapshot changed files back (reference :126-142).
            with span("snapshot", files=str(len(outcome.files))):
                out_files, snapshot_bytes = await self._snapshot_files(
                    core, outcome.files
                )
            usage = dict(outcome.usage or {})
            usage.update(
                uploaded_bytes=restored_bytes,
                uploaded_files=len(files),
                downloaded_bytes=snapshot_bytes,
                downloaded_files=len(out_files),
            )
            return Result(
                stdout=outcome.stdout,
                stderr=outcome.stderr,
                exit_code=outcome.exit_code,
                files=out_files,
                usage=usage,
            )
        finally:
            shutil.rmtree(workspace, ignore_errors=True)

    async def _restore_files(self, core: ExecutorCore, files: dict) -> int:
        """Restore the snapshot map into the workspace, all files
        concurrently (the serial per-file loop was pure added latency for
        multi-file workspaces); returns total bytes restored."""

        async def restore_one(logical_path: str, object_id: str) -> int:
            moved = 0
            real = core.resolve(logical_path)
            real.parent.mkdir(parents=True, exist_ok=True)
            with open(real, "wb") as f:
                async with self._storage.reader(object_id) as r:
                    async for chunk in r:
                        moved += len(chunk)
                        f.write(chunk)
            return moved

        return sum(
            await asyncio.gather(
                *(restore_one(p, oid) for p, oid in files.items())
            )
        )

    async def _snapshot_files(
        self, core: ExecutorCore, logical_paths
    ) -> tuple[dict[str, str], int]:
        """Snapshot changed files into content-addressed storage, all files
        concurrently — the post-execute half of the satellite overlap work
        (ISSUE 7): the snapshot no longer serializes file-by-file ahead of
        the response. Returns ({logical path: object id}, total bytes)."""

        async def snapshot_one(logical_path: str) -> tuple[str, str, int]:
            moved = 0
            real = core.resolve(logical_path)
            async with self._storage.writer() as w:
                with open(real, "rb") as f:
                    while chunk := f.read(1 << 20):
                        moved += len(chunk)
                        await w.write(chunk)
            return logical_path, w.hash, moved

        snapshots = await asyncio.gather(
            *(snapshot_one(p) for p in logical_paths)
        )
        out_files = {path: object_id for path, object_id, _ in snapshots}
        return out_files, sum(moved for _, _, moved in snapshots)

    async def execute_stream(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        on_event=None,  # async (kind, text) -> None per stdout/stderr chunk
        deadline: Deadline | None = None,
    ) -> Result:
        """Streaming execute (docs/sessions.md): same fresh-workspace
        lifecycle as :meth:`execute`, with output chunks forwarded to
        ``on_event`` as the child produces them."""
        files = files or {}
        if deadline is not None:
            deadline.check("execute")
            timeout_s = deadline.clamp(
                self._clamp_timeout(timeout_s) or self._execution_timeout_s
            )
        workspace = self._workspace_root / secrets.token_hex(8)
        core = self._make_core(workspace)
        try:
            with span("restore", files=str(len(files))):
                restored_bytes = await self._restore_files(core, files)
            outcome = None
            with span("execute", stream="1"):
                gen = core.execute_stream(
                    source_code,
                    env=env,
                    timeout_s=self._clamp_timeout(timeout_s),
                    predicted_deps=predicted_deps(),
                )
                try:
                    async for kind, payload in gen:
                        if kind == "end":
                            outcome = payload
                        elif on_event is not None:
                            await on_event(kind, payload)
                finally:
                    await gen.aclose()
            with span("snapshot", files=str(len(outcome.files))):
                out_files, snapshot_bytes = await self._snapshot_files(
                    core, outcome.files
                )
            usage = dict(outcome.usage or {})
            usage.update(
                uploaded_bytes=restored_bytes,
                uploaded_files=len(files),
                downloaded_bytes=snapshot_bytes,
                downloaded_files=len(out_files),
            )
            return Result(
                stdout=outcome.stdout,
                stderr=outcome.stderr,
                exit_code=outcome.exit_code,
                files=out_files,
                usage=usage,
            )
        finally:
            shutil.rmtree(workspace, ignore_errors=True)

    # ---------------------------------------------------------------- leases

    async def checkout_for_lease(self, deadline: Deadline | None = None):
        """Session lease over the in-process backend: a PERSISTENT workspace
        + core that live until the lease ends — the one place this backend
        deliberately departs from its fresh-workspace-per-execute hygiene
        (state is the entire point of a session)."""
        from bee_code_interpreter_tpu.services.code_executor import LeaseHandle

        workspace = self._workspace_root / f"session-{secrets.token_hex(8)}"
        core = self._make_core(workspace)
        return LeaseHandle(
            name=f"local-{workspace.name}",
            kill=lambda: shutil.rmtree(workspace, ignore_errors=True),
            handle=workspace,
            core=core,
        )

    def release_lease(
        self, lease, state: str = "released", reason: str = "lease_released",
        detail: str | None = None,
    ) -> None:
        lease.kill()

    def _make_core(self, workspace: Path) -> ExecutorCore:
        return ExecutorCore(
            workspace=workspace,
            preinstalled=(
                frozenset()
                if self._disable_dep_install
                else self._preinstalled_set()
            ),
            disable_dep_install=self._disable_dep_install,
            default_timeout_s=self._execution_timeout_s,
            shim_dir=self._shim_dir,
            installed_cache=self._installed_cache,
        )
