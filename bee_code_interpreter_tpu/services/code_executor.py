"""Service-level code-executor contract shared by all backends.

``Result`` is the service-level execution result: stdout/stderr/exit code plus
the {logical path → storage object id} map of files the execution created or
modified — the *workspace file map* that doubles as the checkpoint/session
mechanism (SURVEY.md §5 "Checkpoint / resume"; reference
kubernetes_code_executor.py:144-149).

Backends: ``KubernetesCodeExecutor`` (warm pod pool on a TPU node pool) and
``LocalCodeExecutor`` (in-process; the unit-test/dev backend the reference
lacked, SURVEY.md §4).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Protocol, runtime_checkable

from pydantic import BaseModel

from bee_code_interpreter_tpu.utils.validation import AbsolutePath, Hash


@dataclass
class LeaseHandle:
    """One sandbox checked out of its pool for a *session lease*
    (docs/sessions.md): unlike the single-use execute path, the holder keeps
    the warm sandbox across N executions and the backend must not treat it
    as queue inventory (reaper) or as a stuck execution (watchdog) while it
    idles between them.

    ``addrs`` are the data-plane ``host:port`` targets (one per gang worker;
    empty for the in-process local backend, which sets ``core`` instead).
    ``kill`` is the backend's sync sandbox teardown; ``handle`` the backend's
    native object (PodGroup / NativeSandbox / workspace path)."""

    name: str
    addrs: list[str] = field(default_factory=list)
    kill: Callable[[], None] = lambda: None
    handle: object | None = None
    core: object | None = None  # runtime.ExecutorCore for the local backend


class Result(BaseModel):
    stdout: str
    stderr: str
    exit_code: int
    files: dict[AbsolutePath, Hash]
    # Per-execution resource accounting (docs/observability.md): sandbox
    # rusage/wall/workspace figures merged with the driver's data-plane byte
    # counts. None from backends that don't measure (e.g. the C++ server).
    usage: dict | None = None


@runtime_checkable
class CodeExecutor(Protocol):
    async def execute(
        self,
        source_code: str,
        files: dict[AbsolutePath, Hash] | None = None,
        env: dict[str, str] | None = None,
        timeout_s: float | None = None,
        deadline=None,  # resilience.Deadline created at the API edge
    ) -> Result: ...
