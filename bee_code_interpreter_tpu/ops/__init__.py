"""Pallas TPU kernels for the hot ops, with XLA fallbacks.

The reference has no compute kernels at all (it is a code-execution service;
SURVEY.md §2) — this package exists because the TPU build makes the sandbox a
first-class numerical runtime: the bundled models (models/) and user-visible
runtime (runtime/) call these ops, and they are written against the TPU memory
hierarchy (HBM→VMEM→MXU/VPU; /opt/skills/guides/pallas_guide.md).
"""

from bee_code_interpreter_tpu.ops.flash_attention import flash_attention  # noqa: F401
