"""Flash attention for TPU (Pallas forward + Pallas backward kernels).

Forward: a Pallas kernel tiled for the MXU — grid (batch·heads, q-blocks,
k-blocks), the k dimension iterated sequentially ("arbitrary" semantics) with
the online-softmax running max/normalizer/accumulator held in VMEM scratch
across k steps. Scores accumulate in float32 regardless of input dtype
(bfloat16 inputs hit the MXU, statistics stay fp32). Fully-masked causal
blocks are skipped with predication. O(L·block) memory instead of O(L²).

Backward: two Pallas kernels (FlashAttention-2 split) recomputing P from the
saved log-sum-exp — one accumulates dK/dV with the q dimension iterated
sequentially, one accumulates dQ with the k dimension sequential; both skip
fully-masked causal blocks. ``delta = rowsum(dO·O)`` is precomputed at the
jax level (one cheap fused reduction). The previous jax-level blockwise scan
(``_attention_bwd_blockwise``) is kept as the oracle the kernel tests check
against.

Grouped-query attention is native: ``k``/``v`` may carry ``kv_heads <
n_heads`` (n_heads % kv_heads == 0) and the kernels index-map each query
head's K/V blocks to its shared KV head instead of materializing the
``jnp.repeat`` broadcast — attention reads ``kv_heads`` worth of K/V HBM
traffic, not ``n_heads`` (4x less for Llama-3-8B's 32/8 grouping, where
long-context attention is KV-bandwidth-bound). In the backward, dK/dV
accumulate across the group's query heads inside the kernel (the sequential
grid dimension runs over ``rep · q-blocks``), so dk/dv come back in the
compact ``[B, kv_heads, L, D]`` shape with no post-hoc segment-sum.

On non-TPU backends (CPU tests) the kernels run in Pallas interpreter mode.
Sequence lengths are padded to the block size internally; padded key (and, in
the backward, padded query) positions are masked out, so any [B, H, L, D]
input works.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(
    q_ref, k_ref, v_ref,  # [1, 1, blk_q, D], [1, blk_k, D], [1, blk_k, D]
    o_ref, lse_ref,       # [1, 1, blk_q, D], [1, 1, blk_q, 1]
    m_scratch, l_scratch, acc_scratch,  # VMEM f32: [blk_q,1],[blk_q,1],[blk_q,D]
    *, sm_scale: float, causal: bool, blk_q: int, blk_k: int, seq_len: int,
    window: int | None = None,
):
    """Grid (B·KVH, rep, q-blocks, k-blocks): q is viewed [B·KVH, rep, L, D]
    (group-major head order) so grouped-query KV sharing is pure grid
    structure — K/V blocks depend only on (b, j). No division in any index
    map: div/mod-bearing maps measurably disable Mosaic's block pipelining
    (5x slower on v5e when this used a flat B·H grid with b→b//rep K/V
    maps)."""
    j = pl.program_id(3)
    num_k = pl.num_programs(3)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    i = pl.program_id(2)
    q_start = i * blk_q
    k_start = j * blk_k

    # causal: skip blocks where every key index > every query index;
    # sliding window additionally skips blocks entirely below the window
    should_compute = True
    if causal:
        should_compute = k_start <= q_start + blk_q - 1
    if window is not None:
        should_compute &= k_start + blk_k - 1 >= q_start - (window - 1)

    @pl.when(should_compute)
    def _compute():
        # inputs stay in their native dtype (bf16 rides the MXU at full rate);
        # the MXU accumulates in f32 via preferred_element_type
        q = q_ref[0, 0]
        k = k_ref[0]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [blk_q, blk_k] f32

        row = q_start + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
        col = k_start + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
        mask = col < seq_len  # padded keys never attend
        if causal:
            mask = mask & (row >= col)
        if window is not None:
            mask = mask & (row - col < window)
        scores = jnp.where(mask, scores, NEG_INF)

        m_prev = m_scratch[:]                      # [blk_q, 1]
        block_max = jnp.max(scores, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, block_max)
        correction = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - m_next)               # [blk_q, blk_k]
        l_next = l_scratch[:] * correction + jnp.sum(p, axis=1, keepdims=True)
        # P in the input dtype for the MXU, f32 accumulation
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scratch[:] = acc_scratch[:] * correction + pv
        m_scratch[:] = m_next
        l_scratch[:] = l_next

    @pl.when(j == num_k - 1)
    def _finalize():
        l = jnp.maximum(l_scratch[:], 1e-30)
        o_ref[0, 0] = (acc_scratch[:] / l).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scratch[:] + jnp.log(l)  # [blk_q, 1]


def _vma(*arrays) -> frozenset:
    """Union of the operands' varying-manual-axes — pallas_call inside
    shard_map (check_vma=True) requires out_shape to declare how outputs
    vary over mesh axes; outside shard_map this is the empty set."""
    out: frozenset = frozenset()
    for a in arrays:
        out = out | getattr(jax.typeof(a), "vma", frozenset())
    return out


def _pad_to(x, length, axis):
    pad = length - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _compatible_blocks(blk_q: int, blk_k: int) -> tuple[int, int]:
    """Shrink the smaller block to gcd when neither divides the other.

    Rounding the padded length to max(blk_q, blk_k) alone is wrong when the
    clamped block sizes differ and the larger is not a multiple of the smaller
    (e.g. L=384 with blk_q=384, blk_k=256 gave Lp=384 → num_k silently
    truncated to 1 and keys 256..383 were never visited). Padding to
    lcm instead would inflate compute quadratically (384→768 here); shrinking
    the smaller block to the gcd (≥128 since both are 128-multiples, so still
    MXU-aligned) keeps the padding minimal at the cost of a shorter inner
    block."""
    if max(blk_q, blk_k) % min(blk_q, blk_k):
        g = math.gcd(blk_q, blk_k)
        if blk_q < blk_k:
            blk_q = g
        else:
            blk_k = g
    return blk_q, blk_k


def _padded_len(L: int, Lk: int, blk_q: int, blk_k: int) -> int:
    """Smallest padded sequence length divisible by both block sizes (after
    _compatible_blocks, lcm == max)."""
    unit = math.lcm(blk_q, blk_k)
    return unit * pl.cdiv(max(L, Lk), unit)


def _flash_fwd(q, k, v, causal, sm_scale, blk_q, blk_k, interpret, window=None):
    B, H, L, D = q.shape
    KVH = k.shape[1]
    rep = H // KVH
    Lk = k.shape[2]
    blk_q, blk_k = _compatible_blocks(blk_q, blk_k)
    Lp = _padded_len(L, Lk, blk_q, blk_k)
    # q viewed [B·KVH, rep, Lp, D]: group-major head order (h = g·rep + r)
    # makes this a plain contiguous reshape
    qp = _pad_to(q.reshape(B * H, L, D), Lp, axis=1).reshape(B * KVH, rep, Lp, D)
    kp = _pad_to(k.reshape(B * KVH, Lk, D), Lp, axis=1)
    vp = _pad_to(v.reshape(B * KVH, Lk, D), Lp, axis=1)

    grid = (B * KVH, rep, Lp // blk_q, Lp // blk_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        blk_q=blk_q, blk_k=blk_k, seq_len=Lk, window=window,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, blk_q, D), lambda b, r, i, j: (b, r, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, r, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, r, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, blk_q, D), lambda b, r, i, j: (b, r, i, 0)),
            # lse block (1, 1, blk_q, 1) satisfies TPU tiling (trailing dim
            # equals the full array dim)
            pl.BlockSpec((1, 1, blk_q, 1), lambda b, r, i, j: (b, r, i, 0)),
        ],
        out_shape=[
            # vma: inside shard_map the outputs vary over the same mesh axes
            # as the operands (required by check_vma; empty set elsewhere)
            jax.ShapeDtypeStruct((B * KVH, rep, Lp, D), q.dtype, vma=_vma(q, k)),
            jax.ShapeDtypeStruct((B * KVH, rep, Lp, 1), jnp.float32, vma=_vma(q, k)),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            # batch·kv-heads, group members and q-blocks are independent;
            # only the k dimension carries the online-softmax state
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(qp, kp, vp)
    out = out.reshape(B * H, Lp, D)[:, :L]
    lse = lse.reshape(B * H, Lp, 1)[:, :L, 0]
    return out.reshape(B, H, L, D), lse


def _attention_bwd_blockwise(q, k, v, o, lse, do, causal, sm_scale, blk_k):
    """dq, dk, dv via scan over k-blocks with the saved lse. All [BH, L, D]."""
    BH, L, D = q.shape
    Lk = k.shape[1]
    nblk = pl.cdiv(Lk, blk_k)
    Lkp = nblk * blk_k
    kp = _pad_to(k, Lkp, 1).reshape(BH, nblk, blk_k, D)
    vp = _pad_to(v, Lkp, 1).reshape(BH, nblk, blk_k, D)

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # [BH, L]
    row_idx = lax.broadcasted_iota(jnp.int32, (L, blk_k), 0)

    def body(dq, blocks):
        k_blk, v_blk, j = blocks  # [BH, blk_k, D], scalar block index
        col_idx = j * blk_k + lax.broadcasted_iota(jnp.int32, (L, blk_k), 1)
        scores = jnp.einsum(
            "bld,bkd->blk", qf, k_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        mask = col_idx < Lk
        if causal:
            mask = mask & (row_idx >= col_idx)
        scores = jnp.where(mask, scores, NEG_INF)
        p = jnp.exp(scores - lse[..., None])  # [BH, L, blk_k]
        dv_blk = jnp.einsum("blk,bld->bkd", p, dof)
        dp = jnp.einsum("bld,bkd->blk", dof, v_blk.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * sm_scale
        dq = dq + jnp.einsum("blk,bkd->bld", ds, k_blk.astype(jnp.float32))
        dk_blk = jnp.einsum("blk,bld->bkd", ds, qf)
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_blocks, dv_blocks) = lax.scan(
        body, dq0,
        (kp.transpose(1, 0, 2, 3), vp.transpose(1, 0, 2, 3), jnp.arange(nblk)),
    )
    dk = dk_blocks.transpose(1, 0, 2, 3).reshape(BH, Lkp, D)[:, :Lk]
    dv = dv_blocks.transpose(1, 0, 2, 3).reshape(BH, Lkp, D)[:, :Lk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ------------------------------------------------------------ pallas backward


def _bwd_p_block(q, k, lse_col, row, col, *, sm_scale, causal, seq_len_q,
                 seq_len_k, window=None):
    """Recompute the probability block P = exp(S - lse) with validity masking.

    Padded-row lse is garbage (the forward never normalized those rows), so P
    must be forced to zero wherever the position pair is invalid — exp of a
    masked score minus a garbage lse is NOT reliably zero.
    """
    scores = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * sm_scale
    mask = (row < seq_len_q) & (col < seq_len_k)
    if causal:
        mask = mask & (row >= col)
    if window is not None:
        mask = mask & (row - col < window)
    p = jnp.where(mask, jnp.exp(scores - lse_col), 0.0)
    return p, mask


def _bwd_dkdv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,  # blocks (see specs)
    dk_ref, dv_ref,
    dk_scratch, dv_scratch,  # VMEM f32 [blk_k, D]
    *, sm_scale: float, causal: bool, blk_q: int, blk_k: int,
    seq_len_q: int, seq_len_k: int, window: int | None = None,
):
    """Grid (B·KVH, k-blocks, rep, q-blocks): the two sequential dimensions
    run over the ``rep`` query heads sharing this KV head and their
    q-blocks; dK/dV for this k-block accumulate in VMEM across all of them
    (rep == 1 when not grouped-query). Division-free index maps — see
    _fwd_kernel."""
    r = pl.program_id(2)
    num_r = pl.num_programs(2)
    i = pl.program_id(3)
    num_q = pl.num_programs(3)
    j = pl.program_id(1)

    @pl.when(jnp.logical_and(r == 0, i == 0))
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    q_start = i * blk_q
    k_start = j * blk_k
    should_compute = True
    if causal:  # skip q-blocks entirely above the diagonal
        should_compute = q_start + blk_q - 1 >= k_start
    if window is not None:  # skip q-blocks entirely above the window
        should_compute &= q_start - (k_start + blk_k - 1) <= window - 1

    @pl.when(should_compute)
    def _compute():
        q = q_ref[0, 0]     # [blk_q, D]
        k = k_ref[0]        # [blk_k, D]
        do = do_ref[0, 0].astype(jnp.float32)
        row = q_start + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
        col = k_start + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
        p, _ = _bwd_p_block(
            q, k, lse_ref[0, 0], row, col, sm_scale=sm_scale, causal=causal,
            seq_len_q=seq_len_q, seq_len_k=seq_len_k, window=window,
        )
        dv_scratch[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),  # pᵀ · dO -> [blk_k, D]
            preferred_element_type=jnp.float32,
        )
        # operand dtypes matched at f32 (like _bwd_dq_kernel's dq matmul):
        # Mosaic's mixed-precision dot lowering is unverified on real TPUs
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32),  # dO · Vᵀ -> [blk_q, blk_k]
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0, 0]) * sm_scale
        dk_scratch[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32),  # dsᵀ · Q -> [blk_k, D]
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(jnp.logical_and(r == num_r - 1, i == num_q - 1))
    def _finalize():
        dk_ref[0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scratch[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
    dq_ref,
    dq_scratch,  # VMEM f32 [blk_q, D]
    *, sm_scale: float, causal: bool, blk_q: int, blk_k: int,
    seq_len_q: int, seq_len_k: int, window: int | None = None,
):
    """Grid (B·KVH, rep, q-blocks, k-blocks): k iterated sequentially, dQ
    for this q-block accumulates in VMEM across k steps. Division-free index
    maps — see _fwd_kernel."""
    j = pl.program_id(3)
    num_k = pl.num_programs(3)
    i = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        dq_scratch[:] = jnp.zeros_like(dq_scratch)

    q_start = i * blk_q
    k_start = j * blk_k
    should_compute = True
    if causal:
        should_compute = k_start <= q_start + blk_q - 1
    if window is not None:  # skip k-blocks entirely below the window
        should_compute &= k_start + blk_k - 1 >= q_start - (window - 1)

    @pl.when(should_compute)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0]
        do = do_ref[0, 0].astype(jnp.float32)
        row = q_start + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
        col = k_start + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
        p, _ = _bwd_p_block(
            q, k, lse_ref[0, 0], row, col, sm_scale=sm_scale, causal=causal,
            seq_len_q=seq_len_q, seq_len_k=seq_len_k, window=window,
        )
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0, 0]) * sm_scale
        dq_scratch[:] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == num_k - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scratch[:].astype(dq_ref.dtype)


def _flash_bwd_pallas(
    q, k, v, o, lse, do, causal, sm_scale, blk_q, blk_k, interpret,
    H: int, KVH: int, g_lse=None, window=None,
):
    """dq, dk, dv via the two Pallas kernels. q/o/do/lse are [B·H, L, D];
    k/v are [B·KVH, Lk, D] (GQA when KVH < H); dk/dv come back compact.

    ``g_lse`` ([B·H, L] or None) is the cotangent of the forward's
    log-sum-exp output (flash_attention_with_lse): since ∂lse_i/∂S_ij = P_ij
    exactly, it enters the FlashAttention-2 backward as
    dS = P ∘ (dP − delta + g_lse) — i.e. a pure shift of delta, with zero
    kernel changes."""
    BH, L, D = q.shape
    BKV = k.shape[0]
    Lk = k.shape[1]
    rep = H // KVH
    blk_q, blk_k = _compatible_blocks(blk_q, blk_k)
    Lp = _padded_len(L, Lk, blk_q, blk_k)
    qp = _pad_to(q, Lp, 1)
    kp = _pad_to(k, Lp, 1)
    vp = _pad_to(v, Lp, 1)
    dop = _pad_to(do, Lp, 1)
    # delta = rowsum(dO ⊙ O): one fused jax-level reduction
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # [BH, L]
    if g_lse is not None:
        delta = delta - g_lse.astype(jnp.float32)
    deltap = _pad_to(delta, Lp, 1)[..., None]  # [BH, Lp, 1]
    lsep = _pad_to(lse, Lp, 1)[..., None]

    # q-side tensors viewed [B·KVH, rep, Lp, ·] (group-major head order →
    # contiguous reshape) so every index map is division-free — see
    # _fwd_kernel for why that matters to Mosaic's pipeline.
    qp = qp.reshape(BKV, rep, Lp, D)
    dop = dop.reshape(BKV, rep, Lp, D)
    deltap = deltap.reshape(BKV, rep, Lp, 1)
    lsep = lsep.reshape(BKV, rep, Lp, 1)

    num_q, num_k = Lp // blk_q, Lp // blk_k

    # dK/dV: grid (B·KVH, k-blocks, rep, q-blocks) — the two trailing
    # (sequential) dimensions sweep the group's query heads and q-blocks, so
    # one kernel instance owns a KV head's full gradient.
    q_spec = pl.BlockSpec((1, 1, blk_q, D), lambda b, j, r, i: (b, r, i, 0))
    kv_spec = pl.BlockSpec((1, blk_k, D), lambda b, j, r, i: (b, j, 0))
    stat_spec = pl.BlockSpec((1, 1, blk_q, 1), lambda b, j, r, i: (b, r, i, 0))
    dkdv = functools.partial(
        _bwd_dkdv_kernel, sm_scale=sm_scale, causal=causal,
        blk_q=blk_q, blk_k=blk_k, seq_len_q=L, seq_len_k=Lk, window=window,
    )
    dk, dv = pl.pallas_call(
        dkdv,
        grid=(BKV, num_k, rep, num_q),
        in_specs=[q_spec, kv_spec, kv_spec, q_spec, stat_spec, stat_spec],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((BKV, Lp, D), k.dtype, vma=_vma(q, k, do)),
            jax.ShapeDtypeStruct((BKV, Lp, D), v.dtype, vma=_vma(q, k, do)),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_k, D), jnp.float32),
            pltpu.VMEM((blk_k, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    q_spec2 = pl.BlockSpec((1, 1, blk_q, D), lambda b, r, i, j: (b, r, i, 0))
    kv_spec2 = pl.BlockSpec((1, blk_k, D), lambda b, r, i, j: (b, j, 0))
    stat_spec2 = pl.BlockSpec((1, 1, blk_q, 1), lambda b, r, i, j: (b, r, i, 0))
    dqk = functools.partial(
        _bwd_dq_kernel, sm_scale=sm_scale, causal=causal,
        blk_q=blk_q, blk_k=blk_k, seq_len_q=L, seq_len_k=Lk, window=window,
    )
    dq = pl.pallas_call(
        dqk,
        grid=(BKV, rep, num_q, num_k),
        in_specs=[q_spec2, kv_spec2, kv_spec2, q_spec2, stat_spec2, stat_spec2],
        out_specs=q_spec2,
        out_shape=jax.ShapeDtypeStruct(
            (BKV, rep, Lp, D), q.dtype, vma=_vma(q, k, do)
        ),
        scratch_shapes=[pltpu.VMEM((blk_q, D), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    return dq.reshape(BH, Lp, D)[:, :L], dk[:, :Lk], dv[:, :Lk]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(
    q, k, v,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool | None = None,
    window: int | None = None,
):
    """Flash attention over [B, H, L, D] tensors. Differentiable.

    Grouped-query attention: ``k``/``v`` may be [B, KVH, Lk, D] with
    ``H % KVH == 0`` — the kernels map each query head to its shared KV head
    (no broadcast materialization; KV HBM traffic stays at KVH heads) and
    dk/dv are returned in the compact KVH shape.

    Default 1024-blocks measured 8x faster than 128-blocks and ~5x XLA's fused
    attention on v5e (tests/bench sweep); p-block VMEM at 1024² f32 is 4 MB,
    comfortably under the 16 MB budget with q/k/v/acc tiles. Shorter sequences
    clamp the block to the padded length. ``interpret=None`` auto-selects
    Pallas interpreter mode off-TPU.
    """
    out, _ = _flash_fwd_rule(
        q, k, v, causal, sm_scale, block_q, block_k, interpret, window
    )
    return out


def _resolve(q, sm_scale, interpret):
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return sm_scale, interpret


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                    window=None):
    sm_scale, interpret = _resolve(q, sm_scale, interpret)
    B, H, L, D = q.shape
    KVH = k.shape[1]
    if H % KVH != 0:
        raise ValueError(f"n_heads {H} not a multiple of kv_heads {KVH}")
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True (sliding window)")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
    blk_q = min(block_q, _round_up(L))
    blk_k = min(block_k, _round_up(k.shape[2]))
    out, lse = _flash_fwd(
        q, k, v, causal, sm_scale, blk_q, blk_k, interpret, window
    )
    return out, (q, k, v, out, lse)


def _bwd_impl(causal, sm_scale, block_q, block_k, interpret, residuals, g_out,
              g_lse=None, window=None):
    """Shared backward plumbing for both VJP rules (g_lse is the lse
    cotangent of the with_lse variant; None for plain flash_attention)."""
    q, k, v, out, lse = residuals
    sm_scale, interpret = _resolve(q, sm_scale, interpret)
    B, H, L, D = q.shape
    KVH = k.shape[1]
    Lk = k.shape[2]
    # The backward holds more live f32 blocks than the forward (P, dP, dS plus
    # two accumulators), so cap its tiles at 512 for VMEM headroom; 512²·f32
    # intermediates are 1 MB each.
    blk_q = min(block_q, 512, _round_up(L))
    blk_k = min(block_k, 512, _round_up(Lk))
    dq, dk, dv = _flash_bwd_pallas(
        q.reshape(B * H, L, D), k.reshape(B * KVH, Lk, D),
        v.reshape(B * KVH, Lk, D),
        out.reshape(B * H, L, D), lse, g_out.reshape(B * H, L, D),
        causal, sm_scale, blk_q, blk_k, interpret, H, KVH,
        g_lse=None if g_lse is None else g_lse.reshape(B * H, L),
        window=window,
    )
    return (
        dq.reshape(B, H, L, D),
        dk.reshape(B, KVH, Lk, D),
        dv.reshape(B, KVH, Lk, D),
    )


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret, window,
                    residuals, g):
    return _bwd_impl(causal, sm_scale, block_q, block_k, interpret, residuals,
                     g, window=window)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention_with_lse(
    q, k, v,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool | None = None,
    window: int | None = None,
):
    """Like ``flash_attention`` but also returns the per-row log-sum-exp
    ([B, H, L] f32) of the (scaled, masked) scores — the quantity needed to
    combine attention over key blocks computed separately (ring attention's
    per-hop kernel calls merge on it). Fully differentiable, INCLUDING
    through the lse output: its cotangent folds into the backward's delta
    shift (see _flash_bwd_pallas). ``window`` is the same sliding-window
    masking as ``flash_attention`` (the ring's own-block hop uses it)."""
    (out, lse), _ = _with_lse_fwd_rule(
        q, k, v, causal, sm_scale, block_q, block_k, interpret, window
    )
    return out, lse


def _with_lse_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret,
                       window=None):
    out, res = _flash_fwd_rule(
        q, k, v, causal, sm_scale, block_q, block_k, interpret, window
    )
    lse = res[4]  # [B·H, L]
    B, H, L, _ = q.shape
    return (out, lse.reshape(B, H, L)), res


def _with_lse_bwd_rule(causal, sm_scale, block_q, block_k, interpret, window,
                       residuals, g):
    g_out, g_lse = g
    return _bwd_impl(
        causal, sm_scale, block_q, block_k, interpret, residuals, g_out,
        g_lse=g_lse, window=window,
    )


flash_attention_with_lse.defvjp(_with_lse_fwd_rule, _with_lse_bwd_rule)


def _round_up(n: int, to: int = 128) -> int:
    return max(to, ((n + to - 1) // to) * to)


def uses_flash() -> bool:
    """Whether the Pallas kernel path is active on this backend — THE single
    predicate behind local_attention's dispatch, ring_attention's use_flash
    default, and the shard_map check_vma decisions (which must track the
    kernel path exactly: vma checking cannot lower pallas_call yet)."""
    return jax.devices()[0].platform == "tpu"


def local_attention(q, k, v, causal: bool = True, window: int | None = None):
    """Single-device attention with platform dispatch: the Pallas flash
    kernel on TPU, the dense reference elsewhere (CPU tests). Both are
    GQA-native (K/V may carry fewer heads than q). The ONE home for this
    dispatch — models/transformer.py and parallel/ulysses.py both route
    through it, so backend policy can't silently diverge between the
    sp-attention strategies."""
    if uses_flash():
        return flash_attention(q, k, v, causal, window=window)
    from bee_code_interpreter_tpu.parallel.ring_attention import (
        reference_attention,
    )

    return reference_attention(q, k, v, causal=causal, window=window)
