"""Flash attention for TPU (Pallas forward kernel + blockwise VJP).

Forward: a Pallas kernel tiled for the MXU — grid (batch·heads, q-blocks,
k-blocks), the k dimension iterated sequentially ("arbitrary" semantics) with
the online-softmax running max/normalizer/accumulator held in VMEM scratch
across k steps. Scores accumulate in float32 regardless of input dtype
(bfloat16 inputs hit the MXU, statistics stay fp32). Fully-masked causal
blocks are skipped with predication. O(L·block) memory instead of O(L²).

Backward: a jax-level *blockwise* recompute using the saved log-sum-exp —
``lax.scan`` over k-blocks keeps memory at O(L·block) while XLA still maps the
matmuls onto the MXU. (A hand-written Pallas backward kernel is the listed
follow-up optimization; the scan already avoids the O(L²) materialization.)

On non-TPU backends (CPU tests) the kernel runs in Pallas interpreter mode.
Sequence lengths are padded to the block size internally; padded key positions
are masked out, so any [B, H, L, D] input works.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(
    q_ref, k_ref, v_ref,  # [1, blk_q, D], [1, blk_k, D], [1, blk_k, D]
    o_ref, lse_ref,       # [1, blk_q, D], [1, blk_q, 1]
    m_scratch, l_scratch, acc_scratch,  # VMEM f32: [blk_q,1],[blk_q,1],[blk_q,D]
    *, sm_scale: float, causal: bool, blk_q: int, blk_k: int, seq_len: int,
):
    j = pl.program_id(2)
    num_k = pl.num_programs(2)

    @pl.when(j == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    i = pl.program_id(1)
    q_start = i * blk_q
    k_start = j * blk_k

    # causal: skip blocks where every key index > every query index
    should_compute = True
    if causal:
        should_compute = k_start <= q_start + blk_q - 1

    @pl.when(should_compute)
    def _compute():
        # inputs stay in their native dtype (bf16 rides the MXU at full rate);
        # the MXU accumulates in f32 via preferred_element_type
        q = q_ref[0]
        k = k_ref[0]
        scores = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale  # [blk_q, blk_k] f32

        row = q_start + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 0)
        col = k_start + lax.broadcasted_iota(jnp.int32, (blk_q, blk_k), 1)
        mask = col < seq_len  # padded keys never attend
        if causal:
            mask = mask & (row >= col)
        scores = jnp.where(mask, scores, NEG_INF)

        m_prev = m_scratch[:]                      # [blk_q, 1]
        block_max = jnp.max(scores, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, block_max)
        correction = jnp.exp(m_prev - m_next)
        p = jnp.exp(scores - m_next)               # [blk_q, blk_k]
        l_next = l_scratch[:] * correction + jnp.sum(p, axis=1, keepdims=True)
        # P in the input dtype for the MXU, f32 accumulation
        pv = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_scratch[:] = acc_scratch[:] * correction + pv
        m_scratch[:] = m_next
        l_scratch[:] = l_next

    @pl.when(j == num_k - 1)
    def _finalize():
        l = jnp.maximum(l_scratch[:], 1e-30)
        o_ref[0] = (acc_scratch[:] / l).astype(o_ref.dtype)
        lse_ref[0] = m_scratch[:] + jnp.log(l)  # [blk_q, 1]


def _pad_to(x, length, axis):
    pad = length - x.shape[axis]
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_fwd(q, k, v, causal, sm_scale, blk_q, blk_k, interpret):
    B, H, L, D = q.shape
    Lk = k.shape[2]
    Lp = max(blk_q, blk_k) * pl.cdiv(max(L, Lk), max(blk_q, blk_k))
    qp = _pad_to(q.reshape(B * H, L, D), Lp, axis=1)
    kp = _pad_to(k.reshape(B * H, Lk, D), Lp, axis=1)
    vp = _pad_to(v.reshape(B * H, Lk, D), Lp, axis=1)

    grid = (B * H, Lp // blk_q, Lp // blk_k)
    kernel = functools.partial(
        _fwd_kernel, sm_scale=sm_scale, causal=causal,
        blk_q=blk_q, blk_k=blk_k, seq_len=Lk,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, blk_k, D), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk_q, D), lambda b, i, j: (b, i, 0)),
            # lse is [BH, L, 1]: block (1, blk_q, 1) satisfies TPU tiling
            # (trailing dim equals the full array dim)
            pl.BlockSpec((1, blk_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Lp, D), q.dtype),
            jax.ShapeDtypeStruct((B * H, Lp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, 1), jnp.float32),
            pltpu.VMEM((blk_q, D), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            # batch·heads and q-blocks are independent; only the k dimension
            # carries the online-softmax state
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :L].reshape(B, H, L, D), lse[:, :L, 0]


def _attention_bwd_blockwise(q, k, v, o, lse, do, causal, sm_scale, blk_k):
    """dq, dk, dv via scan over k-blocks with the saved lse. All [BH, L, D]."""
    BH, L, D = q.shape
    Lk = k.shape[1]
    nblk = pl.cdiv(Lk, blk_k)
    Lkp = nblk * blk_k
    kp = _pad_to(k, Lkp, 1).reshape(BH, nblk, blk_k, D)
    vp = _pad_to(v, Lkp, 1).reshape(BH, nblk, blk_k, D)

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * o.astype(jnp.float32), axis=-1)  # [BH, L]
    row_idx = lax.broadcasted_iota(jnp.int32, (L, blk_k), 0)

    def body(dq, blocks):
        k_blk, v_blk, j = blocks  # [BH, blk_k, D], scalar block index
        col_idx = j * blk_k + lax.broadcasted_iota(jnp.int32, (L, blk_k), 1)
        scores = jnp.einsum(
            "bld,bkd->blk", qf, k_blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        mask = col_idx < Lk
        if causal:
            mask = mask & (row_idx >= col_idx)
        scores = jnp.where(mask, scores, NEG_INF)
        p = jnp.exp(scores - lse[..., None])  # [BH, L, blk_k]
        dv_blk = jnp.einsum("blk,bld->bkd", p, dof)
        dp = jnp.einsum("bld,bkd->blk", dof, v_blk.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * sm_scale
        dq = dq + jnp.einsum("blk,bkd->bld", ds, k_blk.astype(jnp.float32))
        dk_blk = jnp.einsum("blk,bld->bkd", ds, qf)
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_blocks, dv_blocks) = lax.scan(
        body, dq0,
        (kp.transpose(1, 0, 2, 3), vp.transpose(1, 0, 2, 3), jnp.arange(nblk)),
    )
    dk = dk_blocks.transpose(1, 0, 2, 3).reshape(BH, Lkp, D)[:, :Lk]
    dv = dv_blocks.transpose(1, 0, 2, 3).reshape(BH, Lkp, D)[:, :Lk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q, k, v,
    causal: bool = True,
    sm_scale: float | None = None,
    block_q: int = 1024,
    block_k: int = 1024,
    interpret: bool | None = None,
):
    """Flash attention over [B, H, L, D] tensors. Differentiable.

    Default 1024-blocks measured 8x faster than 128-blocks and ~5x XLA's fused
    attention on v5e (tests/bench sweep); p-block VMEM at 1024² f32 is 4 MB,
    comfortably under the 16 MB budget with q/k/v/acc tiles. Shorter sequences
    clamp the block to the padded length. ``interpret=None`` auto-selects
    Pallas interpreter mode off-TPU.
    """
    out, _ = _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret)
    return out


def _resolve(q, sm_scale, interpret):
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    return sm_scale, interpret


def _flash_fwd_rule(q, k, v, causal, sm_scale, block_q, block_k, interpret):
    sm_scale, interpret = _resolve(q, sm_scale, interpret)
    B, H, L, D = q.shape
    blk_q = min(block_q, _round_up(L))
    blk_k = min(block_k, _round_up(k.shape[2]))
    out, lse = _flash_fwd(q, k, v, causal, sm_scale, blk_q, blk_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, sm_scale, block_q, block_k, interpret, residuals, g):
    q, k, v, out, lse = residuals
    sm_scale, _ = _resolve(q, sm_scale, interpret)
    B, H, L, D = q.shape
    Lk = k.shape[2]
    dq, dk, dv = _attention_bwd_blockwise(
        q.reshape(B * H, L, D), k.reshape(B * H, Lk, D), v.reshape(B * H, Lk, D),
        out.reshape(B * H, L, D), lse, g.reshape(B * H, L, D),
        causal, sm_scale, block_k,
    )
    return dq.reshape(B, H, L, D), dk.reshape(B, H, Lk, D), dv.reshape(B, H, Lk, D)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _round_up(n: int, to: int = 128) -> int:
    return max(to, ((n + to - 1) // to) * to)
