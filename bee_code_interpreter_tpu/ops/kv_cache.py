"""Int8 KV-cache quantization for the decode path.

Incremental decode is KV-cache-bandwidth-bound: every generated token
re-reads the whole cache, and the matmuls against a 1-token query are
MXU-trivial. Storing K/V as int8 with per-(token, head) absmax scales halves
the bytes streamed per step vs bf16 (scales add 1/64 overhead at
head_dim 128) — on top of the 4× the compact GQA layout already saves.

Symmetric per-row quantization: ``s = absmax(x) / 127`` over the head_dim
axis, ``q = round(x / s)``. The dequantize multiply rides the attention
einsum's operand pipeline (XLA fuses convert+scale into the dot's input),
so f32 K/V never materializes in HBM.

The transformer opts in via ``TransformerConfig(kv_cache_dtype="int8")``
(models/transformer.py decode path); accuracy cost is pinned by
tests/test_kv_cache.py (greedy decode vs the bf16 cache).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

Quantized = tuple[jax.Array, jax.Array]  # (int8 values, f32 scales)


def quantize(x: jax.Array, axis: int = -1) -> Quantized:
    """Symmetric int8 quantization with absmax scales over ``axis``.

    Returns (q int8 same shape, scale f32 with ``axis`` size 1). Zero rows
    quantize to zeros with scale 0 (dequantizes to exact zeros).
    """
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = absmax / 127.0
    q = jnp.where(
        scale > 0.0,
        jnp.round(x.astype(jnp.float32) / jnp.maximum(scale, 1e-30)),
        0.0,
    )
    return q.astype(jnp.int8), scale


def dequantize(q: jax.Array, scale: jax.Array, dtype=jnp.float32) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)
