"""Int8 KV-cache quantization for the decode path.

Incremental decode is KV-cache-bandwidth-bound: every generated token
re-reads the whole cache, and the matmuls against a 1-token query are
MXU-trivial. Storing K/V as int8 with per-(token, head) absmax scales halves
the bytes streamed per step vs bf16 (scales add 1/64 overhead at
head_dim 128) — on top of the 4× the compact GQA layout already saves.

Symmetric per-row quantization: ``s = absmax(x) / 127`` over the head_dim
axis, ``q = round(x / s)``. The dequantize multiply rides the attention
einsum's operand pipeline (XLA fuses convert+scale into the dot's input),
so f32 K/V never materializes in HBM.

The transformer opts in via ``TransformerConfig(kv_cache_dtype="int8")``
(models/transformer.py decode path); accuracy cost is pinned by
tests/test_kv_cache.py (greedy decode vs the bf16 cache).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

Quantized = tuple[jax.Array, jax.Array]  # (int8 values, f32 scales)


def quantize(x: jax.Array, axis: int = -1) -> Quantized:
    """Symmetric int8 quantization with absmax scales over ``axis``.

    Returns (q int8 same shape, scale f32 with ``axis`` size 1). Zero rows
    quantize to zeros with scale 0 (dequantizes to exact zeros).
    """
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = absmax / 127.0
    q = jnp.where(
        scale > 0.0,
        jnp.round(x.astype(jnp.float32) / jnp.maximum(scale, 1e-30)),
        0.0,
    )
    return q.astype(jnp.int8), scale


def dequantize(q: jax.Array, scale: jax.Array, dtype=jnp.float32) -> jax.Array:
    return (q.astype(jnp.float32) * scale).astype(dtype)


# --- layout strategy -------------------------------------------------------
# The ONE pair of append/read primitives both decode bodies go through
# (models/transformer.decode_window — decode_step is its W=1 case), so the
# bf16 and int8 cache layouts cannot drift apart in the layer math (VERDICT
# r3 weak #2: the int8 decode body was a near-copy of the bf16 one). The
# layout is self-describing: the presence of scale leaves ("k_s"/"v_s")
# selects the int8 strategy, so these work on a per-layer slice inside
# lax.scan and on the full [n_layers, ...] stack at init alike.


def cache_append(c_layer: dict, k_new: jax.Array, v_new: jax.Array,
                 pos: jax.Array | int) -> dict:
    """Write new K/V rows at positions ``pos..pos+W-1`` of the -2 axis.

    ``k_new``/``v_new`` carry W consecutive rows. The int8 layout quantizes
    per (token, head) row — each row's scale depends only on that row, so a
    window append is bit-identical to W single-row appends (what makes
    speculative decoding's window-verify exact over the quantized cache).
    """

    def upd(name: str, val: jax.Array) -> jax.Array:
        return lax.dynamic_update_slice_in_dim(
            c_layer[name], val, pos, axis=c_layer[name].ndim - 2
        )

    if "k_s" in c_layer:
        kq, ks = quantize(k_new)
        vq, vs = quantize(v_new)
        return {"k": upd("k", kq), "v": upd("v", vq),
                "k_s": upd("k_s", ks), "v_s": upd("v_s", vs)}
    dtype = c_layer["k"].dtype
    return {"k": upd("k", k_new.astype(dtype)),
            "v": upd("v", v_new.astype(dtype))}


def cache_read(c_layer: dict, dtype) -> tuple[jax.Array, jax.Array]:
    """(K as f32 for the scores einsum, V as ``dtype`` for the output
    einsum). Dequantization rides the einsums' operand pipeline — XLA fuses
    convert+scale into the dot, so f32 K/V never lands in HBM."""
    if "k_s" in c_layer:
        return (
            dequantize(c_layer["k"], c_layer["k_s"]),
            dequantize(c_layer["v"], c_layer["v_s"], dtype),
        )
    return c_layer["k"].astype(jnp.float32), c_layer["v"]
