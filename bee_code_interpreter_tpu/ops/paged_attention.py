"""Pallas paged-attention decode kernel: K/V pages read IN PLACE.

The einsum decode path (``ops/paged_kv_cache.paged_read`` +
``models/transformer.decode_window_paged``) gathers each row's pages into
a contiguous [B, kvh, S, dh] view before the attention einsums — on TPU
that gather MATERIALIZES a full copy of the visible cache in HBM every
decode step, doubling the traffic of the already-bandwidth-bound loop.
This kernel removes the copy: the page pool is an input whose BlockSpec
index map reads the block table through Pallas SCALAR PREFETCH
(``pltpu.PrefetchScalarGridSpec``), so each grid step DMAs exactly one
physical page from wherever it lives — the indirection costs an index
lookup, not a gather.

Structure — the flash forward kernel's online softmax specialized to
decode (one query token per row):

- grid (B, kvh, P): pages sequential innermost, the per-(row, kv-head)
  running max/normalizer/accumulator in VMEM scratch across page steps;
- GQA-native: the ``rep = nh/kvh`` query heads sharing a KV head form the
  kernel's row block (padded to the 8-row sublane tile when rep < 8);
- per-row visible lengths ride the second scalar-prefetch operand: pages
  at or beyond a row's length are skipped by predication, slots past the
  length inside the boundary page are masked to -inf.

bf16/f32 pools only — the int8 pool's per-slot scale planes stay on the
einsum path (dequantization there rides the gather it already pays).
CPU tests run the kernel in Pallas interpreter mode against the grouped
einsum oracle (tests/test_paged_attention.py); Mosaic lowering and the
HBM win are measured on hardware by scripts/bench-decode.py.

The reference has no kernels at all (SURVEY §2); within this rebuild the
kernel is the serving-side sibling of ops/flash_attention.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _kernel(
    bt_ref,        # scalar prefetch: [B, P] block table (int32)
    len_ref,       # scalar prefetch: [B] visible lengths (int32)
    q_ref,         # [1, 1, rep_p, dh]
    k_ref,         # [1, 1, ps, dh] — the page selected by the index map
    v_ref,         # [1, 1, ps, dh]
    o_ref,         # [1, 1, rep_p, dh]
    m_s, l_s, acc_s,  # VMEM f32: [rep_p, 1], [rep_p, 1], [rep_p, dh]
    *, ps: int, sm_scale: float,
):
    b = pl.program_id(0)
    p = pl.program_id(2)
    num_pages = pl.num_programs(2)

    @pl.when(p == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    length = len_ref[b]
    base = p * ps

    @pl.when(base < length)
    def _compute():
        q = q_ref[0, 0].astype(jnp.float32)        # [rep_p, dh]
        k = k_ref[0, 0].astype(jnp.float32)        # [ps, dh]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                               # [rep_p, ps]
        slot = base + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(slot < length, s, NEG_INF)

        m_prev, l_prev = m_s[:], l_s[:]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        pexp = jnp.exp(s - m_new)
        l_s[:] = l_prev * alpha + pexp.sum(axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            pexp, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_s[:] = m_new

    @pl.when(p == num_pages - 1)
    def _finalize():
        o_ref[0, 0] = (
            acc_s[:] / jnp.maximum(l_s[:], 1e-30)
        ).astype(o_ref.dtype)


def paged_decode_attention(
    q: jax.Array,            # [B, nh, dh] — ONE query token per row
    k_pages: jax.Array,      # [n_pages, kvh, ps, dh] — one layer's pool
    v_pages: jax.Array,
    block_table: jax.Array,  # [B, P] int32 logical block -> physical page
    lengths: jax.Array,      # [B] int32 visible length per row (pos + 1)
    sm_scale: float | None = None,
    interpret: bool | None = None,
) -> jax.Array:              # [B, nh, dh]
    """Single-token paged attention with in-place page reads (module
    docstring). GQA-native: ``nh % kvh == 0``; bf16/f32 pools."""
    B, nh, dh = q.shape
    n_pages, kvh, ps, _ = k_pages.shape
    P = block_table.shape[1]
    if nh % kvh:
        raise ValueError(f"n_heads {nh} not a multiple of kv_heads {kvh}")
    rep = nh // kvh
    rep_p = max(8, -(-rep // 8) * 8)  # query rows padded to the sublane tile
    if sm_scale is None:
        sm_scale = dh ** -0.5
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    # group-major view [B, kvh, rep, dh], zero-padded to rep_p rows
    qg = q.reshape(B, kvh, rep, dh)
    if rep_p != rep:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, rep_p - rep), (0, 0)))

    grid = (B, kvh, P)
    out = pl.pallas_call(
        functools.partial(_kernel, ps=ps, sm_scale=float(sm_scale)),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, rep_p, dh), lambda b, h, p, bt, lens: (b, h, 0, 0)
                ),
                # THE point: the page index comes from the prefetched
                # block table, over the pool's NATIVE layout — the DMA
                # reads the physical page in place (any relayout of the
                # pool here would itself be the copy this kernel exists
                # to avoid)
                # the index is clamped to the pool: entries at/past a
                # row's visible length have their compute predicated off
                # but the DMA still issues, and a sentinel like -1 (a
                # common block-table convention) would read out of bounds
                # in the Mosaic path while passing interpreter-mode tests
                pl.BlockSpec(
                    (1, 1, ps, dh),
                    lambda b, h, p, bt, lens: (
                        jnp.clip(bt[b, p], 0, n_pages - 1), h, 0, 0
                    ),
                ),
                pl.BlockSpec(
                    (1, 1, ps, dh),
                    lambda b, h, p, bt, lens: (
                        jnp.clip(bt[b, p], 0, n_pages - 1), h, 0, 0
                    ),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, rep_p, dh), lambda b, h, p, bt, lens: (b, h, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((rep_p, 1), jnp.float32),
                pltpu.VMEM((rep_p, 1), jnp.float32),
                pltpu.VMEM((rep_p, dh), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, kvh, rep_p, dh), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ) if not interpret else None,
        interpret=interpret,
    )(
        block_table.astype(jnp.int32), lengths.astype(jnp.int32),
        qg, k_pages, v_pages,
    )
    return out[:, :, :rep].reshape(B, nh, dh)
