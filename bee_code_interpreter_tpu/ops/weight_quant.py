"""Weight-only int8 quantization for the decode family.

Decode is HBM-bandwidth-bound: every step streams the full parameter set
to produce one token per row, so halving the bytes per weight is (up to)
a 2x decode speedup before any kernel work. This module quantizes the
matmul weights to symmetric per-OUT-CHANNEL int8:

    W[..., in, out]  ->  {"q": int8 same shape, "s": f32 [..., out]}
    with  W ≈ q * s[..., None, :],  s = max|W| per out column / 127

and the compute path (``transformer.qeinsum``) evaluates

    y = (x @ q.astype(compute_dtype)) * s

— the scale applied as a matmul EPILOGUE, exact algebra for per-out
scales, so the int8→bf16 convert fuses into the dot read and no
dequantized weight copy ever materializes in HBM. Activations and the
KV cache are untouched (w8a16; the int8 KV cache in ops/kv_cache.py
composes independently).

What quantizes: the seven dense projection weights per layer and the
lm_head. What doesn't: embeddings (a gather, not a matmul), norms
(1-D), MoE expert weights (expert matmuls route through moe.py), LoRA
banks (rank-r deltas are tiny and applied on the raw activations —
multi-LoRA serving composes with a quantized base). Quantize AFTER
``merge_lora`` if folding adapters.

Every decode/forward path takes the quantized pytree interchangeably
with the fp one (the ``qeinsum`` dispatch is per leaf), so the
cross-path exactness pins (decode == forward, batched == solo) hold
verbatim ON the quantized model; closeness TO the fp model is a
quantization-quality property, tested with tolerances. The reference
has no model runtime at all (SURVEY §2).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

Params = dict[str, Any]

DEFAULT_TARGETS = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
)


def is_quantized(leaf) -> bool:
    """THE quantized-leaf predicate — the schema lives here; every
    consumer (qeinsum dispatch, serving/lora guards, sharding refusal)
    imports this instead of duck-typing the dict shape itself."""
    return isinstance(leaf, dict) and "q" in leaf and "s" in leaf


def any_quantized(params: Params) -> bool:
    layers = params.get("layers", {})
    return is_quantized(params.get("lm_head")) or any(
        is_quantized(leaf) for leaf in layers.values()
    )


def quantize_weight(w: jnp.ndarray) -> dict:
    """One weight [..., d_in, d_out] -> {"q": int8, "s": f32 [..., d_out]}
    (symmetric, per out column; stacked [n_layers, ...] leaves keep their
    leading axis on both leaves, so lax.scan slices them together)."""
    amax = jnp.max(jnp.abs(w), axis=-2, keepdims=True)
    s = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = (
        jnp.clip(jnp.round(w.astype(jnp.float32) / s), -127, 127)
        .astype(jnp.int8)
    )
    return {"q": q, "s": s.squeeze(-2)}


def quantize_weights(
    params: Params, targets: tuple[str, ...] = DEFAULT_TARGETS
) -> Params:
    """The params pytree with every ``targets`` matmul weight quantized —
    drop-in for forward/decode/serving (see module docstring)."""
    out = dict(params)
    out["layers"] = {
        name: quantize_weight(leaf) if name in targets else leaf
        for name, leaf in params["layers"].items()
    }
    if "lm_head" in targets and "lm_head" in params:
        out["lm_head"] = quantize_weight(params["lm_head"])
    return out


def quantized_nbytes(params: Params) -> int:
    """Total bytes of every array leaf (dicts included) — the memory
    claim's receipt."""
    return sum(x.nbytes for x in jax.tree.leaves(params))
