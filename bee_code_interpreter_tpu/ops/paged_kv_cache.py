"""Paged KV cache: block-table indirection over a shared page pool.

The modern serving primitive (vLLM's PagedAttention, here TPU-first):
instead of one contiguous [B, kvh, max_len, dh] cache per batch — which
reserves worst-case length for every row — K/V live in fixed-size PAGES
drawn from one pool, and each sequence maps logical block → physical page
through a small int32 block table. Heterogeneous-length requests then share
the pool densely: a 100-token and a 4000-token request cost pages
proportional to their actual lengths, and a finished request's pages are
recycled immediately (models/serving.py does the recycling — continuous
batching).

TPU-first constraints shape the layout:

- **Static shapes everywhere.** The pool, the block table, and the gather
  in ``paged_read`` are all fixed-size; "allocation" is host-side integer
  bookkeeping between steps, never a traced shape change.
- **Gather/scatter ride XLA.** ``paged_read`` is one advanced-indexing
  gather (lowered to a single dynamic-gather HLO) producing the same
  [B, kvh, S, dh] view the contiguous attention einsums consume — the
  decode layer math is UNCHANGED (models/transformer.decode_step_paged
  reuses the grouped-query einsums), so paged-vs-contiguous equality is a
  pure indexing property, pinned by tests/test_paged_kv_cache.py.
- **Page size is a multiple of the lane tile.** Pages are [kvh, page_size,
  dh] slabs; dh is contiguous and page_size defaults to a multiple of 8 so
  gathered slabs keep the (8, 128) tiling XLA wants.

The reference has no serving stack at all (SURVEY §2); this module is part
of the rebuild's decode family next to the int8 cache (ops/kv_cache.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bee_code_interpreter_tpu.ops.kv_cache import quantize


def pool_telemetry(
    *,
    block_table: np.ndarray,  # [B, P] int32, scratch-page entries for holes
    pos: np.ndarray,  # [B] int32 decode cursors (tokens written per row)
    active: np.ndarray,  # [B] bool
    page_ref: np.ndarray,  # [n_pages] int32 refcounts
    page_size: int,
    free_pages: int,
    parked_pages: int,
    scratch_page: int = 0,
) -> dict:
    """Host-side page-pool telemetry (docs/observability.md "Serving
    observability") — pure integer bookkeeping over the scheduler's own
    state, zero device traffic, cheap enough for every ``/metrics`` scrape.

    ``fragmentation`` is slot-level INTERNAL fragmentation of the pages
    active rows hold: ``1 - used_slots / allocated_slots``. A page holds
    ``page_size`` K/V slots but a row's cursor covers only ``pos`` of the
    slots its pages reserve — the tail of the last page (and budget-sized
    over-allocation) is capacity the pool cannot hand to anyone else.
    Prefix-shared pages are counted once per HOLDER (each sharer's table
    maps them), which is deliberate: the metric describes how efficiently
    *reserved* capacity is used, and a shared page is reserved by every
    sharer's admission arithmetic. ``pages_shared`` (refcount > 1) reports
    the sharing itself.
    """
    n_pages = int(page_ref.shape[0])
    held = int((page_ref > 0).sum())
    shared = int((page_ref > 1).sum())
    slots_allocated = 0
    slots_used = 0
    for row in np.flatnonzero(active):
        row_pages = int((block_table[row] != scratch_page).sum())
        slots_allocated += row_pages * page_size
        slots_used += int(pos[row])
    fragmentation = (
        1.0 - slots_used / slots_allocated if slots_allocated else 0.0
    )
    return {
        "pages_total": n_pages - 1,  # the scratch page is never allocatable
        "pages_free": free_pages,
        "pages_parked": parked_pages,
        "pages_held": held,
        "pages_shared": shared,
        "page_size": page_size,
        "slots_allocated": slots_allocated,
        "slots_used": slots_used,
        "fragmentation": fragmentation,
    }


def alloc_paged_cache(config, n_pages: int, page_size: int) -> dict:
    """Zeroed page pool: k/v [n_layers, n_pages, kvh, page_size, dh].

    One pool serves every layer by giving each layer its own leading-axis
    slice of every page — a sequence's page i holds layer ℓ's tokens at
    ``pages[ℓ, page]``, so the block table is shared across layers (one
    table per sequence, not per layer — same trick as the stacked
    contiguous cache).

    ``kv_cache_dtype="int8"`` stores int8 values plus per-(token, head)
    scale planes per page — the same self-describing layout convention as
    the contiguous cache (ops/kv_cache.py): scale leaves present selects
    the quantized strategy in append/read, and the decode bandwidth halves
    on top of paging's density win.
    """
    c = config
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    shape = (c.n_layers, n_pages, c.kv_heads, page_size, c.head_dim)
    if c.kv_cache_dtype == "int8":
        return {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_s": jnp.zeros(shape[:-1] + (1,), jnp.float32),
            "v_s": jnp.zeros(shape[:-1] + (1,), jnp.float32),
        }
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def paged_append(
    c_layer: dict,  # one layer's pool slice: [n_pages, kvh, ps, dh]
    k_new: jax.Array,  # [B, W, kvh, dh] — W consecutive tokens per row
    v_new: jax.Array,
    page_idx: jax.Array,  # [B, W] int32 physical page per (row, token)
    slot_idx: jax.Array,  # [B, W] int32 slot within the page
) -> dict:
    """Scatter W new tokens' K/V per batch row into their (page, slot)s.

    Rows of a batch may land in arbitrary distinct pages, and a row's W
    tokens may straddle a page boundary — the scatter is one XLA scatter
    op either way. Two (row, token)s writing the same (page, slot) is a
    scheduler bug (pages are owned by one sequence); last-writer-wins as
    with any scatter. The int8 layout quantizes per (token, head) row —
    identical semantics to the contiguous cache_append, so paged int8
    decode equals contiguous int8 decode (and a window append is
    bit-identical to W single appends, which keeps paged speculative
    verify exact).
    """
    if "k_s" in c_layer:
        kq, ks = quantize(k_new)  # [B, W, kvh, dh] -> values + [B, W, kvh, 1]
        vq, vs = quantize(v_new)
        return {
            "k": c_layer["k"].at[page_idx, :, slot_idx, :].set(kq),
            "v": c_layer["v"].at[page_idx, :, slot_idx, :].set(vq),
            "k_s": c_layer["k_s"].at[page_idx, :, slot_idx, :].set(ks),
            "v_s": c_layer["v_s"].at[page_idx, :, slot_idx, :].set(vs),
        }
    dtype = c_layer["k"].dtype
    return {
        "k": c_layer["k"].at[page_idx, :, slot_idx, :].set(
            k_new.astype(dtype)
        ),
        "v": c_layer["v"].at[page_idx, :, slot_idx, :].set(
            v_new.astype(dtype)
        ),
    }


def paged_read(
    c_layer: dict,  # [n_pages, kvh, ps, dh]
    block_table: jax.Array,  # [B, P] int32 logical block -> physical page
    dtype,  # V compute dtype — required, matching cache_read's contract
) -> tuple[jax.Array, jax.Array]:
    """Gather each row's pages into the contiguous [B, kvh, P·ps, dh] view
    the attention einsums consume. K comes back f32 (scores operand), V in
    ``dtype`` — the same contract as ops/kv_cache.cache_read; int8 pools
    dequantize after the gather (scales gathered alongside)."""
    B, P = block_table.shape
    n_pages, kvh, ps, dh = c_layer["k"].shape

    def view(x, out_dtype):
        g = x[block_table]  # [B, P, kvh, ps, last]
        last = x.shape[-1]
        return (
            g.transpose(0, 2, 1, 3, 4).reshape(B, kvh, P * ps, last)
            .astype(out_dtype)
        )

    if "k_s" in c_layer:
        from bee_code_interpreter_tpu.ops.kv_cache import dequantize

        return (
            dequantize(view(c_layer["k"], jnp.int8), view(c_layer["k_s"], jnp.float32)),
            dequantize(view(c_layer["v"], jnp.int8), view(c_layer["v_s"], jnp.float32), dtype),
        )
    return view(c_layer["k"], jnp.float32), view(c_layer["v"], dtype)


def seed_prefill(
    cache: dict,  # full pool: leaves [n_layers, n_pages, ...]
    pages: jax.Array,  # [P] int32 physical pages covering ceil(L/ps)
    k_pre: jax.Array,  # [n_layers, kvh, L, dh] — one sequence's prefill K
    v_pre: jax.Array,
) -> dict:
    """Write one sequence's prefill K/V into its pages — ONE batched
    scatter per pool leaf; the single copy of the prefill-seeding logic
    (serving.ContinuousBatcher.submit and the equality tests both call
    this, so the tested path IS the served path). int8 pools quantize per
    (token, head) row, identical to cache_append's semantics; the pad tail
    quantizes to scale-0 exact zeros and stays masked by ``s <= pos``."""
    ps = cache["k"].shape[3]
    n_pages_used = int(pages.shape[0])
    L = k_pre.shape[2]
    if L > n_pages_used * ps:
        raise ValueError(
            f"prefill length {L} exceeds {n_pages_used} pages of {ps}"
        )

    def page_view(x):  # [n_layers, kvh, L, dh] -> [n_layers, P, kvh, ps, dh]
        x = jnp.pad(
            x, ((0, 0), (0, 0), (0, n_pages_used * ps - L), (0, 0))
        )
        nl, kvh, _, dh = x.shape
        return x.reshape(nl, kvh, n_pages_used, ps, dh).transpose(0, 2, 1, 3, 4)

    def put(cache, name, sname, pre):
        vals = page_view(pre)
        if sname in cache:
            q, s = quantize(vals)
            return {
                **cache,
                name: cache[name].at[:, pages].set(q),
                sname: cache[sname].at[:, pages].set(s),
            }
        return {
            **cache,
            name: cache[name].at[:, pages].set(
                vals.astype(cache[name].dtype)
            ),
        }

    cache = put(cache, "k", "k_s", k_pre)
    return put(cache, "v", "v_s", v_pre)


def seed_from_contiguous(
    cache: dict,  # paged pool: leaves [n_layers, n_pages, kvh, ps, last]
    pages: jax.Array,  # [P] int32 — pages covering the contiguous cache
    contig_row: dict,  # ONE row's contiguous cache: [n_layers, kvh, P·ps, last]
) -> dict:
    """Copy a contiguous-cache row (already in the pool's layout — bf16
    values or int8 values+scales) into pages VERBATIM. This is how chunked
    prefill admits into the pool: ``prefill_chunked`` builds the layout
    (quantizing per row for int8), and re-quantizing its dequantized
    values would double the rounding — a straight leaf copy keeps paged
    admission bit-identical to the contiguous cache it came from."""
    P = int(pages.shape[0])
    ps = cache["k"].shape[3]
    out = dict(cache)
    for name, x in contig_row.items():
        nl, kvh, total, last = x.shape
        if total != P * ps:
            raise ValueError(
                f"contiguous length {total} != {P} pages of {ps}"
            )
        vals = x.reshape(nl, kvh, P, ps, last).transpose(0, 2, 1, 3, 4)
        out[name] = out[name].at[:, pages].set(vals.astype(out[name].dtype))
    return out
