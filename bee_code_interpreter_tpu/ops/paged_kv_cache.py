"""Paged KV cache: block-table indirection over a shared page pool.

The modern serving primitive (vLLM's PagedAttention, here TPU-first):
instead of one contiguous [B, kvh, max_len, dh] cache per batch — which
reserves worst-case length for every row — K/V live in fixed-size PAGES
drawn from one pool, and each sequence maps logical block → physical page
through a small int32 block table. Heterogeneous-length requests then share
the pool densely: a 100-token and a 4000-token request cost pages
proportional to their actual lengths, and a finished request's pages are
recycled immediately (models/serving.py does the recycling — continuous
batching).

TPU-first constraints shape the layout:

- **Static shapes everywhere.** The pool, the block table, and the gather
  in ``paged_read`` are all fixed-size; "allocation" is host-side integer
  bookkeeping between steps, never a traced shape change.
- **Gather/scatter ride XLA.** ``paged_read`` is one advanced-indexing
  gather (lowered to a single dynamic-gather HLO) producing the same
  [B, kvh, S, dh] view the contiguous attention einsums consume — the
  decode layer math is UNCHANGED (models/transformer.decode_step_paged
  reuses the grouped-query einsums), so paged-vs-contiguous equality is a
  pure indexing property, pinned by tests/test_paged_kv_cache.py.
- **Page size is a multiple of the lane tile.** Pages are [kvh, page_size,
  dh] slabs; dh is contiguous and page_size defaults to a multiple of 8 so
  gathered slabs keep the (8, 128) tiling XLA wants.

The reference has no serving stack at all (SURVEY §2); this module is part
of the rebuild's decode family next to the int8 cache (ops/kv_cache.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def alloc_paged_cache(config, n_pages: int, page_size: int) -> dict:
    """Zeroed page pool: k/v [n_layers, n_pages, kvh, page_size, dh].

    One pool serves every layer by giving each layer its own leading-axis
    slice of every page — a sequence's page i holds layer ℓ's tokens at
    ``pages[ℓ, page]``, so the block table is shared across layers (one
    table per sequence, not per layer — same trick as the stacked
    contiguous cache).
    """
    c = config
    if page_size < 1:
        raise ValueError(f"page_size must be >= 1, got {page_size}")
    shape = (c.n_layers, n_pages, c.kv_heads, page_size, c.head_dim)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def paged_append(
    c_layer: dict,  # one layer's pool slice: [n_pages, kvh, ps, dh]
    k_new: jax.Array,  # [B, kvh, dh] — one token per row
    v_new: jax.Array,
    page_idx: jax.Array,  # [B] int32 physical page per row
    slot_idx: jax.Array,  # [B] int32 slot within the page
) -> dict:
    """Scatter one new token's K/V per batch row into its (page, slot).

    Rows of a batch may land in arbitrary distinct pages — the scatter is
    one XLA scatter op. Two rows writing the same (page, slot) is a
    scheduler bug (pages are owned by one sequence); last-writer-wins as
    with any scatter.
    """
    dtype = c_layer["k"].dtype
    return {
        "k": c_layer["k"].at[page_idx, :, slot_idx, :].set(
            k_new.astype(dtype)
        ),
        "v": c_layer["v"].at[page_idx, :, slot_idx, :].set(
            v_new.astype(dtype)
        ),
    }


def paged_read(
    c_layer: dict,  # [n_pages, kvh, ps, dh]
    block_table: jax.Array,  # [B, P] int32 logical block -> physical page
) -> tuple[jax.Array, jax.Array]:
    """Gather each row's pages into the contiguous [B, kvh, P·ps, dh] view
    the attention einsums consume. K comes back f32 (scores operand), V in
    the pool dtype — the same contract as ops/kv_cache.cache_read."""
    B, P = block_table.shape
    n_pages, kvh, ps, dh = c_layer["k"].shape

    def view(x, dtype):
        g = x[block_table]  # [B, P, kvh, ps, dh]
        return (
            g.transpose(0, 2, 1, 3, 4).reshape(B, kvh, P * ps, dh)
            .astype(dtype)
        )

    return view(c_layer["k"], jnp.float32), view(
        c_layer["v"], c_layer["v"].dtype
    )
