"""The fleet router's own aiohttp app (docs/fleet.md).

Launchable (``python -m bee_code_interpreter_tpu.fleet``) and embeddable in
tests (``create_router_app(FleetRouter([...]))``). Proxied surface:

- ``POST /v1/execute`` (+ ``?stream=1`` SSE passthrough) — consistent-hash
  affinity on the request's ``files`` map, cross-replica retry of sheds,
  unavailability, 5xx, and unreachable replicas.
- ``POST /v1/parse-custom-tool`` / ``/v1/execute-custom-tool`` — keyless
  (load-based) placement, same retry envelope.
- ``POST /v1/sessions`` — placed by the initial snapshot's affinity key and
  PINNED; every ``/v1/sessions/{id}/*`` call then follows the pin (never
  retried cross-replica: the lease is one sandbox on one replica).
- ``GET /v1/fleet/replicas`` — the router's decision/health view;
  ``POST /v1/fleet/replicas/{name}/drain`` evacuates a replica's leases.
- ``POST /v1/fleet/quota/lease`` — the fleet-wide tenancy plane's lease
  grant: a replica asks for its slice of each tenant's fleet-wide rate
  quota (docs/fleet.md "Fleet-wide tenancy").
- ``GET /v1/fleet/peer`` — the router-HA gossip exchange: session pins +
  the quota-lease ledger, pulled by peer router edges (APP_ROUTER_PEERS).
- Federated fleet observability (docs/observability.md "Fleet
  observability"): ``GET /v1/traces`` / ``/v1/traces/{id}`` /
  ``/v1/events`` (``?follow=1`` SSE-tails the router's own journal) /
  ``/v1/slo`` / ``/v1/tenants`` scatter-gather the live replicas and merge
  with the router's own stores, every response carrying
  ``replicas_reporting``/``replicas_failed`` partial-result accounting;
  ``GET /v1/fleet/debug/bundle`` is the one-call fleet incident snapshot.
- ``GET /healthz``; ``GET /metrics``.

Every response carries ``X-Request-Id`` (the router's own id for this
request) and — on the traced data plane — ``X-Trace-Id``, the distributed
trace the router rooted (or continued from the client's ``traceparent``)
and propagated to the chosen replica, so an error or shed answer is always
one federated ``GET /v1/traces/{id}`` away from its full span tree.

Status contract at this edge: 503 + Retry-After when no replica is
eligible, 502 when every attempt died in transport, 404 for session ids the
router has no pin for; everything else is the chosen replica's own answer,
proxied verbatim. Tenant-scoped 429s (``reason="tenant_quota"`` /
``"heavy_lane"``) are returned verbatim WITHOUT cross-replica retry —
retrying a quota shed into a fresh replica's bucket would silently multiply
the tenant's effective quota — and every cross-replica retry first debits
the requesting tenant's router-edge retry budget.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import time

from aiohttp import web

from bee_code_interpreter_tpu.analysis import classify_cost, inspect_source
from bee_code_interpreter_tpu.fleet.ring import affinity_key
from bee_code_interpreter_tpu.fleet.router import (
    FleetRouter,
    NoReplicasAvailable,
    UnknownRouterSession,
)
from bee_code_interpreter_tpu.observability import event_matches
from bee_code_interpreter_tpu.observability.tracing import (
    REQUEST_ID_HEADER,
    current_trace,
    parse_traceparent,
    span,
)
from bee_code_interpreter_tpu.resilience import BreakerOpenError
from bee_code_interpreter_tpu.utils.metrics import (
    OPENMETRICS_CONTENT_TYPE,
    PROMETHEUS_CONTENT_TYPE,
    accepts_openmetrics,
)
from bee_code_interpreter_tpu.utils.request_id import new_request_id

logger = logging.getLogger(__name__)

#: The distributed-trace correlation handle on every traced router
#: response (docs/observability.md "Fleet observability"): feed it to the
#: federated ``GET /v1/traces/{id}`` for the full router+replica span tree.
TRACE_ID_HEADER = "X-Trace-Id"


def _key_from_body(raw: bytes) -> str | None:
    """The affinity key from a request body's ``files`` snapshot map;
    malformed bodies have no key — the replica's own validation is the
    source of truth for rejecting them."""
    try:
        body = json.loads(raw)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(body, dict):
        return None
    files = body.get("files")
    return affinity_key(files if isinstance(files, dict) else None)


#: Source larger than this is never classified at the router edge — the
#: replica's own analysis gate (APP_ANALYSIS_MAX_SOURCE_BYTES) owns the
#: real verdict; here classification is only a placement hint and must
#: stay sub-ms on the router's event loop.
_CLASSIFY_MAX_SOURCE_BYTES = 262_144


def _cost_class_from_body(raw: bytes) -> str | None:
    """The submission's cost class ("accelerator"/"io"/"cpu") as a
    placement steering hint, or None when the body can't be cheaply
    classified. Best-effort by design: a None here just means least-loaded
    placement, the replica still runs its own full gate."""
    if len(raw) > _CLASSIFY_MAX_SOURCE_BYTES:
        return None
    try:
        body = json.loads(raw)
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(body, dict):
        return None
    source = body.get("source_code")
    if not isinstance(source, str) or not source:
        return None
    try:
        return classify_cost(inspect_source(source))
    except Exception:
        return None


def _truthy(request: web.Request, name: str) -> bool:
    return request.query.get(name, "").lower() in ("1", "true", "yes", "on")


def _upstream_response(response) -> web.Response:
    # passthrough_headers keeps Retry-After: the shed/drain contract's
    # backoff hint must survive the proxy hop.
    return web.Response(
        body=response.content,
        status=response.status_code,
        headers=response.passthrough_headers(),
    )


def _no_replicas(e: NoReplicasAvailable) -> web.Response:
    return web.json_response(
        {"detail": "no eligible replicas; fleet is draining or down"},
        status=503,
        headers={"Retry-After": str(max(1, math.ceil(e.retry_after_s)))},
    )


def create_router_app(router: FleetRouter) -> web.Application:
    app = web.Application(client_max_size=1 << 30)
    clock = time.monotonic

    @web.middleware
    async def trace_middleware(request: web.Request, handler):
        """The router edge's twin of the replica's request_id middleware:
        one request id per inbound request, one TRACE per routed data-plane
        request (continuing the client's ``traceparent`` when one came in),
        and the correlation headers on EVERY response — success, shed, 502,
        404, all of them."""
        rid = new_request_id()
        # Label by the *matched* route template, never the raw path (raw
        # paths are attacker-controlled — unbounded trace-name cardinality).
        match_info = request.match_info
        resource = match_info.route.resource if match_info is not None else None
        route = resource.canonical if resource is not None else "unmatched"
        # Trace the proxied data plane only (the replica edge's rule, plus
        # the pinned DELETE): the federated GET surface, /healthz and
        # /metrics must not drown the store in self-traffic.
        traced = (
            request.method in ("POST", "DELETE")
            and route.startswith("/v1/")
            and not route.startswith("/v1/fleet/")
        )
        inbound = (
            parse_traceparent(request.headers.get("traceparent"))
            if traced
            else None
        )
        trace_id = None
        try:
            if traced:
                with router.tracer.trace(
                    route,
                    trace_id=inbound[0] if inbound else None,
                    parent_span_id=inbound[1] if inbound else None,
                    request_id=rid,
                ) as trace:
                    trace_id = trace.trace_id
                    response = await handler(request)
            else:
                response = await handler(request)
        except web.HTTPException as e:
            e.headers.setdefault(REQUEST_ID_HEADER, rid)
            if trace_id is not None:
                e.headers.setdefault(TRACE_ID_HEADER, trace_id)
            raise
        if not response.prepared:
            # A committed SSE stream already carries these (set by the pump
            # before prepare; headers are spent once sent).
            response.headers[REQUEST_ID_HEADER] = rid
            if trace_id is not None:
                response.headers[TRACE_ID_HEADER] = trace_id
        return response

    app.middlewares.append(trace_middleware)

    # ------------------------------------------------------ routed proxying

    async def _proxy_routed(
        request: web.Request,
        route: str,
        path: str,
        keyed: bool,
        retry_5xx: bool,
        classify: bool = False,
    ) -> web.Response:
        raw = await request.read()
        key = _key_from_body(raw) if keyed else None
        tenant = router.resolve_tenant(request.headers)
        cost_class = _cost_class_from_body(raw) if classify else None
        headers = router.forward_headers(request.headers)
        params = dict(request.query)
        start = clock()
        try:
            response, replica, retries = await router.route_buffered(
                route,
                "POST",
                path,
                key=key,
                body=raw,
                headers=headers,
                params=params,
                retry_5xx=retry_5xx,
                tenant=tenant,
                cost_class=cost_class,
            )
        except NoReplicasAvailable as e:
            router.record_route(
                route,
                outcome="unrouteable",
                replica=None,
                key=key,
                duration_s=clock() - start,
                tenant=tenant,
            )
            return _no_replicas(e)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            router.record_route(
                route,
                outcome="unreachable",
                replica=None,
                key=key,
                duration_s=clock() - start,
                tenant=tenant,
            )
            logger.warning("All replica attempts failed for %s: %s", route, e)
            return web.json_response(
                {"detail": "all replica attempts failed"}, status=502
            )
        router.record_route(
            route,
            outcome=router.outcome_for_status(response.status_code),
            replica=replica,
            key=key,
            affinity=(
                router.affinity_result(key, replica, tenant=tenant)
                if replica is not None
                else None
            ),
            retries=retries,
            duration_s=clock() - start,
            tenant=tenant,
        )
        return _upstream_response(response)

    async def _routed(request, route, path, keyed, retry_5xx=True, classify=False):
        return await _proxy_routed(
            request, route, path, keyed, retry_5xx, classify
        )

    async def _pump_sse(
        request: web.Request,
        route: str,
        upstream,
        *,
        replica: str,
        key: str | None = None,
        affinity: str | None = None,
        session: str | None = None,
        tenant=None,
        retries: int,
        start: float,
    ) -> web.StreamResponse:
        """Copy a COMMITTED upstream SSE body to the client, accounting the
        route exactly once whatever ends the stream. Once ``prepare()`` has
        run, the response status is spent: failures here are terminal —
        never retried on another replica, never re-accounted by a caller
        (only a CancelledError escapes, already recorded)."""
        # The middleware can't stamp a prepared stream, so the correlation
        # headers ride the first (only) header flush here.
        corr: dict[str, str] = {}
        trace = current_trace()
        if trace is not None:
            corr[TRACE_ID_HEADER] = trace.trace_id
            if trace.request_id:
                corr[REQUEST_ID_HEADER] = trace.request_id
        response = web.StreamResponse(
            status=upstream.status_code,
            headers={
                **upstream.passthrough_headers("text/event-stream"),
                "Cache-Control": "no-store",
                "X-Accel-Buffering": "no",
                **corr,
            },
        )
        response.enable_chunked_encoding()
        outcome = "error"
        try:
            with span("sse_pump", replica=replica):
                await response.prepare(request)
                async for chunk in upstream.aiter_bytes():
                    await response.write(chunk)
                await response.write_eof()
                outcome = "ok"
            return response
        except asyncio.CancelledError:
            outcome = "cancelled"
            raise
        except (ConnectionResetError, ConnectionAbortedError):
            outcome = "cancelled"  # the downstream client vanished
            return response
        except Exception as e:
            # The upstream died mid-body: delivered chunks cannot be
            # un-delivered, so this is a terminal truncated stream.
            logger.warning("Stream relay for %s ended early: %s", route, e)
            return response
        finally:
            router.record_route(
                route,
                outcome=outcome,
                replica=replica,
                key=key,
                affinity=affinity,
                retries=retries,
                duration_s=clock() - start,
                session=session,
                tenant=tenant,
            )

    async def _stream_routed(
        request: web.Request, route: str, path: str, key: str | None, raw: bytes
    ) -> web.StreamResponse:
        """SSE passthrough with retry-before-first-byte: sheds and
        unavailability walk the ring like the buffered path, but once the
        upstream answered 200 the stream is committed to that replica
        (``_pump_sse``) — delivered chunks cannot be un-delivered.
        Tenant-scoped sheds are terminal here too, and every retry debits
        the tenant's router-edge retry budget."""
        tenant = router.resolve_tenant(request.headers)
        cost_class = _cost_class_from_body(raw)
        headers = router.forward_headers(request.headers)
        params = dict(request.query)
        start = clock()
        exclude: set[str] = set()
        retries = 0
        last_verdict: tuple[int, dict, bytes] | None = None
        for _ in range(router.retry_attempts):
            try:
                replica = router.place(
                    key, exclude=exclude, tenant=tenant, cost_class=cost_class
                )[0]
            except NoReplicasAvailable as e:
                if last_verdict is not None:
                    break
                router.record_route(
                    route,
                    outcome="unrouteable",
                    replica=None,
                    key=key,
                    retries=retries,
                    duration_s=clock() - start,
                    tenant=tenant,
                )
                return _no_replicas(e)
            try:
                async with router.stream_replica(
                    replica, "POST", path, body=raw, headers=headers, params=params
                ) as upstream:
                    reason = router.retry_reason(upstream.status_code)
                    if reason is not None:
                        last_verdict = (
                            upstream.status_code,
                            upstream.passthrough_headers(),
                            await upstream.aread(),
                        )
                        # Tenant-scoped sheds (tenant_quota / heavy_lane)
                        # are terminal: retrying them into another
                        # replica's bucket multiplies the tenant's
                        # effective quota. Denied retry budget ends the
                        # walk the same way — the verdict stands.
                        if (
                            reason == "shed"
                            and router.sticky_shed(last_verdict[2])
                        ) or not router.spend_retry_budget(tenant):
                            break
                        router.record_retry(reason)
                        retries += 1
                        exclude.add(replica.name)
                        continue
                    if upstream.status_code >= 400:
                        body = await upstream.aread()
                        router.record_route(
                            route,
                            outcome="client_error",
                            replica=replica.name,
                            key=key,
                            retries=retries,
                            duration_s=clock() - start,
                            tenant=tenant,
                        )
                        return web.Response(
                            body=body,
                            status=upstream.status_code,
                            headers=upstream.passthrough_headers(),
                        )
                    return await _pump_sse(
                        request,
                        route,
                        upstream,
                        replica=replica.name,
                        key=key,
                        affinity=router.affinity_result(
                            key, replica.name, tenant=tenant
                        ),
                        tenant=tenant,
                        retries=retries,
                        start=start,
                    )
            except asyncio.CancelledError:
                raise  # _pump_sse already accounted a committed stream
            except BreakerOpenError:
                # Same handling as the buffered path: an open breaker is a
                # placement miss, not a transport failure — skip silently.
                exclude.add(replica.name)
            except Exception as e:
                logger.warning(
                    "Stream attempt on %s failed before first byte: %s",
                    replica.name,
                    e,
                )
                if not router.spend_retry_budget(tenant):
                    break
                router.record_retry("unreachable")
                retries += 1
                exclude.add(replica.name)
        if last_verdict is not None:
            # Out of replicas: the last upstream verdict (a shed or 503,
            # Retry-After included) is the honest answer — not a 502.
            status, verdict_headers, body = last_verdict
            router.record_route(
                route,
                outcome=router.outcome_for_status(status),
                replica=None,
                key=key,
                retries=retries,
                duration_s=clock() - start,
                tenant=tenant,
            )
            return web.Response(
                body=body, status=status, headers=verdict_headers
            )
        router.record_route(
            route,
            outcome="unreachable",
            replica=None,
            key=key,
            retries=retries,
            duration_s=clock() - start,
            tenant=tenant,
        )
        return web.json_response(
            {"detail": "all replica attempts failed"}, status=502
        )

    async def execute(request: web.Request) -> web.StreamResponse:
        if _truthy(request, "stream"):
            raw = await request.read()
            return await _stream_routed(
                request, "/v1/execute", "/v1/execute", _key_from_body(raw), raw
            )
        return await _routed(
            request, "/v1/execute", "/v1/execute", keyed=True, classify=True
        )

    async def parse_custom_tool(request: web.Request) -> web.Response:
        return await _routed(
            request,
            "/v1/parse-custom-tool",
            "/v1/parse-custom-tool",
            keyed=False,
        )

    async def execute_custom_tool(request: web.Request) -> web.Response:
        return await _routed(
            request,
            "/v1/execute-custom-tool",
            "/v1/execute-custom-tool",
            keyed=False,
        )

    # --------------------------------------------------------- session pins

    async def session_create(request: web.Request) -> web.Response:
        raw = await request.read()
        key = _key_from_body(raw)
        tenant = router.resolve_tenant(request.headers)
        headers = router.forward_headers(request.headers)
        start = clock()
        try:
            # 5xx is NOT retried here: a create that failed after the
            # replica leased a sandbox would leak that lease if silently
            # re-run elsewhere; shed/unavailable (nothing leased) still
            # walk the ring.
            response, replica, retries = await router.route_buffered(
                "/v1/sessions",
                "POST",
                "/v1/sessions",
                key=key,
                body=raw,
                headers=headers,
                params=dict(request.query),
                retry_5xx=False,
                tenant=tenant,
            )
        except NoReplicasAvailable as e:
            router.record_route(
                "/v1/sessions",
                outcome="unrouteable",
                replica=None,
                key=key,
                duration_s=clock() - start,
                tenant=tenant,
            )
            return _no_replicas(e)
        except asyncio.CancelledError:
            raise
        except Exception:
            router.record_route(
                "/v1/sessions",
                outcome="unreachable",
                replica=None,
                key=key,
                duration_s=clock() - start,
                tenant=tenant,
            )
            return web.json_response(
                {"detail": "all replica attempts failed"}, status=502
            )
        session_id = None
        if response.status_code == 200 and replica is not None:
            session_id = response.json().get("session_id")
            if session_id:
                router.pin_session(session_id, replica)
        router.record_route(
            "/v1/sessions",
            outcome=router.outcome_for_status(response.status_code),
            replica=replica,
            key=key,
            affinity=(
                router.affinity_result(key, replica, tenant=tenant)
                if replica is not None
                else None
            ),
            retries=retries,
            duration_s=clock() - start,
            session=session_id,
            tenant=tenant,
        )
        return _upstream_response(response)

    def _public_body(response, session) -> bytes:
        """A migrated session's replica answers with ITS lease id; the
        client must keep seeing the stable public id."""
        if session.backend_id == session.public_id:
            return response.content
        try:
            body = response.json()
        except ValueError:
            return response.content
        if isinstance(body, dict) and "session_id" in body:
            body["session_id"] = session.public_id
            return json.dumps(body).encode()
        return response.content

    async def _session_op(
        request: web.Request, route: str, method: str, suffix: str
    ) -> web.StreamResponse:
        session_id = request.match_info["session_id"]
        tenant = router.resolve_tenant(request.headers)
        start = clock()
        try:
            session = router.get_session(session_id)
        except UnknownRouterSession as e:
            router.record_route(
                route,
                outcome="client_error",
                replica=None,
                session=session_id,
                duration_s=clock() - start,
                tenant=tenant,
            )
            return web.json_response({"detail": str(e)}, status=404)
        raw = await request.read()
        headers = router.forward_headers(request.headers)
        params = dict(request.query)
        streaming = suffix == "/execute" and _truthy(request, "stream")
        async with session.lock:
            replica = router.replicas[session.replica]
            path = f"/v1/sessions/{session.backend_id}{suffix}"
            try:
                if streaming:
                    # Pinned stream: no cross-replica retry possible, so
                    # drive the passthrough directly under the lock (a
                    # migration must wait out the in-flight REPL turn).
                    return await _pinned_stream(
                        request, route, session, replica, path, raw,
                        headers, params, start, tenant,
                    )
                response = await router.call_replica(
                    replica, method, path, body=raw, headers=headers, params=params
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                router.record_route(
                    route,
                    outcome="unreachable",
                    replica=session.replica,
                    session=session_id,
                    duration_s=clock() - start,
                    tenant=tenant,
                )
                logger.warning(
                    "Pinned session call to %s failed: %s", session.replica, e
                )
                return web.json_response(
                    {"detail": "leasing replica unreachable"}, status=502
                )
            retries = 0
            if response.status_code == 503 and method != "DELETE":
                # The pinned replica is draining (or its breaker is open):
                # hand the lease off NOW — checkpoint is exempt from the
                # drain gate exactly for this — and re-issue the call once
                # against the new lease. The handoff is invisible to the
                # client: same public id, state restored from the shared
                # checkpoint.
                rescued = await router.migrate_session(
                    session, exclude={session.replica}, locked=True
                )
                if rescued:
                    retries = 1
                    router.record_retry("unavailable")
                    replica = router.replicas[session.replica]
                    path = f"/v1/sessions/{session.backend_id}{suffix}"
                    try:
                        response = await router.call_replica(
                            replica,
                            method,
                            path,
                            body=raw,
                            headers=headers,
                            params=params,
                        )
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        router.record_route(
                            route,
                            outcome="unreachable",
                            replica=session.replica,
                            session=session_id,
                            retries=retries,
                            duration_s=clock() - start,
                            tenant=tenant,
                        )
                        return web.json_response(
                            {"detail": "leasing replica unreachable"},
                            status=502,
                        )
            if response.status_code == 404:
                # The backend lease is gone (expired/released there): the
                # pin is stale and must not shadow future ids.
                router.unpin_session(session_id)
            if method == "DELETE" and response.status_code < 400:
                router.unpin_session(session_id)
            router.record_route(
                route,
                outcome=router.outcome_for_status(response.status_code),
                replica=session.replica,
                session=session_id,
                retries=retries,
                duration_s=clock() - start,
                tenant=tenant,
            )
            return web.Response(
                body=_public_body(response, session),
                status=response.status_code,
                headers=response.passthrough_headers(),
            )

    async def _pinned_stream(
        request, route, session, replica, path, raw, headers, params, start,
        tenant=None,
    ) -> web.StreamResponse:
        """Pinned SSE: no cross-replica retry ever; the pump owns the
        accounting once the stream is committed. Failures OPENING the
        stream propagate to ``_session_op``'s handler (nothing prepared,
        nothing recorded yet)."""
        async with router.stream_replica(
            replica, "POST", path, body=raw, headers=headers, params=params
        ) as upstream:
            if upstream.status_code >= 400:
                body = await upstream.aread()
                if upstream.status_code == 404:
                    router.unpin_session(session.public_id)
                router.record_route(
                    route,
                    outcome=router.outcome_for_status(upstream.status_code),
                    replica=session.replica,
                    session=session.public_id,
                    duration_s=clock() - start,
                    tenant=tenant,
                )
                return web.Response(
                    body=body,
                    status=upstream.status_code,
                    headers=upstream.passthrough_headers(),
                )
            return await _pump_sse(
                request,
                route,
                upstream,
                replica=session.replica,
                session=session.public_id,
                tenant=tenant,
                retries=0,
                start=start,
            )

    async def session_execute(request: web.Request) -> web.StreamResponse:
        return await _session_op(
            request, "/v1/sessions/{id}/execute", "POST", "/execute"
        )

    async def session_checkpoint(request: web.Request) -> web.Response:
        return await _session_op(
            request, "/v1/sessions/{id}/checkpoint", "POST", "/checkpoint"
        )

    async def session_rollback(request: web.Request) -> web.Response:
        return await _session_op(
            request, "/v1/sessions/{id}/rollback", "POST", "/rollback"
        )

    async def session_delete(request: web.Request) -> web.Response:
        return await _session_op(request, "/v1/sessions/{id}", "DELETE", "")

    async def session_list(_request: web.Request) -> web.Response:
        return web.json_response(
            {
                "sessions": [s.to_dict() for s in router.sessions.values()],
                "pinned": len(router.sessions),
            }
        )

    # ------------------------------------------------------- router surface

    async def fleet_replicas(_request: web.Request) -> web.Response:
        return web.json_response(router.snapshot())

    async def quota_lease(request: web.Request) -> web.Response:
        """One lease grant in the fleet-wide quota plane: a replica posts
        ``{"replica": name, "tenants": [ids...]}`` and gets back its slice
        of each tenant's fleet-wide rate quota (docs/fleet.md)."""
        try:
            body = await request.json()
        except (ValueError, UnicodeDecodeError):
            return web.json_response(
                {"detail": "body must be a JSON object"}, status=400
            )
        if not isinstance(body, dict):
            return web.json_response(
                {"detail": "body must be a JSON object"}, status=400
            )
        replica = body.get("replica")
        tenants = body.get("tenants")
        if not isinstance(replica, str) or not replica:
            return web.json_response(
                {"detail": "replica (non-empty string) is required"},
                status=400,
            )
        if not isinstance(tenants, list) or not all(
            isinstance(t, str) for t in tenants
        ):
            return web.json_response(
                {"detail": "tenants must be a list of tenant ids"},
                status=400,
            )
        return web.json_response(router.grant_quota_leases(replica, tenants))

    async def fleet_peer(_request: web.Request) -> web.Response:
        """The router-HA gossip exchange: this edge's session pins and
        quota-lease ledger, pulled by peers every refresh tick."""
        return web.json_response(router.peer_export())

    async def drain_replica(request: web.Request) -> web.Response:
        name = request.match_info["name"]
        try:
            tally = await router.drain_replica(name)
        except KeyError:
            return web.json_response(
                {"detail": f"unknown replica {name!r}"}, status=404
            )
        return web.json_response({"replica": name, **tally})

    # --------------------------------------- federated fleet observability

    async def _tail_events(
        request: web.Request, filters: dict, limit: int | None
    ) -> web.StreamResponse:
        """``?follow=1``: SSE-tail the ROUTER'S OWN journal (routing +
        migration decisions, live). Federating a live tail would need N
        upstream SSE connections per client; the merged historical view is
        the plain GET — the follow mode is the router's decision stream."""
        response = web.StreamResponse(
            status=200,
            headers={
                "Content-Type": "text/event-stream",
                "Cache-Control": "no-store",
                "X-Accel-Buffering": "no",
            },
        )
        response.enable_chunked_encoding()
        await response.prepare(request)

        async def send(event: dict) -> None:
            payload = json.dumps({**event, "source": "router"})
            await response.write(
                f"event: wide_event\ndata: {payload}\n\n".encode("utf-8")
            )

        # Subscribe BEFORE snapshotting the backlog so nothing recorded in
        # between is lost (the replica edge's exact ordering).
        queue = router.recorder.subscribe()
        try:
            for event in reversed(
                router.recorder.events(limit=limit, **filters)
            ):
                await send(event)
            while True:
                try:
                    event = await asyncio.wait_for(queue.get(), timeout=15.0)
                except asyncio.TimeoutError:
                    await response.write(b": keep-alive\n\n")
                    continue
                if event_matches(event, **filters):
                    await send(event)
        except (
            asyncio.CancelledError,
            ConnectionResetError,
            ConnectionAbortedError,
        ):
            return response
        finally:
            router.recorder.unsubscribe(queue)

    async def events(request: web.Request) -> web.StreamResponse:
        query = request.query
        try:
            limit = int(query["limit"]) if "limit" in query else None
            min_duration_ms = (
                float(query["min_duration_ms"])
                if "min_duration_ms" in query
                else None
            )
            since = float(query["since"]) if "since" in query else None
        except ValueError:
            return web.json_response(
                {"detail": "limit, min_duration_ms and since must be numeric"},
                status=400,
            )
        if limit is not None and limit < 0:
            return web.json_response(
                {"detail": "limit must be >= 0"}, status=400
            )
        filters = dict(
            kind=query.get("kind"),
            outcome=query.get("outcome"),
            session=query.get("session"),
            tenant=query.get("tenant"),
            min_duration_ms=min_duration_ms,
            since=since,
        )
        if _truthy(request, "follow"):
            return await _tail_events(request, filters, limit)
        return web.json_response(
            await router.federation.events(limit=limit, **filters)
        )

    async def fleet_slo(request: web.Request) -> web.Response:
        return web.json_response(
            await router.federation.slo(tenant=request.query.get("tenant"))
        )

    async def fleet_traces(request: web.Request) -> web.Response:
        query = request.query
        try:
            limit = int(query["limit"]) if "limit" in query else None
            min_duration_ms = (
                float(query["min_duration_ms"])
                if "min_duration_ms" in query
                else None
            )
        except ValueError:
            return web.json_response(
                {"detail": "limit and min_duration_ms must be numeric"},
                status=400,
            )
        if limit is not None and limit < 0:
            return web.json_response(
                {"detail": "limit must be >= 0"}, status=400
            )
        return web.json_response(
            await router.federation.traces(
                limit=limit, min_duration_ms=min_duration_ms
            )
        )

    async def fleet_trace(request: web.Request) -> web.Response:
        body = await router.federation.trace(request.match_info["trace_id"])
        if not body["sources"]:
            # Same shape as the replica edge's miss — but only when NOBODY
            # that answered knows the id; a partial fleet never 404s a
            # trace a surviving source still holds.
            return web.json_response(
                {"detail": "unknown or evicted trace", **body}, status=404
            )
        return web.json_response(body)

    async def fleet_tenants(_request: web.Request) -> web.Response:
        return web.json_response(await router.federation.tenants())

    async def fleet_autoscale(_request: web.Request) -> web.Response:
        return web.json_response(await router.federation.autoscale())

    async def fleet_debug_bundle(_request: web.Request) -> web.Response:
        return web.json_response(await router.federation.debug_bundle())

    async def healthz(request: web.Request) -> web.Response:
        """The router's own liveness + the fleet reachability verdict
        ``health_check.py --router`` keys off: a router with zero healthy
        replicas is alive but can't route — status "degraded"."""
        now = clock()
        by_state: dict[str, list[str]] = {"healthy": [], "draining": [], "dead": []}
        for replica in router.replicas.values():
            by_state[replica.state(now, router.dead_after_s)].append(
                replica.name
            )
        status = "ok" if by_state["healthy"] else "degraded"
        body = {"status": status, "replicas": {k: sorted(v) for k, v in by_state.items()}}
        if request.query.get("verbose", "").lower() in ("1", "true", "yes", "on"):
            body["sessions_pinned"] = len(router.sessions)
            body["totals"] = dict(router.totals)
        return web.json_response(body)

    async def metrics_endpoint(request: web.Request) -> web.Response:
        openmetrics = accepts_openmetrics(request.headers.get("Accept", ""))
        return web.Response(
            body=router.metrics.expose(openmetrics=openmetrics).encode("utf-8"),
            headers={
                "Content-Type": (
                    OPENMETRICS_CONTENT_TYPE
                    if openmetrics
                    else PROMETHEUS_CONTENT_TYPE
                )
            },
        )

    app.router.add_post("/v1/execute", execute)
    app.router.add_post("/v1/parse-custom-tool", parse_custom_tool)
    app.router.add_post("/v1/execute-custom-tool", execute_custom_tool)
    app.router.add_post("/v1/sessions", session_create)
    app.router.add_get("/v1/sessions", session_list)
    app.router.add_post("/v1/sessions/{session_id}/execute", session_execute)
    app.router.add_post("/v1/sessions/{session_id}/checkpoint", session_checkpoint)
    app.router.add_post("/v1/sessions/{session_id}/rollback", session_rollback)
    app.router.add_delete("/v1/sessions/{session_id}", session_delete)
    app.router.add_get("/v1/fleet/replicas", fleet_replicas)
    app.router.add_post("/v1/fleet/replicas/{name}/drain", drain_replica)
    app.router.add_post("/v1/fleet/quota/lease", quota_lease)
    app.router.add_get("/v1/fleet/peer", fleet_peer)
    app.router.add_get("/v1/events", events)
    app.router.add_get("/v1/slo", fleet_slo)
    app.router.add_get("/v1/traces", fleet_traces)
    app.router.add_get("/v1/traces/{trace_id}", fleet_trace)
    app.router.add_get("/v1/tenants", fleet_tenants)
    app.router.add_get("/v1/autoscale", fleet_autoscale)
    app.router.add_get("/v1/fleet/debug/bundle", fleet_debug_bundle)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics_endpoint)
    return app
