"""Consistent-hash ring + execution affinity keys (docs/fleet.md).

Placement wants two properties at once: *stability* (adding or losing one
replica must not reshuffle every key — a reshuffle throws away every warm
snapshot chain at once) and *affinity* (the same key must keep landing on
the same replica, because that replica's content-addressed store and XLA
compile cache are warm for it). A consistent-hash ring with virtual nodes
gives both: each replica owns ``vnodes`` pseudo-random arcs of the hash
space, a key belongs to the first arc clockwise of its hash, and losing a
replica only re-homes the arcs it owned.

The affinity key is the execution's **files hash chain**: the sha256 over
the sorted ``{path: object_id}`` snapshot map. Repeat executions over the
same workspace (an agent iterating on one checkpoint chain) hash
identically and land where their snapshots are warm; executions with no
files have no affinity and are placed by load instead.
"""

from __future__ import annotations

import hashlib
from bisect import bisect_right


def affinity_key(files: dict | None) -> str | None:
    """The placement key for one execution: sha256 over the sorted
    ``{path: object_id}`` map, or None when there is nothing to be warm
    for (docs/fleet.md "Placement rules")."""
    if not files:
        return None
    hasher = hashlib.sha256()
    for path in sorted(files):
        hasher.update(str(path).encode())
        hasher.update(b"\0")
        hasher.update(str(files[path]).encode())
        hasher.update(b"\0")
    return hasher.hexdigest()


def _point(token: str) -> int:
    return int.from_bytes(hashlib.sha256(token.encode()).digest()[:8], "big")


class HashRing:
    """Virtual-node consistent-hash ring over replica names. Membership is
    the *registered* fleet, not the healthy one: health filters placement
    (``FleetRouter.place``), never ring ownership, so a replica bouncing in
    and out of health keeps its arcs — and its warm keys — stable."""

    _SPACE = 1 << 64

    def __init__(self, vnodes: int = 64) -> None:
        self._vnodes = max(1, vnodes)
        self._points: list[tuple[int, str]] = []  # sorted (point, name)

    def add(self, name: str) -> None:
        for i in range(self._vnodes):
            self._points.append((_point(f"{name}#{i}"), name))
        self._points.sort()

    def remove(self, name: str) -> None:
        self._points = [(p, n) for p, n in self._points if n != name]

    def __contains__(self, name: str) -> bool:
        return any(n == name for _, n in self._points)

    def owner(self, key: str) -> str | None:
        """The replica whose arc contains ``key`` — the warm home."""
        order = self.preference(key, limit=1)
        return order[0] if order else None

    def preference(self, key: str, limit: int | None = None) -> list[str]:
        """Distinct replicas in ring order clockwise from ``key``'s hash:
        the owner first, then the natural spill-over sequence (the same
        order a key would re-home through as replicas drop)."""
        if not self._points:
            return []
        idx = bisect_right(self._points, (_point(key), "￿"))
        seen: dict[str, None] = {}
        for offset in range(len(self._points)):
            name = self._points[(idx + offset) % len(self._points)][1]
            if name not in seen:
                seen[name] = None
                if limit is not None and len(seen) >= limit:
                    break
        return list(seen)

    def shares(self) -> dict[str, float]:
        """Fraction of the hash space each replica owns (vnodes make these
        approach 1/N); the ``ring_share`` column in fleet-router-top."""
        if not self._points:
            return {}
        out: dict[str, float] = {}
        for i, (point, name) in enumerate(self._points):
            prev = self._points[i - 1][0]
            arc = (point - prev) % self._SPACE
            if arc == 0 and len(self._points) == 1:
                arc = self._SPACE
            out[name] = out.get(name, 0.0) + arc / self._SPACE
        return out
