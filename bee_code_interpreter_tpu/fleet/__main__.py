"""Router entry point: ``python -m bee_code_interpreter_tpu.fleet``.

Reads the same ``APP_*`` env surface as the service (``APP_ROUTER_LISTEN_ADDR``,
``APP_ROUTER_REPLICAS``, and the rest of the ``APP_ROUTER_*`` family —
docs/fleet.md). SIGTERM stops the refresh loop and the listener; the router
holds no durable state beyond session pins and the quota-lease ledger, and
with ``APP_ROUTER_PEERS`` set (docs/fleet.md "Fleet-wide tenancy") N router
edges gossip both every refresh tick — a killed or restarted edge re-learns
the fleet from its first refresh and its pins from the surviving peers, so
HA is a config line, not an external store.

    APP_ROUTER_REPLICAS="r0=http://replica-0:50081,r1=http://replica-1:50081" \\
    APP_ROUTER_PEERS="http://router-b:50080" \\
        python -m bee_code_interpreter_tpu.fleet
"""

from __future__ import annotations

import asyncio
import logging
import signal

from aiohttp import web

from bee_code_interpreter_tpu.config import Config
from bee_code_interpreter_tpu.fleet.app import create_router_app
from bee_code_interpreter_tpu.fleet.router import FleetRouter

# Explicit name: under `python -m` this module runs as "__main__", which
# would fall to the root logger's WARNING level and drop the startup lines.
logger = logging.getLogger("bee_code_interpreter_tpu.fleet.main")


async def main() -> None:
    import logging.config

    from bee_code_interpreter_tpu.utils.request_id import (
        install_request_id_filter,
    )

    config = Config.from_env()
    logging.config.dictConfig(config.resolved_logging_config())
    # The shared log format expects %(request_id)s on every record; the
    # filter supplies it (or "-") exactly as the service's own entry point.
    install_request_id_filter()
    if not (config.router_replicas or "").strip():
        raise SystemExit(
            "APP_ROUTER_REPLICAS is required (comma-separated replica base "
            "URLs, e.g. http://replica-0:50081,http://replica-1:50081)"
        )
    router = FleetRouter.from_config(config)
    router.start()

    # Telemetry export: the router edge pushes its OWN traces (the routed
    # data plane's span trees) and wide events (routing/migration journal)
    # to the same APP_OTLP_ENDPOINT collector the replicas use — the
    # distributed trace arrives from both ends and stitches by trace_id.
    exporter = None
    if config.otlp_endpoint:
        from bee_code_interpreter_tpu.observability import TelemetryExporter
        from bee_code_interpreter_tpu.resilience import RetryPolicy

        exporter = TelemetryExporter(
            config.otlp_endpoint,
            router.metrics,
            flush_interval_s=config.otlp_flush_interval_s,
            queue_max=config.otlp_queue_max,
            batch_max=config.otlp_batch_max,
            retry=RetryPolicy(
                attempts=config.otlp_retry_attempts,
                wait_min_s=config.otlp_retry_wait_min_s,
                wait_max_s=config.otlp_retry_wait_max_s,
            ),
            timeout_s=config.otlp_timeout_s,
        )
        router.tracer.add_sink(exporter.enqueue_trace)
        router.recorder.add_sink(exporter.enqueue_log)
        exporter.start()

    host, _, port = config.router_listen_addr.rpartition(":")
    runner = web.AppRunner(create_router_app(router), shutdown_timeout=3.0)
    await runner.setup()
    await web.TCPSite(runner, host or "0.0.0.0", int(port)).start()
    logger.info(
        "Fleet router listening on %s over %d replica(s)",
        config.router_listen_addr,
        len(router.replicas),
    )

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    await stop.wait()

    logger.info("Shutting down fleet router")
    await runner.cleanup()
    if exporter is not None:
        await exporter.stop()
    await router.stop()


if __name__ == "__main__":
    asyncio.run(main())
