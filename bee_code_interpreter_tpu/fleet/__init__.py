"""Fleet tier (docs/fleet.md): the replica-aware router edge that turns N
single-replica stacks into one logical service — consistent-hash placement
over shared snapshot storage, per-replica circuit breakers, cross-replica
retry, mandatory session affinity, and lease handoff on drain."""

from bee_code_interpreter_tpu.fleet.app import create_router_app
from bee_code_interpreter_tpu.fleet.ring import HashRing, affinity_key
from bee_code_interpreter_tpu.fleet.router import (
    FleetRouter,
    NoReplicasAvailable,
    PeerRouter,
    Replica,
    RouterSession,
    UnknownRouterSession,
)
from bee_code_interpreter_tpu.fleet.tenancy_plane import (
    QuotaLedger,
    RetryBudget,
    rendezvous_rank,
    subset_size,
)

__all__ = [
    "FleetRouter",
    "HashRing",
    "NoReplicasAvailable",
    "PeerRouter",
    "QuotaLedger",
    "Replica",
    "RetryBudget",
    "RouterSession",
    "UnknownRouterSession",
    "affinity_key",
    "create_router_app",
    "rendezvous_rank",
    "subset_size",
]
