"""FleetRouter: the replica-aware edge that turns N single-replica stacks
into one logical service (docs/fleet.md).

Every signal the router needs already exists on each replica — ``/v1/fleet``
(pool utilization, drain state, lease table), ``/v1/slo`` (burn-rate
alerts) — so the router is a thin, *stateless-except-for-pins* tier:

- **Placement** (:meth:`FleetRouter.place`): consistent-hash affinity on the
  execution's files hash chain (``fleet.ring``), weighted by the live
  utilization/burn pulled on a background refresh loop. The ring owner is a
  preference, overload is a veto: an owner at/over the spill threshold (or
  with its SLO page alert firing) is passed over while a healthier replica
  exists.
- **Resilience**: a per-replica :class:`CircuitBreaker` (reusing
  ``resilience/``) around the proxied data plane, and cross-replica retry
  of sheds (429), unavailability (503), 5xx, and transport errors — safe
  for the stateless routes for exactly the reason in-replica replay is
  (single-use sandboxes over content-addressed snapshots, at-least-once).
- **Mandatory session affinity**: ``/v1/sessions/{id}/*`` pins to the
  replica holding the lease (a lease IS one sandbox on one replica); pinned
  calls are never retried cross-replica.
- **Lease handoff on drain** (:meth:`drain_replica`): instead of a draining
  replica killing its leases, the router migrates each live one —
  checkpoint through the SHARED snapshot storage → re-lease on another
  replica (restoring the checkpoint) → release the old lease — and keeps
  the client-visible session id stable by re-pointing its pin at the new
  backend lease. The refresh loop auto-evacuates replicas it sees enter
  drain (give them ``APP_SESSION_DRAIN_GRACE_S`` so their own sweep doesn't
  win the race).
- **Fleet-wide tenancy** (docs/fleet.md "Fleet-wide tenancy"): with a
  tenant table wired in, each declared tenant is rendezvous-hashed onto a
  bounded replica subset (k ∝ weight) so per-replica quotas compose into a
  fleet-wide bound; ``cost_class="accelerator"`` submissions steer toward
  replicas whose cost-class mix shows accelerator capability; the router
  holds the quota-lease ledger (``POST /v1/fleet/quota/lease``); and
  per-tenant ``tenant_quota``/``heavy_lane`` sheds are returned VERBATIM —
  never retried into a fresh replica's bucket — with cross-replica retries
  debiting the tenant's router-side retry budget.
- **Router HA** (``APP_ROUTER_PEERS``): N router edges gossip session pins
  and the quota-lease ledger over ``GET /v1/fleet/peer`` each refresh tick,
  with consecutive-failure peer detection — killing one edge mid-flood
  loses zero pins, and lease reconciliation bounds quota double-issue to
  one lease TTL of membership skew.

Accounting is exactly-once by construction: every routed request lands in
the decision totals (``GET /v1/fleet/replicas``), ONE ``kind="routing"``
wide event, and ``bci_router_requests_total`` from a single chokepoint
(:meth:`record_route`); migrations likewise via ``kind="lease_migrate"`` +
``bci_router_lease_migrations_total``.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from contextlib import asynccontextmanager
from dataclasses import dataclass, field
from typing import Callable

from bee_code_interpreter_tpu.fleet.ring import HashRing, affinity_key
from bee_code_interpreter_tpu.fleet.tenancy_plane import (
    QuotaLedger,
    RetryBudget,
    rendezvous_rank,
    subset_size,
)
from bee_code_interpreter_tpu.observability import FlightRecorder
from bee_code_interpreter_tpu.observability.federation import FederationPlane
from bee_code_interpreter_tpu.observability.slo import SloEngine
from bee_code_interpreter_tpu.observability.tracing import (
    TraceStore,
    Tracer,
    current_trace,
    outbound_headers,
    span,
)
from bee_code_interpreter_tpu.resilience import (
    BreakerOpenError,
    BreakerState,
    CircuitBreaker,
)
from bee_code_interpreter_tpu.tenancy import (
    DEFAULT_TENANT_ID,
    TENANT_HEADER,
    bearer_token,
)

logger = logging.getLogger(__name__)

# Headers worth forwarding to a replica: content negotiation, the trace
# context (a replica's trace continues the router-side caller's), and the
# tenant identity (header or API key) — the replica-side admission gate
# must see WHO is asking through the proxy hop.
_FORWARD_HEADERS = (
    "content-type",
    "traceparent",
    "x-request-id",
    "accept",
    "x-tenant-id",
    "authorization",
)

# Shed reasons that are per-tenant verdicts (docs/tenancy.md): retrying
# them on another replica would charge a FRESH token bucket there,
# silently multiplying the tenant's effective quota. Returned verbatim.
_TENANT_SCOPED_SHEDS = frozenset({"tenant_quota", "heavy_lane"})

# A peer router is DOWN after this many consecutive failed gossip syncs.
_PEER_DOWN_AFTER = 2


class NoReplicasAvailable(Exception):
    """No eligible replica for this placement (all dead/draining/open)."""

    def __init__(self, retry_after_s: float) -> None:
        super().__init__("no eligible replicas")
        self.retry_after_s = retry_after_s


class UnknownRouterSession(Exception):
    """Session id the router has no pin for (HTTP 404 at the router edge)."""


@dataclass
class Replica:
    """One registered replica and the router's live view of it."""

    name: str
    base_url: str
    breaker: CircuitBreaker
    utilization: float = 0.0
    live_pods: int = 0
    ready_pods: int = 0
    leases: int = 0
    # Tenant mix off /v1/fleet (docs/tenancy.md): per-tenant request totals
    # this replica has absorbed — the signal tenant-aware placement reads.
    tenants: dict = field(default_factory=dict)
    # Cost-class mix off /v1/fleet (docs/analysis.md "Cost classes"): a
    # replica whose mix shows absorbed `accelerator` work is known
    # TPU-capable, and accelerator submissions steer toward it.
    cost_classes: dict = field(default_factory=dict)
    # Accelerator summary off /v1/fleet (docs/observability.md "Accelerator
    # observability"): compile/retrace totals, mesh shape, and HBM headroom
    # — the signal for steering load off retracing or memory-tight replicas.
    accelerator: dict = field(default_factory=dict)
    draining: bool = False  # the replica says so (/v1/fleet "draining")
    cordoned: bool = False  # the ROUTER says so (drain_replica)
    slo_fast_burn: bool = False
    last_refresh_mono: float | None = None
    refresh_error: str | None = None
    routed_total: int = 0

    def state(self, now: float, dead_after_s: float) -> str:
        if (
            self.last_refresh_mono is None
            or now - self.last_refresh_mono > dead_after_s
        ):
            return "dead"
        if self.draining or self.cordoned:
            return "draining"
        return "healthy"

    def eligible(self, now: float, dead_after_s: float) -> bool:
        return (
            self.state(now, dead_after_s) == "healthy"
            and self.breaker.state is not BreakerState.OPEN
        )

    def to_dict(self, now: float, dead_after_s: float, ring_share: float) -> dict:
        return {
            "name": self.name,
            "base_url": self.base_url,
            "state": self.state(now, dead_after_s),
            "cordoned": self.cordoned,
            "utilization": self.utilization,
            "live_pods": self.live_pods,
            "ready_pods": self.ready_pods,
            "leases": self.leases,
            "tenants": dict(self.tenants),
            "cost_classes": dict(self.cost_classes),
            "accelerator": dict(self.accelerator),
            "slo_fast_burn": self.slo_fast_burn,
            "breaker": self.breaker.state.name.lower(),
            "ring_share": ring_share,
            "routed_total": self.routed_total,
            "last_refresh_age_s": (
                now - self.last_refresh_mono
                if self.last_refresh_mono is not None
                else None
            ),
            "refresh_error": self.refresh_error,
        }


@dataclass
class PeerRouter:
    """One fellow router edge (``APP_ROUTER_PEERS``) and this edge's view
    of it: gossip reachability plus what the last syncs adopted."""

    name: str
    base_url: str
    failures: int = 0
    last_sync_mono: float | None = None
    last_error: str | None = None
    pins_adopted: int = 0
    leases_merged: int = 0

    @property
    def up(self) -> bool:
        return self.failures < _PEER_DOWN_AFTER

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "base_url": self.base_url,
            "up": self.up,
            "failures": self.failures,
            "pins_adopted": self.pins_adopted,
            "leases_merged": self.leases_merged,
            "last_error": self.last_error,
        }


@dataclass
class RouterSession:
    """A client-visible session id pinned to the replica leasing it. After
    a migration the public id stays while ``backend_id`` (the new lease on
    the new replica) changes — handoff is invisible to the client."""

    public_id: str
    replica: str
    backend_id: str
    created_unix: float = field(default_factory=time.time)
    migrations: int = 0
    lock: asyncio.Lock = field(default_factory=asyncio.Lock)

    def to_dict(self) -> dict:
        return {
            "session_id": self.public_id,
            "replica": self.replica,
            "backend_id": self.backend_id,
            "created_unix": self.created_unix,
            "migrations": self.migrations,
        }


# Response headers a proxied answer must carry back to the client:
# content negotiation plus the shed/drain contract's backoff hint
# (docs/resilience.md promises Retry-After on 429/503 — the router must
# not strip it).
_PASSTHROUGH_RESPONSE_HEADERS = ("Content-Type", "Retry-After")


class ProxiedResponse:
    """A fully buffered upstream answer: status + passthrough headers +
    body, with the connection already back in the pool."""

    __slots__ = ("status_code", "headers", "content")

    def __init__(self, status: int, headers, content: bytes) -> None:
        self.status_code = status
        self.headers = {
            name.lower(): headers[name]
            for name in _PASSTHROUGH_RESPONSE_HEADERS
            if headers.get(name)
        }
        self.content = content

    def passthrough_headers(
        self, default_content_type: str = "application/json"
    ) -> dict[str, str]:
        out = {"Content-Type": default_content_type}
        for name in _PASSTHROUGH_RESPONSE_HEADERS:
            value = self.headers.get(name.lower())
            if value:
                out[name] = value
        return out

    def json(self):
        return json.loads(self.content)


class ProxiedStream:
    """A live upstream stream (``stream_replica``): status/headers known,
    body consumed chunk-by-chunk by the passthrough handler."""

    __slots__ = ("_response",)

    def __init__(self, response) -> None:
        self._response = response

    @property
    def status_code(self) -> int:
        return self._response.status

    @property
    def headers(self):
        return self._response.headers  # CIMultiDict: .get() is case-free

    def passthrough_headers(
        self, default_content_type: str = "application/json"
    ) -> dict[str, str]:
        out = {"Content-Type": default_content_type}
        for name in _PASSTHROUGH_RESPONSE_HEADERS:
            value = self._response.headers.get(name)
            if value:
                out[name] = value
        return out

    async def aiter_bytes(self):
        async for chunk in self._response.content.iter_chunked(1 << 16):
            yield chunk

    async def aread(self) -> bytes:
        return await self._response.read()


class FleetRouter:
    """Owns the replica table, the hash ring, the session pins, and the
    refresh loop. The aiohttp handlers live in ``fleet.app``; everything
    they must agree on (placement, accounting, migration) lives here."""

    def __init__(
        self,
        replicas: list[tuple[str, str]],
        *,
        metrics=None,
        vnodes: int = 64,
        refresh_interval_s: float = 2.0,
        utilization_spill: float = 0.9,
        retry_attempts: int = 3,
        http_timeout_s: float = 120.0,
        dead_after_s: float = 10.0,
        events_max: int = 1024,
        http_client=None,
        clock: Callable[[], float] = time.monotonic,
        tenancy=None,  # tenancy.TenantRegistry (fleet-wide tenancy plane)
        peers: list[tuple[str, str]] | None = None,
        quota_ttl_s: float = 3.0,
        router_id: str = "router",
        slo_objectives=None,  # list[observability.slo.Objective]
        trace_max_traces: int = 256,
        trace_slowest_keep: int = 32,
        federation_timeout_s: float = 2.0,
    ) -> None:
        from bee_code_interpreter_tpu.utils.metrics import Registry

        self.metrics = metrics or Registry()
        self._clock = clock
        self.router_id = router_id
        self._tenancy = tenancy
        # The router's half of the quota-lease protocol (docs/fleet.md
        # "Fleet-wide tenancy"). Constructed unconditionally: without a
        # tenant table every grant answers empty and replicas stay on
        # their local fallback split.
        self.ledger = QuotaLedger(tenancy, ttl_s=quota_ttl_s, clock=clock)
        # Router-edge retry budgets, one bucket per rate-quota'd tenant.
        self._retry_budgets: dict[str, RetryBudget] = {}
        self._refresh_interval_s = refresh_interval_s
        self._utilization_spill = utilization_spill
        self.retry_attempts = max(1, retry_attempts)
        self._dead_after_s = dead_after_s
        # aiohttp client, created lazily inside the loop: per-request
        # overhead measured ~0.2 ms vs httpx's ~1.4 ms on a 1-core box —
        # the difference is most of the < 2 ms routing-tax budget
        # (bench.py `router` phase).
        self._http_timeout_s = http_timeout_s
        self._client = http_client
        self.ring = HashRing(vnodes=vnodes)
        self.replicas: dict[str, Replica] = {}
        for name, base_url in replicas:
            self.add_replica(name, base_url)
        self.peers: dict[str, PeerRouter] = {}
        for name, base_url in peers or []:
            self.add_peer(name, base_url)
        self.sessions: dict[str, RouterSession] = {}
        self._rr = 0  # keyless-placement tie-break rotation
        self._task: asyncio.Task | None = None
        self._migrating: set[str] = set()
        self._evacuations: set[asyncio.Task] = set()  # anchored bg handoffs
        # The router's own wide-event journal: kind="routing" per routed
        # request, kind="lease_migrate" per handoff (docs/fleet.md).
        self.recorder = FlightRecorder(
            max_events=events_max, metrics=self.metrics
        )
        # The router is a first-class trace participant (docs/
        # observability.md "Fleet observability"): one trace per routed
        # request — continued from the client's traceparent, continued BY
        # the replica edge downstream — with stage spans for placement,
        # breaker gate, retry attempts, and the proxied call.
        self.trace_store = TraceStore(
            max_traces=trace_max_traces, slowest_keep=trace_slowest_keep
        )
        self.tracer = Tracer(store=self.trace_store, metrics=self.metrics)
        # User-perceived SLO: what the CLIENT saw after retries/failover —
        # the number no per-replica engine can measure (a request that
        # failed on two replicas and succeeded on the third is ONE good
        # request here and three mixed samples fleet-wide).
        self.slo = SloEngine(
            slo_objectives or [], metrics=self.metrics, clock=clock
        )
        # Fleet-scoped scatter-gather queries (federated /v1/traces,
        # /v1/slo, /v1/events, /v1/tenants, /v1/fleet/debug/bundle).
        self.federation = FederationPlane(
            self, timeout_s=federation_timeout_s, metrics=self.metrics
        )
        self.totals: dict[str, int] = {
            "routed": 0,
            "retries": 0,
            "migrations_ok": 0,
            "migrations_failed": 0,
        }
        self.affinity_totals: dict[str, int] = {
            "warm": 0,
            "spill": 0,
            "keyless": 0,
            # Tenant-aware placements (no affinity key, declared tenant):
            # the request landed inside its rendezvous subset.
            "tenant": 0,
        }
        self._requests_total = self.metrics.counter(
            "bci_router_requests_total",
            "Requests routed by the fleet router, by route and outcome",
        )
        self._request_seconds = self.metrics.histogram(
            "bci_router_request_seconds",
            "Router edge latency per proxied request, by route",
        )
        self._retries_total = self.metrics.counter(
            "bci_router_retries_total",
            "Cross-replica retries, by reason (shed/unavailable/"
            "server_error/unreachable)",
        )
        self._affinity_total = self.metrics.counter(
            "bci_router_affinity_total",
            "Keyed placements by affinity result (warm=ring owner, spill="
            "re-homed) plus keyless load-based placements",
        )
        self._migrations_total = self.metrics.counter(
            "bci_router_lease_migrations_total",
            "Lease handoffs attempted during replica drain, by outcome",
        )
        for state in ("healthy", "draining", "dead"):
            self.metrics.gauge(
                "bci_router_replicas",
                "Registered replicas by observed state",
                (lambda s: lambda: self._count_state(s))(state),
                state=state,
            )
        self.metrics.gauge(
            "bci_router_pinned_sessions",
            "Sessions the router currently pins to a replica",
            lambda: len(self.sessions),
        )
        # Fleet-wide tenancy surface (docs/observability.md): the lease
        # ledger and the peer-gossip health, registered unconditionally so
        # the families exist from first scrape.
        self._quota_leases_total = self.metrics.counter(
            "bci_router_quota_leases_total",
            "Quota lease grants served by this router edge, by outcome "
            "(granted/empty)",
        )
        self.metrics.gauge(
            "bci_router_quota_active_leases",
            "Non-expired (tenant, replica) quota leases in this router's "
            "ledger",
            lambda: self.ledger.active_count(),
        )
        self._peer_sync_total = self.metrics.counter(
            "bci_router_peer_sync_total",
            "Peer-router gossip syncs, by peer and outcome (ok/error)",
        )
        self._retry_budget_denied_total = self.metrics.counter(
            "bci_router_retry_budget_denied_total",
            "Cross-replica retries suppressed by a tenant's exhausted "
            "router-side retry budget",
        )

    @staticmethod
    def _parse_endpoints(spec: str | None, prefix: str) -> list[tuple[str, str]]:
        """Comma-separated ``name=url`` (bare URLs auto-named
        ``{prefix}0..N``) — the shared APP_ROUTER_REPLICAS /
        APP_ROUTER_PEERS spelling."""
        out: list[tuple[str, str]] = []
        entries = filter(None, (s.strip() for s in (spec or "").split(",")))
        for i, entry in enumerate(entries):
            if "=" in entry.split("://", 1)[0]:
                name, _, url = entry.partition("=")
                out.append((name.strip(), url.strip().rstrip("/")))
            else:
                out.append((f"{prefix}{i}", entry.rstrip("/")))
        return out

    @classmethod
    def from_config(cls, config, **overrides) -> "FleetRouter":
        """Build from ``APP_ROUTER_*`` (docs/fleet.md): replicas come from
        the comma-separated ``APP_ROUTER_REPLICAS`` list of base URLs,
        optionally ``name=url`` named (bare URLs are auto-named r0..rN);
        fellow router edges from ``APP_ROUTER_PEERS`` (auto-named
        p0..pN); the tenant table from ``APP_TENANTS`` — declared tenants
        get rendezvous placement, quota leases, and router-side retry
        budgets."""
        from bee_code_interpreter_tpu.observability.slo import (
            parse_objectives,
        )
        from bee_code_interpreter_tpu.tenancy import (
            TenantRegistry,
            parse_tenants,
        )

        kwargs = dict(
            vnodes=config.router_vnodes,
            refresh_interval_s=config.router_refresh_interval_s,
            utilization_spill=config.router_utilization_spill,
            retry_attempts=config.router_retry_attempts,
            http_timeout_s=config.router_http_timeout_s,
            dead_after_s=config.router_dead_after_s,
            events_max=config.router_events_max,
            peers=cls._parse_endpoints(config.router_peers, "p"),
            tenancy=TenantRegistry(parse_tenants(config.tenants)),
            quota_ttl_s=config.router_quota_ttl_s,
            router_id=config.router_listen_addr,
            # Same APP_SLO_* declarations as the replicas, but measured at
            # the edge the client actually talks to.
            slo_objectives=parse_objectives(
                config.slo_availability, config.slo_latency_ms
            ),
            trace_max_traces=config.trace_max_traces,
            trace_slowest_keep=config.trace_slowest_keep,
            federation_timeout_s=config.router_federation_timeout_s,
        )
        kwargs.update(overrides)
        return cls(cls._parse_endpoints(config.router_replicas, "r"), **kwargs)

    # ---------------------------------------------------------------- fleet

    @property
    def dead_after_s(self) -> float:
        return self._dead_after_s

    def _count_state(self, state: str) -> int:
        now = self._clock()
        return sum(
            1
            for r in self.replicas.values()
            if r.state(now, self._dead_after_s) == state
        )

    def add_replica(self, name: str, base_url: str) -> Replica:
        if name in self.replicas:
            raise ValueError(f"replica {name!r} already registered")
        replica = Replica(
            name=name,
            base_url=base_url.rstrip("/"),
            # A replica-sized breaker: the router must stop hammering a
            # melting replica quickly, and probe it again on its own.
            breaker=CircuitBreaker(
                f"router-{name}",
                window=8,
                failure_rate_threshold=0.5,
                min_calls=4,
                cooldown_s=max(self._refresh_interval_s * 2, 5.0),
                clock=self._clock,
            ),
        )
        self.replicas[name] = replica
        self.ring.add(name)
        return replica

    def add_peer(self, name: str, base_url: str) -> PeerRouter:
        if name in self.peers:
            raise ValueError(f"peer router {name!r} already registered")
        peer = PeerRouter(name=name, base_url=base_url.rstrip("/"))
        self.peers[name] = peer
        self.metrics.gauge(
            "bci_router_peer_up",
            "Peer router edges answering gossip (1) vs failing "
            "consecutive syncs (0)",
            (lambda p: lambda: 1 if p.up else 0)(peer),
            peer=name,
        )
        return peer

    # ------------------------------------------------------------ refreshing

    def start(self) -> asyncio.Task:
        """Start the background refresh loop (requires a running loop);
        idempotent. The first refresh fires immediately so placement has a
        live view before the first request."""
        if self._task is not None and not self._task.done():
            return self._task
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self._task

    def _session(self):
        """The shared aiohttp client session, created on first use inside
        the running loop (constructing one outside a loop is an error, and
        FleetRouter is constructable synchronously)."""
        if self._client is None:
            import aiohttp

            self._client = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self._http_timeout_s)
            )
        return self._client

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        for evacuation in list(self._evacuations):
            evacuation.cancel()
        for evacuation in list(self._evacuations):
            try:
                await evacuation
            except asyncio.CancelledError:
                pass
        client, self._client = self._client, None
        if client is not None:
            await client.close()

    async def _run(self) -> None:
        while True:
            try:
                await self.refresh_once()
                await self.evacuate_draining()
                await self.sync_peers()
            except asyncio.CancelledError:
                raise
            except Exception:
                # One bad sweep must not end placement refresh for good.
                logger.exception("Fleet refresh failed")
            await asyncio.sleep(self._refresh_interval_s)

    async def refresh_once(self) -> None:
        """Pull ``/v1/fleet`` + ``/v1/slo`` from every replica concurrently
        (docs/fleet.md "Refresh loop"); a replica that stops answering goes
        stale and, past ``dead_after_s``, out of placement."""
        await asyncio.gather(
            *(self._refresh_replica(r) for r in self.replicas.values())
        )

    async def _refresh_replica(self, replica: Replica) -> None:
        timeout = min(5.0, self._refresh_interval_s * 2)
        try:
            fleet_resp = await self._request(
                "GET", f"{replica.base_url}/v1/fleet", timeout=timeout
            )
            slo_resp = await self._request(
                "GET", f"{replica.base_url}/v1/slo", timeout=timeout
            )
            if fleet_resp.status_code >= 400 or slo_resp.status_code >= 400:
                raise OSError(
                    f"refresh HTTP {fleet_resp.status_code}/{slo_resp.status_code}"
                )
            fleet = fleet_resp.json()
            slo = slo_resp.json()
        except Exception as e:
            replica.refresh_error = str(e) or type(e).__name__
            return
        # EWMA over the instantaneous busy fraction: a small pool's
        # utilization snapshot is nearly binary (one busy pod of two reads
        # 0.5 or 1.0 depending on the sampling instant), and placement must
        # veto SUSTAINED saturation, not one unlucky sample.
        sample = float(fleet.get("utilization") or 0.0)
        replica.utilization = (
            sample
            if replica.last_refresh_mono is None
            else 0.5 * replica.utilization + 0.5 * sample
        )
        replica.live_pods = int(fleet.get("live") or 0)
        replica.ready_pods = int((fleet.get("by_state") or {}).get("ready") or 0)
        replica.draining = bool(fleet.get("draining"))
        sessions = fleet.get("sessions") or {}
        replica.leases = int(sessions.get("active") or 0)
        replica.tenants = dict(fleet.get("tenants") or {})
        replica.cost_classes = dict(fleet.get("cost_classes") or {})
        replica.accelerator = dict(fleet.get("accelerator") or {})
        replica.slo_fast_burn = bool(slo.get("fast_burn_alerting"))
        replica.last_refresh_mono = self._clock()
        replica.refresh_error = None

    # ------------------------------------------------------------- HA gossip

    def peer_export(self) -> dict:
        """The ``GET /v1/fleet/peer`` document this edge serves: its pins
        and its lease ledger, in peer-portable (clock-free) form."""
        return {
            "router": self.router_id,
            "pins": [s.to_dict() for s in self.sessions.values()],
            "ledger": self.ledger.export(),
        }

    def adopt_pins(self, pins) -> int:
        """Merge a peer's session pins: unknown ids are adopted as-is; for
        ids both edges know, the entry with more migrations wins (each
        handoff bumps the count, so it is a monotonic version). Adopted
        pins are what make a router kill lose zero sessions — the
        surviving edge already holds every pin the dead one created."""
        adopted = 0
        if not isinstance(pins, list):
            return 0
        for doc in pins:
            if not isinstance(doc, dict):
                continue
            sid = doc.get("session_id")
            replica = doc.get("replica")
            backend_id = doc.get("backend_id")
            if not sid or replica not in self.replicas or not backend_id:
                continue
            migrations = int(doc.get("migrations") or 0)
            mine = self.sessions.get(sid)
            if mine is None:
                session = RouterSession(
                    public_id=sid,
                    replica=replica,
                    backend_id=backend_id,
                    created_unix=float(
                        doc.get("created_unix") or time.time()
                    ),
                )
                session.migrations = migrations
                self.sessions[sid] = session
                adopted += 1
            elif migrations > mine.migrations:
                mine.replica = replica
                mine.backend_id = backend_id
                mine.migrations = migrations
                adopted += 1
        return adopted

    async def sync_peers(self) -> None:
        """One gossip round: pull every peer's pins + ledger concurrently.
        A peer failing ``_PEER_DOWN_AFTER`` consecutive syncs is DOWN
        (``bci_router_peer_up`` 0) until it answers again; its state last
        adopted here keeps serving — failure detection informs operators,
        it never discards pins."""
        if self.peers:
            await asyncio.gather(
                *(self._sync_peer(p) for p in self.peers.values())
            )

    async def _sync_peer(self, peer: PeerRouter) -> None:
        timeout = min(5.0, self._refresh_interval_s * 2)
        try:
            response = await self._request(
                "GET", f"{peer.base_url}/v1/fleet/peer", timeout=timeout
            )
            if response.status_code >= 400:
                raise OSError(f"peer sync HTTP {response.status_code}")
            doc = response.json()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            peer.failures += 1
            peer.last_error = str(e) or type(e).__name__
            self._peer_sync_total.inc(peer=peer.name, outcome="error")
            return
        peer.failures = 0
        peer.last_error = None
        peer.last_sync_mono = self._clock()
        peer.pins_adopted += self.adopt_pins(doc.get("pins"))
        peer.leases_merged += self.ledger.merge(doc.get("ledger") or {})
        self._peer_sync_total.inc(peer=peer.name, outcome="ok")

    # ---------------------------------------------------------- quota leases

    def grant_quota_leases(self, replica: str, tenant_ids) -> dict:
        """The ``POST /v1/fleet/quota/lease`` body: per-tenant slices from
        the ledger plus the registered fleet size (the replica's fail-safe
        1/N divisor while partitioned from every router)."""
        leases = self.ledger.grant(replica, tenant_ids)
        self._quota_leases_total.inc(
            outcome="granted" if leases else "empty"
        )
        return {
            "router": self.router_id,
            "fleet_size": len(self.replicas),
            "leases": leases,
        }

    # ------------------------------------------------------------- placement

    def tenant_subset(self, tenant) -> list[str]:
        """The bounded replica subset a declared tenant's keyless traffic
        lands on: the top-k of the rendezvous ranking over ALL registered
        replica names (health-independent, so every router edge and every
        moment agree), k proportional to the tenant's WFQ weight."""
        ranked = rendezvous_rank(tenant.id, sorted(self.replicas))
        return ranked[: subset_size(tenant.weight, len(ranked))]

    def _steer_accelerator(self, ordered: list[Replica]) -> list[Replica]:
        """Stable-partition known TPU-capable replicas first. Capability
        is LEARNED from each replica's ``/v1/fleet`` cost-class mix (it
        has absorbed ``accelerator`` work before); while no replica has,
        there is no signal and the order stands."""
        capable = [
            r for r in ordered if (r.cost_classes.get("accelerator") or 0) > 0
        ]
        if not capable or len(capable) == len(ordered):
            return ordered
        return capable + [r for r in ordered if r not in capable]

    def place(
        self,
        key: str | None,
        exclude: frozenset[str] | set[str] = frozenset(),
        *,
        tenant=None,
        cost_class: str | None = None,
    ) -> list[Replica]:
        """Preference-ordered eligible replicas for one request. Keyed:
        ring order with the overloaded/burning owner demoted (spill) —
        snapshot locality beats every other signal. Unkeyed with a
        declared tenant: its rendezvous subset first (least-utilized
        within it), the remaining eligible replicas only as a last-resort
        tail — per-replica quota enforcement composes into a fleet-wide
        bound because the subset is where the traffic lands. Keyless/
        default-tenant: least-utilized first, round-robin tie-break.
        ``cost_class="accelerator"`` steers unkeyed placements toward
        known TPU-capable replicas."""
        with span("placement", keyed=str(key is not None)):
            return self._place(
                key, exclude, tenant=tenant, cost_class=cost_class
            )

    def _place(
        self,
        key: str | None,
        exclude: frozenset[str] | set[str] = frozenset(),
        *,
        tenant=None,
        cost_class: str | None = None,
    ) -> list[Replica]:
        now = self._clock()
        eligible = {
            r.name: r
            for r in self.replicas.values()
            if r.name not in exclude and r.eligible(now, self._dead_after_s)
        }
        if not eligible:
            raise NoReplicasAvailable(retry_after_s=self._refresh_interval_s)
        if key is None:
            ordered = sorted(eligible.values(), key=lambda r: r.utilization)
            # Equal-load fleets (the common idle case) rotate instead of
            # dog-piling the alphabetically first replica.
            self._rr += 1
            pivot = self._rr % len(ordered)
            head = [r for r in ordered if r.utilization == ordered[0].utilization]
            if len(head) > 1:
                rotated = head[pivot % len(head) :] + head[: pivot % len(head)]
                ordered = rotated + ordered[len(head) :]
            if (
                tenant is not None
                and getattr(tenant, "id", None) not in (None, DEFAULT_TENANT_ID)
            ):
                # The subset is the top-k of the rendezvous ranking over
                # the ELIGIBLE replicas: a dead member's slot is taken by
                # the next-ranked name (minimal re-form), every other
                # tenant's subset is untouched.
                ranked = [
                    name
                    for name in rendezvous_rank(
                        tenant.id, sorted(self.replicas)
                    )
                    if name in eligible
                ]
                members = set(
                    ranked[: subset_size(tenant.weight, len(self.replicas))]
                )
                if members:
                    ordered = [r for r in ordered if r.name in members] + [
                        r for r in ordered if r.name not in members
                    ]
            if cost_class == "accelerator":
                ordered = self._steer_accelerator(ordered)
            return ordered
        ordered = [
            eligible[name]
            for name in self.ring.preference(key)
            if name in eligible
        ]
        # Registered-but-unrung can't happen (add_replica keeps them in
        # lockstep) — but a defensive union keeps placement total.
        ordered += [r for r in eligible.values() if r not in ordered]
        owner = ordered[0]
        # Spill veto: the warm owner is still the fastest home while it has
        # ANY warm sandbox ready — demote it only when it is saturated AND
        # would make this request cold-spawn/queue anyway (or its SLO page
        # is firing).
        if len(ordered) > 1 and (
            owner.slo_fast_burn
            or (
                owner.utilization >= self._utilization_spill
                and owner.ready_pods == 0
            )
        ):
            better = next(
                (
                    r
                    for r in ordered[1:]
                    if not r.slo_fast_burn
                    and r.utilization < self._utilization_spill
                ),
                None,
            )
            if better is not None:
                ordered.remove(better)
                ordered.insert(0, better)
        return ordered

    def affinity_result(
        self, key: str | None, chosen: str, tenant=None
    ) -> str:
        """warm = the request landed on its ring owner (its snapshot chain
        is warm there); spill = re-homed (owner dead/overloaded/retried
        past); tenant = unkeyed but placed inside a declared tenant's
        rendezvous subset; keyless = no files, placed by load."""
        if key is None:
            if (
                tenant is not None
                and getattr(tenant, "id", None)
                not in (None, DEFAULT_TENANT_ID)
                and chosen in self.tenant_subset(tenant)
            ):
                return "tenant"
            return "keyless"
        return "warm" if self.ring.owner(key) == chosen else "spill"

    # ----------------------------------------------------- tenant resolution

    def resolve_tenant(self, headers):
        """The request's tenant at the ROUTER edge (same resolution rule
        as the replica edges: API key beats the X-Tenant-Id header), for
        placement and the router-side retry budget. None without a tenant
        table — every placement is then load-based, as before tenancy."""
        if self._tenancy is None:
            return None
        return self._tenancy.resolve(
            headers.get(TENANT_HEADER),
            bearer_token(headers.get("Authorization")),
        ).tenant

    def spend_retry_budget(self, tenant) -> bool:
        """Debit one cross-replica retry from the tenant's router-side
        budget. Tenants without a rate quota (and anonymous traffic) have
        no budget — unlimited, preserving pre-tenancy retry behavior."""
        rps = getattr(tenant, "rps", None)
        if tenant is None or rps is None:
            return True
        budget = self._retry_budgets.get(tenant.id)
        if budget is None:
            budget = self._retry_budgets[tenant.id] = RetryBudget(
                rps, clock=self._clock
            )
        if budget.spend():
            return True
        self._retry_budget_denied_total.inc(tenant=tenant.id)
        return False

    @staticmethod
    def sticky_shed(content: bytes) -> bool:
        """True when a 429 body carries a per-tenant shed reason
        (``tenant_quota``/``heavy_lane``): the verdict applies to the
        TENANT, not the replica — retrying it elsewhere would charge a
        fresh bucket and silently multiply the tenant's quota."""
        try:
            doc = json.loads(content)
        except (ValueError, UnicodeDecodeError):
            return False
        return (
            isinstance(doc, dict)
            and doc.get("reason") in _TENANT_SCOPED_SHEDS
        )

    # ------------------------------------------------------------ accounting

    def record_route(
        self,
        route: str,
        *,
        outcome: str,
        replica: str | None,
        key: str | None = None,
        affinity: str | None = None,
        retries: int = 0,
        duration_s: float = 0.0,
        session: str | None = None,
        tenant=None,
    ) -> None:
        """The ONE chokepoint every routed request passes through exactly
        once: decision totals, the ``kind="routing"`` wide event, the
        ``bci_router_*`` counters, and the router's user-perceived SLO
        sample all land here — they can only agree."""
        self.totals["routed"] += 1
        self.totals["retries"] += retries
        if replica is not None and replica in self.replicas:
            self.replicas[replica].routed_total += 1
        if affinity is not None:
            self.affinity_totals[affinity] += 1
            self._affinity_total.inc(result=affinity)
        self._requests_total.inc(route=route, outcome=outcome)
        self._request_seconds.observe(duration_s, route=route)
        if outcome != "shed":
            # User-perceived availability: the verdict the CLIENT saw after
            # every retry/failover the router performed. 4xx is the
            # client's own doing; error/unavailable/unreachable/unrouteable
            # all spend fleet error budget. Sheds are deliberate per-tenant
            # quota verdicts — excluded, matching the replica engines.
            self.slo.record(
                outcome in ("ok", "client_error", "cancelled"),
                duration_s,
                tenant=getattr(tenant, "id", None),
            )
        event = {
            "kind": "routing",
            "name": route,
            "outcome": outcome,
            "replica": replica,
            "retries": retries,
            "duration_ms": duration_s * 1000.0,
        }
        trace = current_trace()
        if trace is not None:
            # The correlation handles the replica recorder already stamps
            # (wide_event_from_trace): events-tail joins events to traces.
            event["trace_id"] = trace.trace_id
            if trace.request_id:
                event["request_id"] = trace.request_id
        if key is not None:
            event["key"] = key[:16]
        if affinity is not None:
            event["affinity"] = affinity
        if session is not None:
            event["session"] = session
        tenant_id = getattr(tenant, "id", None)
        if tenant_id is not None:
            event["tenant"] = tenant_id
        self.recorder.record(event)

    def record_retry(self, reason: str) -> None:
        self._retries_total.inc(reason=reason)

    # ----------------------------------------------------------- data plane

    @staticmethod
    def forward_headers(headers) -> dict[str, str]:
        return {
            name: headers[name]
            for name in _FORWARD_HEADERS
            if headers.get(name)
        }

    @staticmethod
    def _inject_trace_context(headers: dict[str, str] | None) -> dict[str, str]:
        """Overlay the router's AMBIENT trace context onto the forwarded
        headers: the replica must continue the router's span (making its
        trace a child of the router trace), not the client's original
        ``traceparent`` — the router's own root already continued that one.
        A case-insensitive replace, so the filtered lowercase client copy
        never rides along as a duplicate header. No ambient trace (peer
        gossip, refresh, evacuations off the request path) leaves the
        headers untouched."""
        out = dict(headers or {})
        extra = outbound_headers()
        if extra:
            lowered = {name.lower() for name in extra}
            out = {
                name: value
                for name, value in out.items()
                if name.lower() not in lowered
            }
            out.update(extra)
        return out

    @staticmethod
    def retry_reason(status: int) -> str | None:
        """Which upstream answers are worth a different replica: sheds and
        unavailability are deliberate go-elsewhere signals, 5xx is the
        at-least-once replay case. 4xx is the client's problem anywhere."""
        if status == 429:
            return "shed"
        if status == 503:
            return "unavailable"
        if status >= 500:
            return "server_error"
        return None

    @staticmethod
    def outcome_for_status(status: int) -> str:
        if status == 429:
            return "shed"
        if status == 503:
            return "unavailable"
        if status >= 500:
            return "error"
        if status >= 400:
            return "client_error"
        return "ok"

    async def _request(
        self,
        method: str,
        url: str,
        *,
        body: bytes | None = None,
        headers: dict[str, str] | None = None,
        params=None,
        timeout: float | None = None,
    ) -> "ProxiedResponse":
        """One buffered HTTP call through the shared aiohttp session,
        returned as a :class:`ProxiedResponse` (read fully, connection back
        to the pool)."""
        import aiohttp

        kwargs: dict = {}
        if params:
            kwargs["params"] = params
        if timeout is not None:
            kwargs["timeout"] = aiohttp.ClientTimeout(total=timeout)
        async with self._session().request(
            method, url, data=body, headers=headers or {}, **kwargs
        ) as response:
            return ProxiedResponse(
                response.status, response.headers, await response.read()
            )

    async def call_replica(
        self,
        replica: Replica,
        method: str,
        path: str,
        *,
        body: bytes | None = None,
        headers: dict[str, str] | None = None,
        params=None,
        timeout: float | None = None,
    ) -> "ProxiedResponse":
        """One breaker-guarded proxied call. Transport errors count against
        the replica's breaker and re-raise; HTTP answers are returned with
        5xx recorded as breaker failures (the replica is answering, badly)
        and everything else as successes."""
        with span("breaker", replica=replica.name):
            replica.breaker.before_call()
        try:
            with span("proxy", replica=replica.name) as proxy_span:
                # Trace context is computed INSIDE the proxy span so the
                # replica's continuation parents at this span — the replica
                # trace slots under the hop that carried it.
                response = await self._request(
                    method,
                    f"{replica.base_url}{path}",
                    body=body,
                    headers=self._inject_trace_context(headers),
                    params=params,
                    timeout=timeout,
                )
                if proxy_span is not None:
                    proxy_span.attributes["status"] = str(response.status_code)
        except asyncio.CancelledError:
            replica.breaker.record_abandoned()
            raise
        except Exception:
            replica.breaker.record_failure()
            raise
        if response.status_code >= 500 and response.status_code != 503:
            replica.breaker.record_failure()
        else:
            replica.breaker.record_success()
        return response

    @asynccontextmanager
    async def stream_replica(
        self,
        replica: Replica,
        method: str,
        path: str,
        *,
        body: bytes | None = None,
        headers: dict[str, str] | None = None,
        params=None,
    ):
        """Breaker-guarded streaming call yielding a :class:`ProxiedStream`.
        The replica's health verdict is taken from the response STATUS
        (known at open); mid-stream trouble — usually the downstream client
        vanishing — deliberately doesn't feed the breaker."""
        with span("breaker", replica=replica.name):
            replica.breaker.before_call()
        kwargs = {"params": params} if params else {}
        cm = self._session().request(
            method,
            f"{replica.base_url}{path}",
            data=body,
            headers=self._inject_trace_context(headers),
            **kwargs,
        )
        try:
            # The proxy span for a stream covers time-to-headers only; the
            # pump's own span owns the body relay.
            with span("proxy", replica=replica.name, stream="1") as proxy_span:
                response = await cm.__aenter__()
                if proxy_span is not None:
                    proxy_span.attributes["status"] = str(response.status)
        except asyncio.CancelledError:
            replica.breaker.record_abandoned()
            raise
        except Exception:
            replica.breaker.record_failure()
            raise
        try:
            if response.status >= 500 and response.status != 503:
                replica.breaker.record_failure()
            else:
                replica.breaker.record_success()
            yield ProxiedStream(response)
        finally:
            await cm.__aexit__(None, None, None)

    async def route_buffered(
        self,
        route: str,
        method: str,
        path: str,
        *,
        key: str | None,
        body: bytes | None,
        headers: dict[str, str] | None,
        params=None,
        retry: bool = True,
        retry_5xx: bool = True,
        tenant=None,
        cost_class: str | None = None,
    ):
        """Place + proxy one buffered request with cross-replica retry;
        returns ``(response, replica_name, retries)`` and leaves the
        accounting to the caller's single ``record_route``. ``retry_5xx``
        is off for calls whose replica-side effect may have happened
        despite the 5xx (session create: a leaked lease). Per-tenant
        sheds (``tenant_quota``/``heavy_lane``) are returned verbatim —
        never walked to another replica's bucket — and every cross-replica
        retry first debits the tenant's router-side retry budget."""
        attempts = self.retry_attempts if retry else 1
        exclude: set[str] = set()
        retries = 0
        last_response = None
        last_error: Exception | None = None
        for attempt in range(attempts):
            # One stage span per attempt: placement + breaker + proxy nest
            # under it, so the trace shows exactly where a retried request
            # spent its time and which replica each walk landed on.
            with span("attempt", attempt=attempt):
                try:
                    candidates = self.place(
                        key,
                        exclude=exclude,
                        tenant=tenant,
                        cost_class=cost_class,
                    )
                except NoReplicasAvailable:
                    if last_response is not None or last_error is not None:
                        break
                    raise
                replica = candidates[0]
                try:
                    response = await self.call_replica(
                        replica,
                        method,
                        path,
                        body=body,
                        headers=headers,
                        params=params,
                    )
                except asyncio.CancelledError:
                    raise
                except BreakerOpenError:
                    exclude.add(replica.name)
                    continue
                except Exception as e:
                    last_error = e
                    if not self.spend_retry_budget(tenant):
                        break
                    self.record_retry("unreachable")
                    retries += 1
                    exclude.add(replica.name)
                    continue
                reason = self.retry_reason(response.status_code)
                if reason is None or (
                    reason == "server_error" and not retry_5xx
                ):
                    return response, replica.name, retries
                if reason == "shed" and self.sticky_shed(response.content):
                    # A per-tenant verdict with its Retry-After: honest
                    # as-is.
                    return response, replica.name, retries
                last_response = response
                if not self.spend_retry_budget(tenant):
                    return response, replica.name, retries
                self.record_retry(reason)
                retries += 1
                exclude.add(replica.name)
        if last_response is not None:
            # Out of replicas: the last upstream verdict is the honest one.
            return last_response, None, retries
        raise last_error if last_error is not None else NoReplicasAvailable(
            retry_after_s=self._refresh_interval_s
        )

    # -------------------------------------------------------------- sessions

    def pin_session(self, session_id: str, replica: str) -> RouterSession:
        session = RouterSession(
            public_id=session_id, replica=replica, backend_id=session_id
        )
        self.sessions[session_id] = session
        return session

    def get_session(self, session_id: str) -> RouterSession:
        session = self.sessions.get(session_id)
        if session is None:
            raise UnknownRouterSession(
                f"router has no session {session_id!r} (created elsewhere, "
                "expired, or released)"
            )
        return session

    def unpin_session(self, session_id: str) -> None:
        self.sessions.pop(session_id, None)

    async def drain_replica(self, name: str) -> dict:
        """Operator-initiated evacuation (``POST /v1/fleet/replicas/{name}/
        drain``, and the preStop hook's call): cordon the replica out of
        placement, then hand every pinned lease off. Returns the migration
        tally; the replica itself keeps serving until ITS drain begins."""
        replica = self.replicas.get(name)
        if replica is None:
            raise KeyError(name)
        replica.cordoned = True
        return await self.migrate_replica_sessions(name)

    async def evacuate_draining(self) -> list[asyncio.Task]:
        """Refresh-loop follow-up: any replica observed draining (its own
        SIGTERM path) gets its pinned leases handed off before the
        replica-side sweep would expire them. Evacuations run as ANCHORED
        background tasks: a migration waits on each session's lock, and one
        long in-flight pinned call must never stall the refresh loop (a
        stalled refresh ages every replica toward dead and takes the whole
        router out). Returns the spawned tasks so tests (and the drain
        endpoint's twin) can await completion."""
        spawned: list[asyncio.Task] = []
        for name, replica in self.replicas.items():
            if (
                (replica.draining or replica.cordoned)
                and name not in self._migrating
                and any(s.replica == name for s in self.sessions.values())
            ):
                # Claim synchronously: two refresh ticks racing the task's
                # startup must not both spawn an evacuation.
                self._migrating.add(name)
                task = asyncio.get_running_loop().create_task(
                    self._evacuate_replica(name)
                )
                self._evacuations.add(task)
                task.add_done_callback(self._evacuations.discard)
                spawned.append(task)
        return spawned

    async def _evacuate_replica(self, name: str) -> None:
        try:
            await self.migrate_replica_sessions(name)
        except asyncio.CancelledError:
            raise
        except Exception:
            logger.exception("Lease evacuation of %s failed", name)

    async def migrate_replica_sessions(self, name: str) -> dict:
        self._migrating.add(name)
        try:
            tally = {"migrated": 0, "failed": 0}
            for session in [
                s for s in self.sessions.values() if s.replica == name
            ]:
                ok = await self.migrate_session(session, exclude={name})
                tally["migrated" if ok else "failed"] += 1
            return tally
        finally:
            self._migrating.discard(name)

    async def migrate_session(
        self, session: RouterSession, exclude: set[str], *, locked: bool = False
    ) -> bool:
        """One lease handoff (docs/fleet.md "Lease handoff"): checkpoint on
        the old replica → create a lease elsewhere restoring the checkpoint
        → release the old lease → re-point the pin. Serialized against the
        session's own proxied calls by its lock, so an in-flight execute
        can never slip between checkpoint and re-lease; ``locked=True`` is
        for the caller already holding it (the pinned-503 rescue path in
        ``fleet.app``)."""
        expect = session.replica
        if locked:
            return await self._migrate_locked(session, exclude, expect)
        async with session.lock:
            return await self._migrate_locked(session, exclude, expect)

    async def _migrate_locked(
        self, session: RouterSession, exclude: set[str], expect: str
    ) -> bool:
        if session.replica != expect:
            # A concurrent evacuation already moved it while we waited for
            # the lock: done, and NOT a second accountable migration.
            return True
        start = self._clock()
        old_replica, old_backend_id = session.replica, session.backend_id
        if session.public_id not in self.sessions:
            return False  # released while we waited for the lock
        outcome = "failed"
        detail = None
        target_name = None
        try:
            checkpoint = await self.call_replica(
                self.replicas[old_replica],
                "POST",
                f"/v1/sessions/{old_backend_id}/checkpoint",
                body=b"{}",
                headers={"content-type": "application/json"},
            )
            if checkpoint.status_code != 200:
                detail = f"checkpoint HTTP {checkpoint.status_code}"
                if checkpoint.status_code == 404:
                    # The lease is already gone (replica sweep won the
                    # race); the pin is stale, not migratable.
                    self.unpin_session(session.public_id)
                    detail = "lease already gone"
                return False
            files = checkpoint.json().get("files", {})
            key = affinity_key(files)
            try:
                targets = self.place(key, exclude=set(exclude))
            except NoReplicasAvailable:
                detail = "no target replica"
                return False
            create = None
            for target in targets:
                try:
                    create = await self.call_replica(
                        target,
                        "POST",
                        "/v1/sessions",
                        body=json.dumps({"files": files}).encode(),
                        headers={"content-type": "application/json"},
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    detail = str(e)
                    continue
                if create.status_code == 200:
                    target_name = target.name
                    break
                detail = f"re-lease HTTP {create.status_code}"
                if create.status_code not in (429, 503):
                    # A plain 5xx may have leased a sandbox on that target
                    # before failing — trying further targets would fan the
                    # leak wider (the same reason session_create never
                    # retries 5xx). Shed/unavailable leased nothing.
                    break
            if target_name is None:
                return False
            new_backend_id = create.json()["session_id"]
            try:
                await self.call_replica(
                    self.replicas[old_replica],
                    "DELETE",
                    f"/v1/sessions/{old_backend_id}",
                )
            except asyncio.CancelledError:
                raise
            except Exception:
                # The old replica is going away regardless; its sweep
                # (or teardown) reclaims the sandbox.
                logger.warning(
                    "Could not release migrated lease %s on %s",
                    old_backend_id,
                    old_replica,
                )
            session.replica = target_name
            session.backend_id = new_backend_id
            session.migrations += 1
            outcome = "ok"
            return True
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # A dead source replica mid-handoff: accounted as failed, the
            # caller decides whether the pin is still worth keeping.
            detail = detail or str(e) or type(e).__name__
            target_name = None
            return False
        finally:
            self.totals[
                "migrations_ok" if outcome == "ok" else "migrations_failed"
            ] += 1
            self._migrations_total.inc(outcome=outcome)
            event = {
                "kind": "lease_migrate",
                "name": "lease.migrate",
                "outcome": outcome,
                "session": session.public_id,
                "from": old_replica,
                "to": target_name,
                "duration_ms": (self._clock() - start) * 1000.0,
            }
            trace = current_trace()
            if trace is not None:
                # A pinned-503 rescue runs inside the request's trace; the
                # correlation fields join the handoff to it. Background
                # evacuations have no ambient trace — fields absent.
                event["trace_id"] = trace.trace_id
                if trace.request_id:
                    event["request_id"] = trace.request_id
            if detail is not None:
                event["detail"] = detail
            self.recorder.record(event)
            logger.info(
                "Lease handoff %s: session %s %s -> %s%s",
                outcome,
                session.public_id,
                old_replica,
                target_name,
                f" ({detail})" if detail else "",
            )

    # -------------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """The ``GET /v1/fleet/replicas`` document: per-replica live view +
        the router's own decision totals (docs/fleet.md)."""
        now = self._clock()
        shares = self.ring.shares()
        return {
            "replicas": [
                r.to_dict(now, self._dead_after_s, shares.get(r.name, 0.0))
                for r in sorted(self.replicas.values(), key=lambda r: r.name)
            ],
            "sessions": {
                "pinned": len(self.sessions),
                "by_replica": {
                    name: sum(
                        1 for s in self.sessions.values() if s.replica == name
                    )
                    for name in self.replicas
                },
            },
            "totals": dict(self.totals),
            "affinity": dict(self.affinity_totals),
            # Fleet-wide tenancy plane (docs/fleet.md "Fleet-wide
            # tenancy"): the quota-lease ledger and the peer-router view.
            "quota": self.ledger.snapshot(),
            "peers": [
                self.peers[name].to_dict() for name in sorted(self.peers)
            ],
        }
