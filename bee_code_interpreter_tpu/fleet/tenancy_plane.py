"""Router-side fleet-wide tenancy plane (docs/fleet.md "Fleet-wide
tenancy").

Three small pieces the :class:`~.router.FleetRouter` composes:

- **Rendezvous placement** (:func:`rendezvous_rank`, :func:`subset_size`):
  each declared tenant is hashed onto a bounded replica subset (k replicas
  proportional to its WFQ weight), so per-replica admission enforcement
  composes into a fleet-wide bound *by construction* — a tenant spraying
  keyless requests cannot collect one bucket per replica. Rendezvous
  (highest-random-weight) hashing re-forms the subset minimally when a
  replica dies: only the dead member's slot moves.
- **Quota-lease ledger** (:class:`QuotaLedger`): the router's half of the
  lease protocol. Each replica periodically asks for a slice of every
  rate-quota'd tenant it serves; the ledger splits the tenant's declared
  fleet-wide ``rps`` equally across the ACTIVE lessees (replicas holding a
  non-expired lease), so the fleet-wide sum converges to the declared
  quota as leases refresh. Membership churn can transiently over-issue —
  bounded by one lease TTL, the declared bound docs/fleet.md states.
- **Router-edge retry budgets** (:class:`RetryBudget`): the proxy-side
  twin of the admission controller's per-tenant retry budget, consulted
  before every cross-replica retry so a retry storm cannot amplify
  through the router.
"""

from __future__ import annotations

import hashlib
import math
import time
from typing import Callable, Iterable

#: Mirrors resilience/admission.py: a tenant with a rate quota may retry at
#: ~10% of it through the router, bucket depth 10.
RETRY_BUDGET_RATIO = 0.1
RETRY_BUDGET_MIN_RATE = 0.1
RETRY_BUDGET_BURST = 10.0


def rendezvous_rank(tenant_id: str, names: Iterable[str]) -> list[str]:
    """All replica names ranked by rendezvous (highest-random-weight)
    score for ``tenant_id``. Deterministic across router edges (pure
    function of the names), and minimally disruptive: removing one name
    never reorders the others, so a dead subset member's slot is taken by
    the next-ranked replica and every other tenant's subset is unmoved."""

    def score(name: str) -> int:
        digest = hashlib.sha256(f"{tenant_id}|{name}".encode()).digest()
        return int.from_bytes(digest[:8], "big")

    return sorted(names, key=score, reverse=True)


def subset_size(weight: float, n_replicas: int) -> int:
    """k ∝ weight, clamped to [1, N]: a weight-1 tenant concentrates on
    one replica (its per-replica quota IS its fleet quota), a weight-4
    tenant spreads across four."""
    return max(1, min(n_replicas, math.ceil(weight)))


class QuotaLedger:
    """Which replicas currently hold a lease on which tenant's quota.

    ``registry`` is the router's :class:`~..tenancy.TenantRegistry` (may
    be None: every grant answers with zero leases, and replicas stay on
    their local fallback). Lessee entries expire after ``ttl_s``; a grant
    recomputes the equal split over the active lessees *including the
    asker*, so the first refresh after membership changes re-converges
    the fleet-wide sum."""

    def __init__(
        self,
        registry=None,
        *,
        ttl_s: float = 3.0,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._registry = registry
        self._ttl_s = ttl_s
        self._clock = clock
        # tenant id -> {replica name -> lease expiry (this clock)}
        self._lessees: dict[str, dict[str, float]] = {}
        self.granted_total = 0
        self.merged_total = 0

    @property
    def ttl_s(self) -> float:
        return self._ttl_s

    def _active(self, tenant_id: str, now: float) -> dict[str, float]:
        table = self._lessees.get(tenant_id)
        if not table:
            return {}
        for name in [n for n, exp in table.items() if exp <= now]:
            del table[name]
        return table

    def grant(self, replica: str, tenant_ids: Iterable[str]) -> dict:
        """One lease request from ``replica``: returns the per-tenant
        slices ``{tenant: {rps, burst, ttl_s}}``. Tenants unknown to the
        registry or without a rate quota are skipped — the replica's own
        table is authoritative for everything but the split."""
        now = self._clock()
        leases: dict[str, dict] = {}
        for tenant_id in tenant_ids:
            tenant = (
                self._registry.get(tenant_id)
                if self._registry is not None
                else None
            )
            if tenant is None or tenant.rps is None:
                continue
            table = self._lessees.setdefault(tenant_id, {})
            table[replica] = now + self._ttl_s
            share = max(1, len(self._active(tenant_id, now)))
            leases[tenant_id] = {
                "rps": tenant.rps / share,
                "burst": max(1.0, tenant.burst_depth / share),
                "ttl_s": self._ttl_s,
            }
            self.granted_total += 1
        return leases

    def active_count(self) -> int:
        now = self._clock()
        return sum(
            len(self._active(tenant_id, now))
            for tenant_id in list(self._lessees)
        )

    # ------------------------------------------------------------ HA gossip

    def export(self) -> dict:
        """The ledger as peer-portable relative expiries (router clocks
        are not comparable): ``{tenant: {replica: expires_in_s}}``."""
        now = self._clock()
        out: dict[str, dict[str, float]] = {}
        for tenant_id in list(self._lessees):
            active = self._active(tenant_id, now)
            if active:
                out[tenant_id] = {
                    name: round(exp - now, 3) for name, exp in active.items()
                }
        return out

    def merge(self, peer_export: dict) -> int:
        """Reconcile a peer's ledger into this one: max expiry wins per
        (tenant, replica). After a router edge dies, the survivor already
        knows every lessee the dead edge granted to — the next refresh
        splits over the full set instead of re-issuing full quotas, which
        is what bounds double-issue to one TTL of membership skew."""
        now = self._clock()
        merged = 0
        if not isinstance(peer_export, dict):
            return 0
        for tenant_id, lessees in peer_export.items():
            if not isinstance(lessees, dict):
                continue
            table = self._lessees.setdefault(str(tenant_id), {})
            for replica, expires_in_s in lessees.items():
                try:
                    expiry = now + min(float(expires_in_s), self._ttl_s)
                except (TypeError, ValueError):
                    continue
                if expiry > table.get(str(replica), 0.0):
                    table[str(replica)] = expiry
                    merged += 1
        self.merged_total += merged
        return merged

    def snapshot(self) -> dict:
        """The operator view (``GET /v1/fleet/replicas`` "quota" section;
        scripts/fleet-router-top.py renders it)."""
        now = self._clock()
        tenants: dict[str, dict] = {}
        for tenant_id in sorted(self._lessees):
            active = self._active(tenant_id, now)
            if not active:
                continue
            tenant = (
                self._registry.get(tenant_id)
                if self._registry is not None
                else None
            )
            rps = tenant.rps if tenant is not None else None
            tenants[tenant_id] = {
                "rps": rps,
                "lessees": {
                    name: round(exp - now, 3)
                    for name, exp in sorted(active.items())
                },
                "slice_rps": (
                    round(rps / max(1, len(active)), 3)
                    if rps is not None
                    else None
                ),
            }
        return {
            "ttl_s": self._ttl_s,
            "granted_total": self.granted_total,
            "merged_total": self.merged_total,
            "tenants": tenants,
        }


class RetryBudget:
    """Router-edge per-tenant retry token bucket, mirroring the admission
    controller's (~10% of the rate quota, depth 10). One instance per
    tenant, created lazily by the router."""

    def __init__(
        self, rps: float, *, clock: Callable[[], float] = time.monotonic
    ) -> None:
        self._rate = max(RETRY_BUDGET_MIN_RATE, rps * RETRY_BUDGET_RATIO)
        self._clock = clock
        self._tokens = RETRY_BUDGET_BURST
        self._mono = clock()
        self.denied = 0

    def spend(self) -> bool:
        now = self._clock()
        self._tokens = min(
            RETRY_BUDGET_BURST,
            self._tokens + (now - self._mono) * self._rate,
        )
        self._mono = now
        if self._tokens >= 1.0:
            self._tokens -= 1.0
            return True
        self.denied += 1
        return False
