"""Per-tenant usage metering — the billing substrate (docs/tenancy.md).

PR 3's accounting answers "what did THIS request cost" (``usage`` blocks on
the wire, ``bci_execution_*`` histograms); this module rolls the same blocks
up per *tenant*: CPU-seconds, peak RSS, data-plane bytes, workspace writes
and request outcomes, served at ``GET /v1/tenants`` (gRPC ``GetTenants``)
and exported as ``bci_tenant_*`` metrics.

Cardinality is bounded twice: the meter itself keeps at most ``max_labels``
tenant slots (further labels collapse into ``other``), and the metrics
Registry's label guard (``utils/metrics.py``) clamps the ``tenant`` label
independently — a tenant-id flood can grow neither this map nor
``/metrics``.
"""

from __future__ import annotations


class _TenantUsage:
    __slots__ = (
        "requests",
        "outcomes",
        "sheds",
        "executions",
        "cpu_s",
        "wall_s",
        "max_rss_bytes",
        "workspace_bytes",
        "uploaded_bytes",
        "downloaded_bytes",
        "files_changed",
    )

    def __init__(self) -> None:
        self.requests = 0
        self.outcomes: dict[str, int] = {}
        self.sheds = 0
        self.executions = 0
        self.cpu_s = 0.0
        self.wall_s = 0.0
        self.max_rss_bytes = 0
        self.workspace_bytes = 0
        self.uploaded_bytes = 0
        self.downloaded_bytes = 0
        self.files_changed = 0

    def to_dict(self) -> dict:
        return {
            "requests": self.requests,
            "outcomes": dict(self.outcomes),
            "sheds": self.sheds,
            "executions": self.executions,
            "cpu_s": round(self.cpu_s, 6),
            "wall_s": round(self.wall_s, 6),
            "max_rss_bytes": self.max_rss_bytes,
            "workspace_bytes": self.workspace_bytes,
            "uploaded_bytes": self.uploaded_bytes,
            "downloaded_bytes": self.downloaded_bytes,
            "files_changed": self.files_changed,
        }


class TenantUsageMeter:
    """Bounded per-tenant usage rollups. Writers are the edges (via the
    ambient :func:`~.context.meter_ambient_usage`) and the admission gate
    (sheds); readers are ``GET /v1/tenants`` and the fleet tenant-mix
    export."""

    def __init__(self, metrics=None, max_labels: int = 32) -> None:
        self._slots: dict[str, _TenantUsage] = {}
        self._max_labels = max(1, max_labels)
        self._requests_total = None
        self._cpu_seconds_total = None
        self._bytes_total = None
        if metrics is not None:
            self._requests_total = metrics.counter(
                "bci_tenant_requests_total",
                "Sandbox-bound requests recorded per tenant, by outcome",
            )
            self._cpu_seconds_total = metrics.counter(
                "bci_tenant_cpu_seconds_total",
                "Sandbox CPU time (user+system) consumed per tenant",
            )
            self._bytes_total = metrics.counter(
                "bci_tenant_bytes_total",
                "Data-plane and workspace bytes moved per tenant, by direction",
            )

    def _slot(self, label: str) -> _TenantUsage:
        slot = self._slots.get(label)
        if slot is None:
            if len(self._slots) >= self._max_labels and label != "other":
                return self._slot("other")
            slot = self._slots[label] = _TenantUsage()
        return slot

    # ------------------------------------------------------------- writers

    def record_request(self, label: str, outcome: str) -> None:
        slot = self._slot(label)
        slot.requests += 1
        slot.outcomes[outcome] = slot.outcomes.get(outcome, 0) + 1
        if outcome == "shed":
            slot.sheds += 1
        if self._requests_total is not None:
            self._requests_total.inc(tenant=label, outcome=outcome)

    def record_usage(self, label: str, usage: dict) -> None:
        """One execution's ``usage`` block (the same dict the response
        carries), attributed to ``label``."""
        slot = self._slot(label)
        slot.executions += 1
        cpu = float(usage.get("cpu_user_s", 0.0)) + float(
            usage.get("cpu_system_s", 0.0)
        )
        slot.cpu_s += cpu
        slot.wall_s += float(usage.get("wall_s", 0.0) or 0.0)
        rss = int(usage.get("max_rss_bytes", 0) or 0)
        slot.max_rss_bytes = max(slot.max_rss_bytes, rss)
        workspace = int(usage.get("workspace_bytes_written", 0) or 0)
        uploaded = int(usage.get("uploaded_bytes", 0) or 0)
        downloaded = int(usage.get("downloaded_bytes", 0) or 0)
        slot.workspace_bytes += workspace
        slot.uploaded_bytes += uploaded
        slot.downloaded_bytes += downloaded
        slot.files_changed += int(usage.get("files_changed", 0) or 0)
        if self._cpu_seconds_total is not None and cpu > 0:
            self._cpu_seconds_total.inc(cpu, tenant=label)
        if self._bytes_total is not None:
            if uploaded:
                self._bytes_total.inc(uploaded, tenant=label, direction="upload")
            if downloaded:
                self._bytes_total.inc(
                    downloaded, tenant=label, direction="download"
                )
            if workspace:
                self._bytes_total.inc(
                    workspace, tenant=label, direction="workspace"
                )

    # ------------------------------------------------------------- readers

    def labels(self) -> tuple[str, ...]:
        return tuple(sorted(self._slots))

    def mix(self) -> dict[str, int]:
        """Per-tenant lifetime request counts — the ``tenants`` section of
        ``GET /v1/fleet`` a placement-aware router consumes."""
        return {label: slot.requests for label, slot in sorted(self._slots.items())}

    def snapshot(self) -> dict[str, dict]:
        return {label: slot.to_dict() for label, slot in sorted(self._slots.items())}
