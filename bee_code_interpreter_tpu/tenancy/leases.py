"""Replica-side fleet-wide quota leases (docs/tenancy.md "Fleet-wide
tenancy").

A tenant's ``rps`` quota is declared for the LOGICAL service, but PR 13's
token bucket enforces it per replica — behind a fleet router, N replicas
would hand out N× the declared rate. The lease protocol closes that gap
without a per-request coordination hop:

- :class:`QuotaLeaseCache` holds the slice of each tenant's fleet-wide
  rate this replica may currently enforce, granted by a router edge
  (``POST /v1/fleet/quota/lease``) with a TTL. The admission controller
  consults it on every token refill — enforcement stays local and
  synchronous; only the *budget* is distributed.
- :class:`QuotaLeaseClient` is the background refresher: every
  ``interval_s`` it asks a router for fresh slices covering the tenants
  this replica has actually seen, failing over across router edges in
  order.

**Fail SAFE, never open**: when every router is unreachable the cached
leases expire and :meth:`QuotaLeaseCache.effective` degrades to a local
``1/N`` split over the last known fleet size — a partitioned replica
enforces a TIGHTER quota than its lease, never an unlimited one, and never
more than the tenant's full declared quota.
"""

from __future__ import annotations

import asyncio
import logging
import time
from dataclasses import dataclass
from typing import Callable

logger = logging.getLogger(__name__)

#: Default lease lifetime a router grants; the refresh interval should be
#: comfortably shorter so a healthy replica never falls into the 1/N
#: fallback between refreshes.
LEASE_DEFAULT_TTL_S = 3.0


@dataclass
class QuotaLease:
    """One granted slice of a tenant's fleet-wide rate quota."""

    tenant_id: str
    rps: float
    burst: float
    expires_mono: float
    router: str | None = None  # which router edge granted it


class QuotaLeaseCache:
    """The replica's view of its granted quota slices, with the fail-safe
    fallback built in. Synchronous and allocation-light: the admission
    controller reads it on every token refill."""

    def __init__(
        self,
        *,
        fleet_size_hint: int = 1,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self._clock = clock
        self._leases: dict[str, QuotaLease] = {}
        # Last known replica count, for the 1/N fallback split. Starts at
        # the configured hint (1 = standalone keeps its full quota) and is
        # updated from every lease response — a replica that has EVER
        # talked to a router keeps splitting correctly while partitioned.
        self._fleet_size = max(1, int(fleet_size_hint))
        self.granted = 0  # lease updates applied
        self.fallbacks = 0  # effective() answers served by the 1/N split

    @property
    def fleet_size(self) -> int:
        return self._fleet_size

    def observe_fleet_size(self, n) -> None:
        if isinstance(n, (int, float)) and n >= 1:
            self._fleet_size = int(n)

    def update(
        self,
        tenant_id: str,
        *,
        rps: float,
        burst: float,
        ttl_s: float,
        router: str | None = None,
    ) -> None:
        self._leases[tenant_id] = QuotaLease(
            tenant_id=tenant_id,
            rps=max(0.0, float(rps)),
            burst=max(1.0, float(burst)),
            expires_mono=self._clock() + max(0.0, float(ttl_s)),
            router=router,
        )
        self.granted += 1

    def lease(self, tenant_id: str) -> QuotaLease | None:
        """The non-expired lease for ``tenant_id``, else None."""
        lease = self._leases.get(tenant_id)
        if lease is None or lease.expires_mono <= self._clock():
            return None
        return lease

    def effective(self, tenant) -> tuple[float, float]:
        """The ``(rps, burst)`` this replica may enforce for ``tenant``
        right now. A valid lease caps at the tenant's own declared quota
        (a buggy or malicious router can tighten, never widen); no valid
        lease means the 1/N fallback split — degraded enforcement is a
        tighter quota, never an open one."""
        rps = tenant.rps
        burst = tenant.burst_depth
        lease = self.lease(tenant.id)
        if lease is not None:
            return min(lease.rps, rps), max(1.0, min(lease.burst, burst))
        self.fallbacks += 1
        n = self._fleet_size
        return rps / n, max(1.0, burst / n)

    def snapshot(self) -> dict:
        now = self._clock()
        return {
            "fleet_size": self._fleet_size,
            "granted": self.granted,
            "fallbacks": self.fallbacks,
            "leases": {
                tid: {
                    "rps": round(lease.rps, 3),
                    "burst": round(lease.burst, 3),
                    "ttl_s": round(max(0.0, lease.expires_mono - now), 3),
                    "router": lease.router,
                }
                for tid, lease in sorted(self._leases.items())
            },
        }


class QuotaLeaseClient:
    """Background lease refresher for one replica.

    Every ``interval_s`` it POSTs ``/v1/fleet/quota/lease`` to the first
    reachable router edge (failing over in declared order, sticking with
    the last one that answered), covering every rate-quota'd tenant the
    admission controller has seen. Total unreachability is not an error
    path the data plane ever observes: the cache simply expires into its
    1/N fallback."""

    def __init__(
        self,
        cache: QuotaLeaseCache,
        admission,
        *,
        replica: str,
        router_urls: list[str],
        interval_s: float = 1.0,
        http_timeout_s: float = 2.0,
        metrics=None,
        http_client=None,
    ) -> None:
        self._cache = cache
        self._admission = admission
        self._replica = replica
        self._urls = [u.rstrip("/") for u in router_urls if u.strip()]
        self._interval_s = interval_s
        self._http_timeout_s = http_timeout_s
        self._client = http_client
        self._task: asyncio.Task | None = None
        self._preferred = 0  # index of the last router that answered
        self._refresh_total = None
        if metrics is not None:
            self._refresh_total = metrics.counter(
                "bci_quota_lease_refresh_total",
                "Quota-lease refresh attempts against the router tier, by "
                "outcome (ok/unreachable)",
            )
            metrics.gauge(
                "bci_quota_lease_fleet_size",
                "Fleet size last reported by a router (the 1/N fallback "
                "divisor)",
                lambda: self._cache.fleet_size,
            )

    def start(self) -> asyncio.Task:
        """Start the refresh loop (requires a running loop); idempotent."""
        if self._task is not None and not self._task.done():
            return self._task
        self._task = asyncio.get_running_loop().create_task(self._run())
        return self._task

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        client, self._client = self._client, None
        if client is not None:
            await client.close()

    async def _run(self) -> None:
        while True:
            try:
                await self.refresh_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                # One bad sweep must not end quota convergence for good.
                logger.exception("Quota lease refresh failed")
            await asyncio.sleep(self._interval_s)

    def _session(self):
        if self._client is None:
            import aiohttp

            self._client = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self._http_timeout_s)
            )
        return self._client

    async def refresh_once(self) -> bool:
        """One refresh attempt across the router list. Returns True when a
        router answered (even with zero leases: the fleet-size observation
        alone keeps the fallback split honest)."""
        import json as _json

        tenants = self._admission.quota_tenants()
        body = _json.dumps(
            {"replica": self._replica, "tenants": tenants}
        ).encode()
        n = len(self._urls)
        for i in range(n):
            url = self._urls[(self._preferred + i) % n]
            try:
                async with self._session().post(
                    f"{url}/v1/fleet/quota/lease",
                    data=body,
                    headers={"content-type": "application/json"},
                ) as response:
                    if response.status != 200:
                        continue
                    doc = await response.json()
            except asyncio.CancelledError:
                raise
            except Exception:
                continue
            router_id = doc.get("router")
            for tid, lease in (doc.get("leases") or {}).items():
                try:
                    self._cache.update(
                        tid,
                        rps=lease["rps"],
                        burst=lease["burst"],
                        ttl_s=lease["ttl_s"],
                        router=router_id,
                    )
                except (KeyError, TypeError, ValueError):
                    continue  # one malformed grant must not kill the rest
            self._cache.observe_fleet_size(doc.get("fleet_size"))
            self._preferred = (self._preferred + i) % n
            if self._refresh_total is not None:
                self._refresh_total.inc(outcome="ok")
            return True
        if self._refresh_total is not None:
            self._refresh_total.inc(outcome="unreachable")
        return False
