"""Ambient tenant identity for one request (docs/tenancy.md).

Both API edges resolve the caller's identity (``X-Tenant-Id`` header /
``x-tenant-id`` gRPC metadata, or an ``Authorization: Bearer`` API key from
the tenant table) into ONE :class:`TenantContext` and activate it here for
the request's lifetime — the same contextvar design as ``tracing.span`` and
``collect_transfer``: downstream layers (admission, SLO, usage accounting,
session caps, the retry loop) read the ambient context instead of threading
a ``tenant=`` argument through every call signature, and code running
outside a request (tests, scripts, background sweeps) sees ``None`` and
behaves exactly as before tenancy existed.

This module deliberately imports nothing from the rest of the service so
any layer (``utils``, ``resilience``, ``observability``) can consume it
without import cycles.
"""

from __future__ import annotations

from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass
from typing import Callable

# The HTTP header and its gRPC invocation-metadata twin (metadata keys are
# lowercase on the wire).
TENANT_HEADER = "X-Tenant-Id"
TENANT_METADATA_KEY = "x-tenant-id"


@dataclass
class TenantContext:
    """One request's resolved tenant identity.

    ``tenant`` is the :class:`~.registry.Tenant` whose quotas/weight apply
    (unknown ids share the ``default`` tenant's lane); ``label`` is the
    bounded-cardinality spelling safe to use as a metric label and span
    attribute; ``raw_id`` is what the client actually sent (wide events
    keep it for forensics, metrics never see it)."""

    tenant: object  # tenancy.registry.Tenant (untyped: no import cycle)
    label: str
    raw_id: str | None = None
    meter: object | None = None  # tenancy.metering.TenantUsageMeter
    # Per-tenant retry budget (docs/tenancy.md "Retry budgets"): the edge
    # binds this to the admission controller's per-tenant token bucket; the
    # resilience retry loop consults it before every re-attempt.
    retry_budget: Callable[[], bool] | None = None

    def record_usage(self, usage: dict | None) -> None:
        if self.meter is not None and usage:
            self.meter.record_usage(self.label, usage)

    def record_request(self, outcome: str) -> None:
        if self.meter is not None:
            self.meter.record_request(self.label, outcome)


_current: ContextVar[TenantContext | None] = ContextVar(
    "bci_tenant_context", default=None
)


def current_tenant_context() -> TenantContext | None:
    return _current.get()


def current_tenant_label() -> str | None:
    ctx = _current.get()
    return ctx.label if ctx is not None else None


@contextmanager
def tenant_scope(ctx: TenantContext | None):
    """Activate ``ctx`` for the enclosed request; ``None`` explicitly
    clears any inherited context (an aiohttp keep-alive connection task
    serves sequential requests in ONE context — identity must never leak
    across them)."""
    token = _current.set(ctx)
    try:
        yield ctx
    finally:
        _current.reset(token)


def meter_ambient_usage(usage: dict | None) -> None:
    """Report one execution's ``usage`` block to the ambient tenant's
    usage meter; a no-op outside a tenant-resolved request. Called by
    ``observability.record_usage_at_edge`` so every path that lands usage
    at an edge also meters it per tenant — by construction, not by eight
    separate call sites."""
    ctx = _current.get()
    if ctx is not None:
        ctx.record_usage(usage)


def consume_retry_budget() -> bool:
    """One retry's worth of the ambient tenant's retry budget. ``True``
    (retry allowed) outside a request or when no budget is bound — the
    pre-tenancy behavior."""
    ctx = _current.get()
    if ctx is None or ctx.retry_budget is None:
        return True
    return bool(ctx.retry_budget())


def bearer_token(authorization: str | None) -> str | None:
    """The token from an ``Authorization: Bearer <token>`` value; None for
    anything else (other schemes are not tenant API keys)."""
    if not authorization:
        return None
    scheme, _, token = authorization.partition(" ")
    if scheme.lower() != "bearer":
        return None
    token = token.strip()
    return token or None
