"""Multi-tenant isolation (docs/tenancy.md).

Identity (``X-Tenant-Id`` / API key → :class:`TenantContext` contextvar),
the config-declared tenant table (``APP_TENANTS`` → :class:`TenantRegistry`),
and per-tenant usage metering (:class:`TenantUsageMeter`, the billing
substrate behind ``GET /v1/tenants``). The *enforcement* lives where the
chokepoints already are: weighted-fair queuing and per-tenant quotas on
``resilience.AdmissionController``, per-tenant SLO slices on
``observability.SloEngine``, per-tenant lease caps on
``sessions.SessionManager`` — this package only says WHO a request is.
"""

from bee_code_interpreter_tpu.tenancy.context import (
    TENANT_HEADER,
    TENANT_METADATA_KEY,
    TenantContext,
    bearer_token,
    consume_retry_budget,
    current_tenant_context,
    current_tenant_label,
    meter_ambient_usage,
    tenant_scope,
)
from bee_code_interpreter_tpu.tenancy.leases import (
    QuotaLease,
    QuotaLeaseCache,
    QuotaLeaseClient,
)
from bee_code_interpreter_tpu.tenancy.metering import TenantUsageMeter
from bee_code_interpreter_tpu.tenancy.registry import (
    DEFAULT_TENANT_ID,
    Tenant,
    TenantRegistry,
    build_tenants_snapshot,
    parse_tenants,
    sanitize_tenant_id,
)

__all__ = [
    "DEFAULT_TENANT_ID",
    "TENANT_HEADER",
    "TENANT_METADATA_KEY",
    "QuotaLease",
    "QuotaLeaseCache",
    "QuotaLeaseClient",
    "Tenant",
    "TenantContext",
    "TenantRegistry",
    "TenantUsageMeter",
    "bearer_token",
    "build_tenants_snapshot",
    "consume_retry_budget",
    "current_tenant_context",
    "current_tenant_label",
    "meter_ambient_usage",
    "parse_tenants",
    "sanitize_tenant_id",
    "tenant_scope",
]
