"""The config-declared tenant table (docs/tenancy.md).

``APP_TENANTS`` declares who shares the service and on what terms, in the
same comma/colon spelling the SLO and policy knobs use::

    APP_TENANTS="alpha:weight=4:max_in_flight=8:rps=20,beta:weight=1:rps=5,
                 default:weight=1:rps=2"

Each entry is ``name[:key=value]...`` with keys:

- ``weight``        WFQ share under saturation (float > 0, default 1)
- ``max_in_flight`` per-tenant concurrency cap (default: unlimited — the
                    global admission bound still applies)
- ``rps``           token-bucket rate quota, requests/second (default: none)
- ``burst``         bucket depth (default ``max(1, rps)``)
- ``sessions``      per-tenant session-lease cap (default: none — the
                    global ``APP_SESSION_MAX`` still applies)
- ``key``           API key: ``Authorization: Bearer <key>`` resolves to
                    this tenant (the header is then unnecessary)

A ``default`` entry customizes the catch-all every unknown or anonymous
request lands in; when absent an unlimited ``default`` tenant is implied, so
an undeclared deployment behaves exactly as before tenancy existed.
Malformed specs raise ``ValueError`` at startup — config errors must fail
loudly, not silently disable isolation.

Unknown tenant ids are *bounded-cardinality*: they share the ``default``
tenant's quotas and lane, and at most ``max_labels`` distinct raw ids are
tracked as labels before collapsing into ``other`` (the metrics Registry's
label guard clamps the ``tenant`` label independently; see
``utils/metrics.py``).
"""

from __future__ import annotations

from dataclasses import dataclass

from bee_code_interpreter_tpu.tenancy.context import TenantContext
from bee_code_interpreter_tpu.tenancy.metering import TenantUsageMeter

DEFAULT_TENANT_ID = "default"

# Raw ids longer than this are truncated before becoming labels/attributes.
_MAX_ID_LEN = 64


@dataclass(frozen=True)
class Tenant:
    """One declared tenant and its quotas. ``None`` means "no per-tenant
    bound" — the global limits still apply."""

    id: str
    weight: float = 1.0
    max_in_flight: int | None = None
    rps: float | None = None
    burst: float | None = None
    max_sessions: int | None = None
    api_key: str | None = None

    @property
    def burst_depth(self) -> float:
        if self.burst is not None:
            return self.burst
        return max(1.0, self.rps) if self.rps is not None else 1.0


def _parse_entry(entry: str) -> Tenant:
    parts = [p.strip() for p in entry.split(":")]
    name = parts[0]
    if not name:
        raise ValueError(f"APP_TENANTS entry {entry!r}: empty tenant name")
    kwargs: dict = {}
    for part in parts[1:]:
        key, sep, value = part.partition("=")
        key, value = key.strip(), value.strip()
        if not sep or not value:
            raise ValueError(
                f"APP_TENANTS entry {entry!r}: expected key=value, got {part!r}"
            )
        try:
            if key == "weight":
                kwargs["weight"] = float(value)
                if kwargs["weight"] <= 0:
                    raise ValueError
            elif key == "max_in_flight":
                kwargs["max_in_flight"] = int(value)
                if kwargs["max_in_flight"] < 1:
                    raise ValueError
            elif key == "rps":
                kwargs["rps"] = float(value)
                if kwargs["rps"] <= 0:
                    raise ValueError
            elif key == "burst":
                kwargs["burst"] = float(value)
                if kwargs["burst"] < 1:
                    raise ValueError
            elif key == "sessions":
                kwargs["max_sessions"] = int(value)
                if kwargs["max_sessions"] < 0:
                    raise ValueError
            elif key == "key":
                kwargs["api_key"] = value
            else:
                raise ValueError(
                    f"APP_TENANTS entry {entry!r}: unknown attribute {key!r}"
                )
        except ValueError as e:
            if e.args and "APP_TENANTS" in str(e):
                raise
            raise ValueError(
                f"APP_TENANTS entry {entry!r}: bad value for {key}: {value!r}"
            ) from None
    return Tenant(id=name, **kwargs)


def parse_tenants(spec: str | None) -> dict[str, Tenant]:
    """Tenant table from the raw ``APP_TENANTS`` string. Always contains a
    ``default`` catch-all (implied unlimited when not declared)."""
    tenants: dict[str, Tenant] = {}
    seen_keys: dict[str, str] = {}
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        tenant = _parse_entry(entry)
        if tenant.id in tenants:
            raise ValueError(f"APP_TENANTS: duplicate tenant {tenant.id!r}")
        if tenant.api_key is not None:
            owner = seen_keys.get(tenant.api_key)
            if owner is not None:
                raise ValueError(
                    f"APP_TENANTS: API key of {tenant.id!r} already "
                    f"assigned to {owner!r}"
                )
            seen_keys[tenant.api_key] = tenant.id
        tenants[tenant.id] = tenant
    tenants.setdefault(DEFAULT_TENANT_ID, Tenant(id=DEFAULT_TENANT_ID))
    return tenants


def sanitize_tenant_id(raw: str) -> str:
    """A raw client-supplied id made safe for labels/span attributes:
    printable, no exposition-hostile characters, bounded length."""
    cleaned = "".join(
        ch if ch.isprintable() and ch not in '",\\\n' else "_" for ch in raw
    )
    return cleaned[:_MAX_ID_LEN]


class TenantRegistry:
    """Identity resolution + the per-tenant usage meter, shared by both API
    edges (one table, one meter — the transports can never disagree about
    who a request belongs to)."""

    def __init__(
        self,
        tenants: dict[str, Tenant] | None = None,
        *,
        max_labels: int = 32,
        metrics=None,
    ) -> None:
        self._tenants = dict(tenants) if tenants else parse_tenants(None)
        self._tenants.setdefault(DEFAULT_TENANT_ID, Tenant(id=DEFAULT_TENANT_ID))
        self._by_key = {
            t.api_key: t for t in self._tenants.values() if t.api_key
        }
        self._max_labels = max(1, max_labels)
        # Distinct unknown ids kept as labels before collapsing to "other";
        # bounded so a tenant-id flood cannot grow this map.
        self._unknown: set[str] = set()
        self.unknown_overflow = 0
        self.meter = TenantUsageMeter(metrics=metrics, max_labels=max_labels)

    @classmethod
    def from_config(cls, config, metrics=None) -> "TenantRegistry":
        return cls(
            parse_tenants(config.tenants),
            max_labels=config.metrics_max_tenant_labels,
            metrics=metrics,
        )

    @property
    def default(self) -> Tenant:
        return self._tenants[DEFAULT_TENANT_ID]

    def get(self, tenant_id: str) -> Tenant | None:
        return self._tenants.get(tenant_id)

    def tenants(self) -> tuple[Tenant, ...]:
        return tuple(self._tenants[name] for name in sorted(self._tenants))

    # ----------------------------------------------------------- resolution

    def resolve(
        self, tenant_id: str | None = None, api_key: str | None = None
    ) -> TenantContext:
        """One request's identity: API key wins over the header; a declared
        id gets its own tenant; anything else shares ``default`` (unknown
        ids keep a bounded-cardinality label for observability)."""
        if api_key is not None:
            tenant = self._by_key.get(api_key)
            if tenant is not None:
                return TenantContext(
                    tenant=tenant, label=tenant.id, raw_id=tenant.id,
                    meter=self.meter,
                )
        if tenant_id:
            tenant = self._tenants.get(tenant_id)
            if tenant is not None:
                return TenantContext(
                    tenant=tenant, label=tenant.id, raw_id=tenant_id,
                    meter=self.meter,
                )
            label = self._unknown_label(sanitize_tenant_id(tenant_id))
            return TenantContext(
                tenant=self.default,
                label=label,
                raw_id=sanitize_tenant_id(tenant_id),
                meter=self.meter,
            )
        return TenantContext(
            tenant=self.default,
            label=DEFAULT_TENANT_ID,
            raw_id=None,
            meter=self.meter,
        )

    def _unknown_label(self, cleaned: str) -> str:
        if cleaned in self._unknown:
            return cleaned
        if len(self._unknown) < self._max_labels:
            self._unknown.add(cleaned)
            return cleaned
        self.unknown_overflow += 1
        return "other"

    # ------------------------------------------------------------- readers

    def mix(self) -> dict[str, int]:
        """Per-tenant request totals for the ``/v1/fleet`` export."""
        return self.meter.mix()

    def snapshot(self) -> dict:
        return {
            "tenants": {
                t.id: {
                    "weight": t.weight,
                    "max_in_flight": t.max_in_flight,
                    "rps": t.rps,
                    "burst": t.burst_depth if t.rps is not None else None,
                    "sessions": t.max_sessions,
                    "has_api_key": t.api_key is not None,
                }
                for t in self.tenants()
            },
            "unknown_ids": len(self._unknown),
            "unknown_overflow": self.unknown_overflow,
        }


def build_tenants_snapshot(
    registry: TenantRegistry | None,
    admission=None,
    slo=None,
    sessions=None,
) -> dict:
    """The ``GET /v1/tenants`` document (gRPC ``GetTenants`` twin): the
    declared table, live admission state, usage metering, SLO-slice
    summaries, and session counts, merged per tenant label. Built in ONE
    place so the transports can never disagree about its shape."""
    if registry is None:
        return {"detail": "no tenant registry wired into this server"}
    table = registry.snapshot()
    usage = registry.meter.snapshot()
    admission_state = (
        admission.tenant_snapshot() if admission is not None else {}
    )
    slo_state = slo.tenant_summaries() if slo is not None else {}
    session_counts = (
        sessions.tenant_counts() if sessions is not None else {}
    )
    labels = (
        set(table["tenants"])
        | set(usage)
        | set(admission_state)
        | set(slo_state)
        | set(session_counts)
    )
    tenants = {}
    for label in sorted(labels):
        tenants[label] = {
            "config": table["tenants"].get(label),
            "admission": admission_state.get(label),
            "usage": usage.get(label),
            "slo": slo_state.get(label),
            "sessions": session_counts.get(label, 0),
        }
    return {
        "tenants": tenants,
        "unknown_ids": table["unknown_ids"],
        "unknown_overflow": table["unknown_overflow"],
    }
