#!/usr/bin/env bash
# Regenerate code_interpreter_pb2.py from the proto. The gRPC service layer is
# hand-written (api/grpc_server.py) because grpc_python_plugin is not available
# in this environment — only message codegen is needed.
set -euo pipefail
cd "$(dirname "$0")"
protoc --python_out=. code_interpreter.proto health.proto reflection.proto
